"""Pallas TPU kernel for the GF(2^8) bit-matrix matmul (alternative path).

Fuses unpack -> MXU int8 matmul -> parity mask -> pack inside VMEM, one
grid program per column tile, with the (small) bit-matrix resident in
VMEM (see /opt/skills/guides/pallas_guide.md for the kernel model).

Round-5 redesign (bit-major layout): the v1 kernel reshaped the unpacked
bits through int32 VMEM (Mosaic only supports minor-dim-inserting
reshapes on 32-bit types), inflating VMEM traffic 4x.  v2 permutes the
bit-matrix rows/columns to BIT-MAJOR order host-side (row' = b*r + j,
col' = b*k + i), so the in-kernel unpack is a plain concatenate of eight
(k, TN) bit slabs and the pack is eight shift-or folds — no reshapes at
all.

MEASURED VERDICT (v5e, ISA k=8,m=4 headline shape, round-5 HONEST
harness — on-device scan loop with slope timing, see BENCH_NOTES.md; the
round-3 numbers comparing 1,136 vs 167 GB/s were both artifacts of
`block_until_ready` not waiting for completion on the axon tunnel):

    XLA fused path        337-414 us / 16.7 MB step
    this kernel (v2)      307-309 us (TN >= 8192)
    v1 kernel (chunk-major, int32 reshapes)  490 us

The kernel wins ~25% on the pre-transposed (k, N) column layout, but the
end-to-end batch path needs the (B,k,S) <-> (k,N) transposes either way
(doing the transpose in-kernel measured 477 us — VMEM int32 transposes
lose to XLA's HBM transpose), which makes the full path a wash.  The
production engines therefore keep the XLA path; this kernel stays as the
validated, benchmarked alternative (bit-exact vs gf8.bitmatrix_matmul on
the real device) and the measurement record.  Both paths sit near two
simultaneous walls: HBM traffic of the materialized bit planes and the
MXU shape-padding floor (K=64, M=32 occupies 1/8 of the 128x128 array —
block-diagonal stacking measured no gain).  Going materially faster
requires bit-planar shard storage end-to-end (future work).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_TILE_N = 16384


@functools.lru_cache(maxsize=64)
def _bitmajor_perm(r8: int, k8: int):
    """Row/col permutations taking a chunk-major bit-matrix (row = j*8+b,
    col = i*8+b, from gf8.expand_bitmatrix) to bit-major order."""
    r, k = r8 // 8, k8 // 8
    rowp = [j * 8 + b for b in range(8) for j in range(r)]
    colp = [i * 8 + b for b in range(8) for i in range(k)]
    return np.asarray(rowp), np.asarray(colp)


def _kernel(bm_ref, d_ref, o_ref, *, k: int, r: int):
    tn = d_ref.shape[-1]
    d32 = d_ref[:].astype(jnp.int32)                      # (k, TN)
    # bit-major unpack: slab b holds bit b of every chunk row — no
    # reshape needed because the matrix columns were permuted to match
    bits = jnp.concatenate(
        [((d32 >> b) & 1).astype(jnp.int8) for b in range(8)], axis=0)
    acc = jax.lax.dot_general(
        bm_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                                      # (8r, TN)
    out = jnp.zeros((r, tn), jnp.int32)
    for b in range(8):
        out = out | ((acc[b * r:(b + 1) * r] & 1) << b)
    o_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _matmul_tiled(bitmat_bm, data, k: int, r: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = data.shape[1]
    grid = (n // _TILE_N,)
    return pl.pallas_call(
        functools.partial(_kernel, k=k, r=r),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((r * 8, k * 8), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((k, _TILE_N), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((r, _TILE_N), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        ),
    )(bitmat_bm, data)


def bitmatrix_matmul(bitmat, data):
    """Drop-in for gf8.bitmatrix_matmul on column counts that tile; the
    ragged tail (n % TILE) falls back to the XLA path and concatenates."""
    from ceph_tpu.ops import gf8

    data = jnp.asarray(data)
    rw, kw = bitmat.shape
    k, r = kw // 8, rw // 8
    rowp, colp = _bitmajor_perm(rw, kw)
    # permute with jnp indexing so device arrays and tracers work without
    # a host round-trip (the matrix is tiny; the gather is trace-safe)
    bm_bm = jnp.asarray(bitmat)[rowp][:, colp].astype(jnp.int8)
    n = data.shape[1]
    main = (n // _TILE_N) * _TILE_N
    parts = []
    if main:
        parts.append(_matmul_tiled(bm_bm, data[:, :main], k, r))
    if main < n:
        parts.append(gf8.bitmatrix_matmul(bitmat, data[:, main:]))
    return parts[0] if len(parts) == 1 else \
        jnp.concatenate(parts, axis=1)


# ---------------------------------------------------------------------------
# v3 (round 6): bit-planar kernel with block-diagonal K-stacking
# ---------------------------------------------------------------------------
#
# Consumes PACKED bit-planes (gf8.bytes_to_planar layout: chunk-major rows
# j*w+t, packed byte i holding source positions 8i..8i+7) and produces
# packed parity planes — the storage format the round-6 layout contract
# keeps stripe batches in end-to-end.  Two changes over v2 attack the two
# measured walls at once:
#
#   * HBM: the {0,1} 8x expansion never leaves VMEM.  Per grid step the
#     kernel reads a (kw, TILE_P) PACKED tile (payload bytes only) and
#     writes (rw, TILE_P) packed parity planes — the byte path's ~270 MB
#     of materialized planes per 16.7 MB step becomes ~25 MB.
#   * MXU: the coding bit-matrix is stacked block-diagonally g =
#     max(1, 128 // kw) times and the tile's packed columns are split
#     into g segments stacked along K, so the dot feeds a g*kw-wide K
#     (128 for the ISA k8m4 headline's kw=64 instead of 64) and g*rw
#     output rows per pass — 2x fewer MXU column passes for the same
#     bytes.  The stacking is a pure reindexing: results are bit-exact
#     with gf8.planar_matmul_xla.
#
# Unpack is 8 shift-and slabs concatenated along LANES (packed byte u-bit
# -> lane u*seg + i), pack is 8 shift-or lane folds — no reshapes, the
# Mosaic lesson from v2 carried over.

_TILE_P = 2048            # packed columns per grid step (= 16 KiB of
                          # source bytes per chunk row)


def stack_groups(kw: int) -> int:
    """Block-diagonal stacking factor: fill the MXU's 128-wide K.

    Rounded DOWN to a power of two so the stacking always divides the
    column tile evenly (kw=24 would otherwise yield g=5 and a ragged
    segment split)."""
    g = max(1, 128 // max(1, kw))
    while g & (g - 1):
        g &= g - 1
    return g


def _planar_kernel(bm_ref, p_ref, o_ref, *, g: int, rw: int):
    tp = p_ref.shape[-1]
    seg = tp // g
    d32 = p_ref[:].astype(jnp.int32)                       # (kw, TILE_P)
    slabs = []
    for h in range(g):
        dh = d32[:, h * seg:(h + 1) * seg]
        slabs.append(jnp.concatenate(
            [((dh >> u) & 1).astype(jnp.int8) for u in range(8)],
            axis=1))                                       # (kw, seg*8)
    op = slabs[0] if g == 1 else jnp.concatenate(slabs, axis=0)
    acc = jax.lax.dot_general(
        bm_ref[:], op,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                                      # (g*rw, seg*8)
    outs = []
    for h in range(g):
        a = acc[h * rw:(h + 1) * rw]
        packed = jnp.zeros((rw, seg), jnp.int32)
        for u in range(8):
            packed = packed | ((a[:, u * seg:(u + 1) * seg] & 1) << u)
        outs.append(packed)
    out = outs[0] if g == 1 else jnp.concatenate(outs, axis=1)
    o_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _planar_tiled(bitmat, planes, rw: int, kw: int, g: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # block-diagonal stack (tiny: (g*rw, g*kw) int8); built inside the jit
    # so the device constant is derived from the ARGUMENT bitmat — no jit
    # closure over a device array (the axon dispatch-poisoning rule)
    stacked = jnp.kron(jnp.eye(g, dtype=jnp.int8), bitmat.astype(jnp.int8))
    npk = planes.shape[1]
    grid = (npk // _TILE_P,)
    return pl.pallas_call(
        functools.partial(_planar_kernel, g=g, rw=rw),
        out_shape=jax.ShapeDtypeStruct((rw, npk), jnp.uint8),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((rw * g, kw * g), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((kw, _TILE_P), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((rw, _TILE_P), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        ),
    )(stacked, planes)


def planar_matmul(bitmat, planes):
    """Drop-in for gf8.planar_matmul_xla on TPU backends; the ragged tail
    (npk % TILE_P) falls back to the XLA planar path and concatenates."""
    from ceph_tpu.ops import gf8

    planes = jnp.asarray(planes)
    rw, kw = int(bitmat.shape[0]), int(bitmat.shape[1])
    g = stack_groups(kw)
    bm = jnp.asarray(bitmat)
    npk = planes.shape[1]
    main = (npk // _TILE_P) * _TILE_P
    parts = []
    if main:
        parts.append(_planar_tiled(bm, planes[:, :main], rw, kw, g))
    if main < npk:
        parts.append(gf8.planar_matmul_xla(bm, planes[:, main:]))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


@functools.lru_cache(maxsize=1)
def planar_available() -> bool:
    """Probe once: does the planar kernel compile+run on this backend?"""
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
        bm = jnp.asarray(np.eye(8, dtype=np.int8))
        p = jnp.zeros((8, _TILE_P), dtype=jnp.uint8)
        out = _planar_tiled(bm, p, 8, 8, stack_groups(8))
        jax.block_until_ready(out)
        return out.shape == (8, _TILE_P)
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """Probe once: does a tiny kernel compile+run on this backend?"""
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
        bm = jnp.asarray(np.eye(8, dtype=np.int8))
        d = jnp.zeros((1, _TILE_N), dtype=jnp.uint8)
        out = _matmul_tiled(bm, d, 1, 1)
        jax.block_until_ready(out)
        return out.shape == (1, _TILE_N)
    except Exception:
        return False
