"""Pallas TPU kernel for the GF(2^8) bit-matrix matmul (alternative path).

Fuses unpack -> MXU int8 matmul -> parity mask -> pack inside VMEM, one
grid program per column tile, with the (small) bit-matrix resident in
VMEM (see /opt/skills/guides/pallas_guide.md for the kernel model).

MEASURED VERDICT (v5e, ISA k=8,m=4 headline shape, round 3): the XLA
path sustains ~1,136 GB/s; this kernel reaches ~167 GB/s at tile 2048
and does NOT improve with larger tiles (130 GB/s at 8k-32k).  Root
cause: Mosaic only supports minor-dim-inserting reshapes on 32-bit
types, so the in-kernel unpack must widen the payload 4x through int32
VMEM before the int8 MXU feed, while XLA's fusion pipelines the bit
expansion straight into the matmul operand without that inflation.  The
production engines therefore keep the XLA path; this kernel stays as a
validated, benchmarked alternative (bit-exact vs gf8.bitmatrix_matmul
on the real device) and the measurement record for why hand-scheduling
loses to the compiler here — exactly the "profile, iterate" loop the
scaling playbook prescribes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_TILE_N = 2048


def _kernel(bitmat_ref, data_ref, out_ref, *, k: int, r: int):
    # stay in 32-bit for the shape manipulation (Mosaic only supports
    # minor-dim-inserting reshapes on 32-bit types), drop to int8 at the
    # MXU boundary
    tn = data_ref.shape[-1]
    data = data_ref[:].astype(jnp.int32)                   # (k, TN)
    shifts = jnp.arange(8, dtype=jnp.int32)
    bits = ((data[:, None, :] >> shifts[None, :, None]) & 1)
    bits = bits.reshape(k * 8, tn).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bitmat_ref[:].astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1                                                  # (r*8, TN)
    acc = acc.reshape(r, 8, tn)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    out_ref[:] = jnp.sum(acc * weights, axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _matmul_tiled(bitmat, data, k: int, r: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = data.shape[1]
    grid = (n // _TILE_N,)
    return pl.pallas_call(
        functools.partial(_kernel, k=k, r=r),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((r * 8, k * 8), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((k, _TILE_N), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((r, _TILE_N), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
        ),
    )(bitmat, data)


def bitmatrix_matmul(bitmat, data):
    """Drop-in for gf8.bitmatrix_matmul on column counts that tile; the
    ragged tail (n % TILE) falls back to the XLA path and concatenates."""
    from ceph_tpu.ops import gf8

    bitmat = jnp.asarray(bitmat)
    data = jnp.asarray(data)
    rw, kw = bitmat.shape
    k, r = kw // 8, rw // 8
    n = data.shape[1]
    main = (n // _TILE_N) * _TILE_N
    parts = []
    if main:
        parts.append(_matmul_tiled(bitmat, data[:, :main], k, r))
    if main < n:
        parts.append(gf8.bitmatrix_matmul(bitmat, data[:, main:]))
    return parts[0] if len(parts) == 1 else \
        jnp.concatenate(parts, axis=1)


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """Probe once: does a tiny kernel compile+run on this backend?"""
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
        bm = jnp.asarray(np.eye(8, dtype=np.uint8))
        d = jnp.zeros((1, _TILE_N), dtype=jnp.uint8)
        out = _matmul_tiled(bm, d, 1, 1)
        jax.block_until_ready(out)
        return out.shape == (1, _TILE_N)
    except Exception:
        return False
