"""OSDMap: the versioned cluster map and its placement pipeline.

Behavioral mirror of reference src/osd/OSDMap.{h,cc} and pg_pool_t
(src/osd/osd_types.cc:1395-1423): pg -> pps seeding (stable_mod +
rjenkins1), CRUSH raw placement (_pg_to_raw_osds, OSDMap.cc:1861),
pg_upmap/pg_upmap_items overrides (:1891-1934), up-set filtering (:1937),
primary affinity (:1962+), pg_temp/primary_temp (:2010), and the full
_pg_to_up_acting_osds chain (:2079).

Two execution paths share the same semantics:
- per-PG scalar (ScalarMapper) — the oracle and control-plane path;
- whole-pool batched (TensorMapper) — every PG of a pool in one TPU
  dispatch, with the sparse host-side post-passes vectorized in numpy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.crush import CrushMap, ScalarMapper
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops import jenkins

CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000

POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """reference src/include/ceph_hash.h ceph_stable_mod."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _calc_mask(n: int) -> int:
    return (1 << max(n - 1, 1).bit_length()) - 1


@dataclass(frozen=True, order=True)
class PGid:
    pool: int
    seed: int

    def __str__(self):
        return f"{self.pool}.{self.seed:x}"


@dataclass
class PGPool:
    """pg_pool_t subset (reference src/osd/osd_types.h)."""

    pool_id: int
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 32
    crush_rule: int = 0
    hashpspool: bool = True
    ec_profile: Dict[str, str] = field(default_factory=dict)
    name: str = ""
    # snapshot state (reference pg_pool_t snap fields): snap_seq is the
    # pool-wide snap id allocator; snaps maps POOL snap ids to names
    # (selfmanaged snaps draw ids from the same allocator but are tracked
    # by the client, e.g. RBD); removed_snaps drive OSD snap trimming
    snap_seq: int = 0
    snaps: Dict[int, str] = field(default_factory=dict)
    removed_snaps: Tuple[int, ...] = ()
    # cache tiering (reference pg_pool_t tier fields, osd_types.h:1323-28
    # + cache_mode_t :1235): ``tiers`` lists cache pools over this base;
    # ``tier_of`` points a cache pool at its base; read/write_tier are
    # the objecter overlay redirect targets on the BASE pool
    tiers: Tuple[int, ...] = ()
    tier_of: int = -1
    read_tier: int = -1
    write_tier: int = -1
    cache_mode: str = "none"   # none|writeback|readproxy|forward
    hit_set_count: int = 4
    hit_set_period: float = 30.0
    hit_set_fpp: float = 0.05
    target_max_objects: int = 0   # agent evict trigger (0 = unbounded)
    cache_target_dirty_ratio: float = 0.4

    @property
    def pg_num_mask(self) -> int:
        return _calc_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _calc_mask(self.pgp_num)

    def snap_context(self) -> Tuple[int, Tuple[int, ...]]:
        """(seq, existent POOL snaps descending) — the SnapContext writes
        carry by default on a pool-snapshotted pool."""
        return (self.snap_seq,
                tuple(sorted(self.snaps.keys(), reverse=True)))

    def can_shift_osds(self) -> bool:
        return self.type == POOL_TYPE_REPLICATED

    def is_tier(self) -> bool:
        return self.tier_of >= 0

    def has_read_tier(self) -> bool:
        return self.read_tier >= 0

    def has_write_tier(self) -> bool:
        return self.write_tier >= 0

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def raw_pg_to_pg(self, seed: int) -> int:
        return ceph_stable_mod(seed, self.pg_num, self.pg_num_mask)

    def raw_pg_to_pps(self, seed: int) -> int:
        if self.hashpspool:
            return int(jenkins.hash2(
                ceph_stable_mod(seed, self.pgp_num, self.pgp_num_mask),
                self.pool_id))
        return ceph_stable_mod(seed, self.pgp_num, self.pgp_num_mask) \
            + self.pool_id

    def raw_pg_to_pps_batch(self, seeds: np.ndarray) -> np.ndarray:
        mask = np.uint32(self.pgp_num_mask)
        half = mask >> np.uint32(1)
        m = seeds.astype(np.uint32) & mask
        stable = np.where(m < self.pgp_num, m, seeds.astype(np.uint32) & half)
        if self.hashpspool:
            return jenkins.hash2(
                stable.astype(np.uint64),
                np.uint64(self.pool_id)).astype(np.uint32)
        return stable + np.uint32(self.pool_id)


@dataclass
class Incremental:
    """Map delta producing epoch ``epoch`` from ``epoch - 1`` (reference
    OSDMap::Incremental, src/osd/OSDMap.h): the mon ships these instead of
    re-serializing the world on every change; consumers apply them in
    order."""

    epoch: int
    new_up: Dict[int, object] = field(default_factory=dict)  # osd -> addr
    new_down: List[int] = field(default_factory=list)
    new_weights: Dict[int, int] = field(default_factory=dict)
    new_pools: Dict[int, "PGPool"] = field(default_factory=dict)
    new_rules: List[object] = field(default_factory=list)  # appended in order
    new_pg_temp: Dict["PGid", List[int]] = field(default_factory=dict)
    # balancer-committed explicit remap pairs (reference
    # OSDMap::Incremental new_pg_upmap_items): pg -> [(from, to), ...];
    # an EMPTY list clears the pg's entry (like new_pg_temp)
    new_pg_upmap_items: Dict["PGid", List[Tuple[int, int]]] = \
        field(default_factory=dict)
    new_primary_temp: Dict["PGid", int] = field(default_factory=dict)
    new_primary_affinity: Dict[int, int] = field(default_factory=dict)
    new_mgr_addr: object = None  # mgr registration (reference MgrMap)
    new_mds_addr: object = None  # active rank-0 MDS (MDSMap-lite)
    new_mds_addrs: Dict[int, object] = field(default_factory=dict)
    new_revoked: Tuple[str, ...] = ()  # cephx entities to revoke
    old_pools: Tuple[int, ...] = ()    # pool deletions
    # cluster flag transitions (round 16, reference CEPH_OSDMAP_FULL /
    # NEARFULL / BACKFILLFULL): flag name -> set (True) / clear (False).
    # The mon's full-ratio tick commits these from beacon statfs; OSDs
    # enforce them (ENOSPC on client writes under "full", backfill
    # deferred under "backfillfull").
    new_flags: Dict[str, bool] = field(default_factory=dict)
    # cluster-log events riding the same Paxos stream (the reference's
    # LogMonitor is likewise a PaxosService on the shared paxos); the
    # OSDMap itself ignores them — the mon's log service consumes them
    new_log_entries: Tuple = ()        # of (who, stamp, prio, msg)
    # elastic reshape (round 21, reference OSDMap::Incremental
    # new_max_osd + full-crush replacement): grow extends the id space
    # and ships the new device-bearing host buckets; purge retires ids.
    # The crush delta rides as data, not a pickled CrushMap — every
    # consumer applies the same mutation to ITS crush copy.
    new_max_osd: int = 0               # 0 = unchanged
    # of (host_name, (osd ids...), (16.16 weights...), root_name)
    new_crush_hosts: Tuple = ()
    old_osds: Tuple[int, ...] = ()     # purged ids (exists -> False)


class OSDMap:
    def __init__(self, crush: CrushMap, max_osd: int = 0):
        self.epoch = 1
        self.crush = crush
        self.max_osd = max_osd or crush.max_devices
        self.osd_exists = [True] * self.max_osd
        self.osd_up = [True] * self.max_osd
        self.osd_weight = [0x10000] * self.max_osd  # in/out weight
        self.mgr_addr = None  # active mgr (reference MgrMap active addr)
        self.mds_addr = None  # active rank-0 MDS (MDSMap-lite, beacons)
        # multi-active MDS ranks (reference MDSMap mds_info): rank -> addr
        self.mds_addrs = {}
        # cephx entities refused ticket issuance (replicated through
        # Paxos like every map mutation, so revocation survives mon
        # failover AND restarts via the persisted map)
        self.revoked_entities: set = set()
        # cluster flags (round 16): "nearfull" | "backfillfull" |
        # "full", committed by the mon's full-ratio tick and enforced
        # by every OSD from its own map copy
        self.flags: set = set()
        self.osd_primary_affinity: Optional[List[int]] = None
        self.pools: Dict[int, PGPool] = {}
        self.pg_upmap: Dict[PGid, List[int]] = {}
        self.pg_upmap_items: Dict[PGid, List[Tuple[int, int]]] = {}
        self.pg_temp: Dict[PGid, List[int]] = {}
        self.primary_temp: Dict[PGid, int] = {}
        self._scalar = ScalarMapper(crush)
        self._tensor = None
        self.osd_addrs: Dict[int, object] = {}

    def invalidate_mappers(self) -> None:
        """Call after mutating the CRUSH map (rules/buckets)."""
        self._scalar = ScalarMapper(self.crush)
        self._tensor = None

    # pickling: mappers hold device arrays; rebuild lazily on the far side
    def __getstate__(self):
        d = dict(self.__dict__)
        d["_scalar"] = None
        d["_tensor"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.__dict__.setdefault("flags", set())
        self._scalar = ScalarMapper(self.crush)
        self._tensor = None

    # -- state helpers -----------------------------------------------------

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and self.osd_exists[osd]

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_up[osd]

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_out(self, osd: int) -> bool:
        return not self.exists(osd) or self.osd_weight[osd] == 0

    def mark_down(self, osd: int) -> None:
        self.osd_up[osd] = False
        self.epoch += 1

    def mark_up(self, osd: int) -> None:
        self.osd_up[osd] = True
        self.epoch += 1

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0
        self.epoch += 1

    def mark_in(self, osd: int, weight: int = 0x10000) -> None:
        self.osd_weight[osd] = weight
        self.epoch += 1

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = \
                [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * self.max_osd
        self.osd_primary_affinity[osd] = aff
        self.epoch += 1

    def add_pool(self, pool: PGPool) -> None:
        self.pools[pool.pool_id] = pool
        self.epoch += 1

    def apply_incremental(self, inc: Incremental) -> None:
        """Advance this map by one epoch delta (reference
        OSDMap::apply_incremental, src/osd/OSDMap.cc)."""
        if inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental {inc.epoch} does not follow epoch {self.epoch}")
        # id-space growth FIRST: later fields of the same inc may
        # reference the new ids (a grow inc carries crush hosts whose
        # devices sit past the old max_osd)
        new_max = getattr(inc, "new_max_osd", 0)
        if new_max > self.max_osd:
            grown = new_max - self.max_osd
            self.osd_exists.extend([True] * grown)
            # new ids boot "down" until they report in (the vstart rule)
            self.osd_up.extend([False] * grown)
            self.osd_weight.extend([0x10000] * grown)
            if self.osd_primary_affinity is not None:
                self.osd_primary_affinity.extend(
                    [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * grown)
            self.max_osd = new_max
        crush_dirty = False
        for host in getattr(inc, "new_crush_hosts", ()):
            hname, devs, weights, root = host
            self.crush.add_host(hname, list(devs), list(weights),
                                root=root)
            crush_dirty = True
        for osd in getattr(inc, "old_osds", ()):
            if 0 <= osd < self.max_osd:
                self.osd_exists[osd] = False
                self.osd_up[osd] = False
                self.osd_weight[osd] = 0
                self.osd_addrs.pop(osd, None)
                if self.crush.remove_device(osd):
                    crush_dirty = True
                # explicit mappings naming a retired id die with it
                # (reference OSDMap::maybe_remove_pg_upmaps)
                for pg in [p for p, v in self.pg_upmap.items()
                           if osd in v]:
                    del self.pg_upmap[pg]
                for pg in [p for p, v in self.pg_upmap_items.items()
                           if any(osd in pair for pair in v)]:
                    del self.pg_upmap_items[pg]
                for pg in [p for p, v in self.pg_temp.items()
                           if osd in v]:
                    del self.pg_temp[pg]
                for pg in [p for p, v in self.primary_temp.items()
                           if v == osd]:
                    del self.primary_temp[pg]
        if crush_dirty:
            self.invalidate_mappers()
        for osd, addr in inc.new_up.items():
            if 0 <= osd < self.max_osd:
                self.osd_up[osd] = True
                if addr is not None:
                    self.osd_addrs[osd] = tuple(addr)
        for osd in inc.new_down:
            if 0 <= osd < self.max_osd:
                self.osd_up[osd] = False
        for osd, w in inc.new_weights.items():
            if 0 <= osd < self.max_osd:
                self.osd_weight[osd] = w
        for osd, aff in inc.new_primary_affinity.items():
            self.set_primary_affinity(osd, aff)
        if inc.new_mgr_addr is not None:
            self.mgr_addr = tuple(inc.new_mgr_addr)
        if inc.new_mds_addr is not None:
            self.mds_addr = tuple(inc.new_mds_addr)
            self.mds_addrs[0] = tuple(inc.new_mds_addr)
        for r, a in getattr(inc, "new_mds_addrs", {}).items():
            self.mds_addrs[r] = tuple(a)
            if r == 0:
                self.mds_addr = tuple(a)
        if inc.new_revoked:
            self.revoked_entities |= set(inc.new_revoked)
        for flag, on in getattr(inc, "new_flags", {}).items():
            if on:
                self.flags.add(flag)
            else:
                self.flags.discard(flag)
        for pg, temp in inc.new_pg_temp.items():
            if temp:
                self.pg_temp[pg] = list(temp)
            else:
                self.pg_temp.pop(pg, None)
        for pg, pairs in getattr(inc, "new_pg_upmap_items", {}).items():
            if pairs:
                self.pg_upmap_items[pg] = [tuple(p) for p in pairs]
            else:
                self.pg_upmap_items.pop(pg, None)
        for pg, tp in inc.new_primary_temp.items():
            if tp >= 0:
                self.primary_temp[pg] = tp
            else:
                self.primary_temp.pop(pg, None)
        if inc.new_rules:
            for rule in inc.new_rules:
                self.crush.add_rule(rule)
            self.invalidate_mappers()
        for pool_id, pool in inc.new_pools.items():
            self.pools[pool_id] = pool
        for pool_id in inc.old_pools:
            self.pools.pop(pool_id, None)
            for pg in [p for p in self.pg_upmap if p.pool == pool_id]:
                del self.pg_upmap[pg]
            for pg in [p for p in self.pg_upmap_items
                       if p.pool == pool_id]:
                del self.pg_upmap_items[pg]
            for pg in [p for p in self.pg_temp if p.pool == pool_id]:
                del self.pg_temp[pg]
            for pg in [p for p in self.primary_temp
                       if p.pool == pool_id]:
                del self.primary_temp[pg]
        self.epoch = inc.epoch

    @property
    def tensor_mapper(self):
        if self._tensor is None:
            from ceph_tpu.crush.mapper import TensorMapper

            try:
                self._tensor = TensorMapper(self.crush)
            except (NotImplementedError, AssertionError) as e:
                # cache the rejection so every pool_mapping call does not
                # retry construction against an unsupported map
                self._tensor = e
        if isinstance(self._tensor, Exception):
            raise self._tensor
        return self._tensor

    # -- placement pipeline (scalar) ---------------------------------------

    def _pg_to_raw_osds(self, pool: PGPool, pgid: PGid) -> Tuple[List[int], int]:
        pps = pool.raw_pg_to_pps(pgid.seed)
        raw = self._scalar.do_rule(pool.crush_rule, pps, pool.size,
                                   self.osd_weight)
        raw = self._remove_nonexistent(pool, raw)
        return raw, pps

    def _remove_nonexistent(self, pool: PGPool, raw: List[int]) -> List[int]:
        if pool.can_shift_osds():
            return [o for o in raw if o == CRUSH_ITEM_NONE or self.exists(o)]
        return [o if o == CRUSH_ITEM_NONE or self.exists(o) else
                CRUSH_ITEM_NONE for o in raw]

    def _apply_upmap(self, pool: PGPool, pgid: PGid, raw: List[int]) -> List[int]:
        pg = PGid(pgid.pool, pool.raw_pg_to_pg(pgid.seed))
        um = self.pg_upmap.get(pg)
        if um is not None:
            if any(o != CRUSH_ITEM_NONE and 0 <= o < self.max_osd
                   and self.osd_weight[o] == 0 for o in um):
                # a target is marked out: reject the explicit mapping and,
                # like the reference (OSDMap.cc:1899), skip pg_upmap_items too
                return raw
            raw = list(um)
        for src, dst in self.pg_upmap_items.get(pg, []):
            exists_already = False
            pos = -1
            for i, o in enumerate(raw):
                if o == dst:
                    exists_already = True
                    break
                if o == src and pos < 0 and not (
                        dst != CRUSH_ITEM_NONE and 0 <= dst < self.max_osd
                        and self.osd_weight[dst] == 0):
                    pos = i
            if not exists_already and pos >= 0:
                raw[pos] = dst
        return raw

    def _raw_to_up(self, pool: PGPool, raw: List[int]) -> List[int]:
        if pool.can_shift_osds():
            return [o for o in raw
                    if o != CRUSH_ITEM_NONE and not self.is_down(o)]
        return [CRUSH_ITEM_NONE if o == CRUSH_ITEM_NONE or self.is_down(o)
                else o for o in raw]

    @staticmethod
    def _pick_primary(osds: List[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, pps: int, pool: PGPool,
                                osds: List[int], primary: int) -> Tuple[List[int], int]:
        aff = self.osd_primary_affinity
        if aff is None:
            return osds, primary
        if not any(o != CRUSH_ITEM_NONE
                   and aff[o] != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
                   for o in osds):
            return osds, primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if a < CEPH_OSD_MAX_PRIMARY_AFFINITY and \
                    (int(jenkins.hash2(pps, o)) >> 16) >= a:
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return osds, primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            osds = [osds[pos]] + osds[:pos] + osds[pos + 1 :]
        return osds, primary

    def _get_temp_osds(self, pool: PGPool, pgid: PGid) -> Tuple[List[int], int]:
        pg = PGid(pgid.pool, pool.raw_pg_to_pg(pgid.seed))
        temp = []
        for o in self.pg_temp.get(pg, []):
            if not self.exists(o) or self.is_down(o):
                if pool.can_shift_osds():
                    continue
                temp.append(CRUSH_ITEM_NONE)
            else:
                temp.append(o)
        tp = self.primary_temp.get(pg, -1)
        if tp == -1 and temp:
            tp = self._pick_primary(temp)
        return temp, tp

    def pg_to_up_acting_osds(self, pgid: PGid):
        """Returns (up, up_primary, acting, acting_primary) — reference
        _pg_to_up_acting_osds (OSDMap.cc:2079)."""
        pool = self.pools.get(pgid.pool)
        if pool is None or pgid.seed >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pgid)
        raw, pps = self._pg_to_raw_osds(pool, pgid)
        raw = self._apply_upmap(pool, pgid, raw)
        up = self._raw_to_up(pool, raw)
        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(pps, pool, up, up_primary)
        if not acting:
            acting = up
            # the up_primary fallback happens only inside the empty-acting
            # branch, so a standalone primary_temp (no pg_temp) survives and
            # an all-down pg_temp keeps acting_primary == -1 (reference
            # _pg_to_up_acting_osds, OSDMap.cc:2110-2116)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def pg_raw_up(self, pgid: PGid) -> List[int]:
        """Down-BLIND placement: raw CRUSH + upmap, existence-filtered
        but never up-filtered.  This is "where the map says the data
        belongs" — the mon's pg_temp mint reasons about data location
        across epochs, and an OSD's transient down-ness (a beacon blip)
        must not read as the data having moved."""
        pool = self.pools.get(pgid.pool)
        if pool is None or pgid.seed >= pool.pg_num:
            return []
        raw, _ = self._pg_to_raw_osds(pool, pgid)
        return self._apply_upmap(pool, pgid, raw)

    # -- whole-pool batched placement --------------------------------------

    def _pool_mapping_row(self, pool: PGPool, pool_id: int, seed: int,
                          pps_s: int, raw: List[int]):
        """One seed's host post-pass: the scalar chain after the raw
        CRUSH placement (nonexistent removal, upmap, up filtering,
        primary affinity)."""
        raw = self._remove_nonexistent(pool, raw)
        pgid = PGid(pool_id, seed)
        raw = self._apply_upmap(pool, pgid, raw)
        u = self._raw_to_up(pool, raw)
        p = self._pick_primary(u)
        return self._apply_primary_affinity(pps_s, pool, u, p)

    def pool_mapping(self, pool_id: int):
        """Map every PG of a pool in one batched TPU dispatch.

        Returns (up (pg_num, size) int64 with CRUSH_ITEM_NONE holes/padding,
        up_primary (pg_num,) int64).  The host post-passes (nonexistent
        removal, up filtering, primary pick) run VECTORIZED in numpy —
        zero per-PG Python on the common path (round 14); sparse
        overrides (upmap entries, non-default primary affinity) re-run
        the scalar chain for just the affected seeds.  Semantics match
        the per-PG scalar pipeline exactly (cross-checked in tests).
        """
        pool = self.pools[pool_id]
        seeds = np.arange(pool.pg_num, dtype=np.uint32)
        pps = pool.raw_pg_to_pps_batch(seeds)
        try:
            mapper = self.tensor_mapper
        except (NotImplementedError, AssertionError) as e:
            # map shape the vectorized mapper rejects (legacy tunables,
            # non-straw2 buckets, sparse bucket ids): scalar fallback with
            # identical semantics.  SURFACED, never silent: a 1M-PG map
            # quietly dropping to a Python loop would look like a device
            # perf bug (round-3 verdict weakness #5)
            self.scalar_fallbacks = getattr(self, "scalar_fallbacks", 0) + 1
            import logging

            logging.getLogger("ceph_tpu.osdmap").warning(
                "pool %d placement FELL BACK to the scalar mapper "
                "(%s); batched device placement disabled for this map",
                pool_id, e)
            res_l, rlen_l = [], []
            for s in range(pool.pg_num):
                raw = self._scalar.do_rule(pool.crush_rule, int(pps[s]),
                                           pool.size, self.osd_weight)
                res_l.append(raw + [0] * (pool.size - len(raw)))
                rlen_l.append(len(raw))
            res = np.asarray(res_l, dtype=np.int64).reshape(
                pool.pg_num, pool.size)
            rlen = np.asarray(rlen_l, dtype=np.int64)
        else:
            weights = np.zeros(self.crush.max_devices, dtype=np.uint32)
            weights[: self.max_osd] = self.osd_weight
            res, rlen = mapper.do_rule_batch(
                pool.crush_rule, pps, pool.size, weights)
            res = np.asarray(res)
            rlen = np.asarray(rlen)
        size = pool.size
        aff = self.osd_primary_affinity
        if aff is not None and any(
                a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY for a in aff):
            # non-default primary affinity reorders/re-picks primaries
            # per (pps, osd) hash: keep the per-seed scalar post-pass
            # for the whole pool (affinity maps are rare and sparse)
            up = np.full((pool.pg_num, size), CRUSH_ITEM_NONE,
                         dtype=np.int64)
            upp = np.full(pool.pg_num, -1, dtype=np.int64)
            for s in range(pool.pg_num):
                u, p = self._pool_mapping_row(
                    pool, pool_id, int(s), int(pps[s]),
                    [int(v) for v in res[s, : rlen[s]]])
                up[s, : len(u)] = u
                upp[s] = p
            return up, upp
        # vectorized post-pass: exists/up masking and first-non-NONE
        # primary pick as whole-pool array ops
        res64 = np.asarray(res, dtype=np.int64)[:, :size]
        rlen64 = np.asarray(rlen, dtype=np.int64)
        cols = np.arange(size, dtype=np.int64)
        raw = np.where(cols[None, :] < rlen64[:, None], res64,
                       CRUSH_ITEM_NONE)
        valid = (raw != CRUSH_ITEM_NONE) & (raw >= 0) & \
            (raw < self.max_osd)
        alive = np.asarray(self.osd_exists, dtype=bool) & \
            np.asarray(self.osd_up, dtype=bool)
        keep = valid & alive[np.where(valid, raw, 0)]
        if pool.can_shift_osds():
            # replicated: dead/nonexistent entries compact out,
            # preserving the order of the survivors (stable sort on the
            # drop mask == the scalar chain's filtered list)
            order = np.argsort(~keep, axis=1, kind="stable")
            vals = np.take_along_axis(raw, order, axis=1)
            kept = np.take_along_axis(keep, order, axis=1)
            up = np.where(kept, vals, CRUSH_ITEM_NONE)
        else:
            # erasure: positions are shard slots — dead entries become
            # NONE holes in place
            up = np.where(keep, raw, CRUSH_ITEM_NONE)
        has = up != CRUSH_ITEM_NONE
        first = has.argmax(axis=1)
        upp = np.where(has.any(axis=1),
                       up[np.arange(pool.pg_num), first],
                       -1).astype(np.int64)
        # sparse upmap overrides re-run the scalar chain per seed (the
        # folded pg id of seed s < pg_num is s itself)
        special = {pg.seed for pg in self.pg_upmap
                   if pg.pool == pool_id and pg.seed < pool.pg_num}
        special |= {pg.seed for pg in self.pg_upmap_items
                    if pg.pool == pool_id and pg.seed < pool.pg_num}
        for s in sorted(special):
            u, p = self._pool_mapping_row(
                pool, pool_id, s, int(pps[s]),
                [int(v) for v in res[s, : rlen[s]]])
            row = np.full(size, CRUSH_ITEM_NONE, dtype=np.int64)
            row[: len(u)] = u
            up[s] = row
            upp[s] = p
        return up, upp

    def rebalance_diff(self, pool_id: int, other: "OSDMap"):
        """Changed-PG set between two maps (the BASELINE rebalance metric)."""
        a, ap = self.pool_mapping(pool_id)
        b, bp = other.pool_mapping(pool_id)
        moved = np.nonzero((a != b).any(axis=1))[0]
        return moved, len(moved) / max(a.shape[0], 1)


# -- vectorized epoch deltas (round 14) -------------------------------------
#
# "Which PGs did this epoch change?" as whole-pool array diffs instead of a
# per-PG Python rescan: an OSD snapshots each pool's resolved placement
# after every map advance and diffs the arrays on the next one, so epoch
# application peers only PGs whose up/acting actually moved.  The per-PG
# scan (affected_pgs_scalar) stays as the bit-exactness anchor.


@dataclass
class PoolPlacement:
    """One pool's resolved placement at an epoch — the diffable unit."""

    pool_id: int
    pg_num: int
    size: int
    shift: bool                       # pool.can_shift_osds()
    mode: str                         # "batched" | "scalar"
    up: Optional[np.ndarray] = None   # (pg_num, size), batched mode
    upp: Optional[np.ndarray] = None  # (pg_num,), batched mode
    # per-seed (up, up_primary, acting, acting_primary) normalized
    # tuples: EVERY seed in scalar mode; only pg_temp/primary_temp
    # overridden seeds in batched mode (acting != up only there)
    resolved: Dict[int, Tuple] = field(default_factory=dict)

    def resolve(self, seed: int) -> Tuple:
        got = self.resolved.get(seed)
        if got is not None:
            return got
        row = self.up[seed]
        if self.shift:
            u = tuple(int(o) for o in row if o != CRUSH_ITEM_NONE)
        else:
            u = tuple(int(o) for o in row)
        p = int(self.upp[seed])
        return (u, p, u, p)


def _norm_placement(size: int, shift: bool, up, upp, acting, actp) -> Tuple:
    """Normalize a pg_to_up_acting_osds 4-tuple so scalar- and
    array-derived resolutions compare equal: replicated sets drop NONE
    holes, erasure sets pad to the pool size (trailing padding is not a
    placement change)."""
    if shift:
        u = tuple(o for o in up if o != CRUSH_ITEM_NONE)
        a = tuple(o for o in acting if o != CRUSH_ITEM_NONE)
    else:
        u = tuple(up) + (CRUSH_ITEM_NONE,) * (size - len(up))
        a = tuple(acting) + (CRUSH_ITEM_NONE,) * (size - len(acting))
    return (u, upp, a, actp)


def placement_snapshot(m: OSDMap, pool_id: int,
                       batch_min: int = 0) -> PoolPlacement:
    """Resolve a pool's full placement: one batched dispatch + sparse
    temp-override scalar re-runs (pools below ``batch_min`` PGs stay on
    the scalar chain — a device dispatch costs more than it saves)."""
    pool = m.pools[pool_id]
    shift = pool.can_shift_osds()
    if pool.pg_num < batch_min:
        snap = PoolPlacement(pool_id, pool.pg_num, pool.size, shift,
                             "scalar")
        for seed in range(pool.pg_num):
            snap.resolved[seed] = _norm_placement(
                pool.size, shift,
                *m.pg_to_up_acting_osds(PGid(pool_id, seed)))
        return snap
    up, upp = m.pool_mapping(pool_id)
    snap = PoolPlacement(pool_id, pool.pg_num, pool.size, shift,
                         "batched", up=up, upp=upp)
    temp = {pg.seed for pg in m.pg_temp
            if pg.pool == pool_id and pg.seed < pool.pg_num}
    temp |= {pg.seed for pg in m.primary_temp
             if pg.pool == pool_id and pg.seed < pool.pg_num}
    for seed in sorted(temp):
        snap.resolved[seed] = _norm_placement(
            pool.size, shift,
            *m.pg_to_up_acting_osds(PGid(pool_id, seed)))
    return snap


def placement_delta(old: Optional[PoolPlacement],
                    new: PoolPlacement) -> Optional[set]:
    """Seeds whose (up, up_primary, acting, acting_primary) changed
    between two snapshots.  ``None`` = treat everything as changed (no
    old snapshot, or an incomparable shape change)."""
    if old is None or old.size != new.size or old.shift != new.shift:
        return None
    if old.pg_num > new.pg_num:
        return None  # shrink is unsupported upstream; stay safe
    changed: set = set(range(old.pg_num, new.pg_num))  # pg_num growth
    overlap = old.pg_num
    if old.mode == "batched" and new.mode == "batched":
        diff = np.nonzero(
            (old.up[:overlap] != new.up[:overlap]).any(axis=1)
            | (old.upp[:overlap] != new.upp[:overlap]))[0]
        changed.update(int(s) for s in diff)
        # temp-overridden seeds (either side) decide by the resolved
        # 4-tuple: the raw arrays ignore pg_temp/primary_temp
        for s in set(old.resolved) | set(new.resolved):
            if s >= overlap:
                continue
            if old.resolve(s) != new.resolve(s):
                changed.add(s)
            else:
                changed.discard(s)
        return changed
    # scalar snapshots (small pools, or a pool that crossed the batch
    # threshold): per-seed tuple compare over the overlap
    for s in range(overlap):
        if old.resolve(s) != new.resolve(s):
            changed.add(s)
    return changed


def affected_pgs(old: OSDMap, new: OSDMap, pool_id: int,
                 batch_min: int = 0) -> set:
    """Vectorized epoch delta: the set of seeds in ``pool_id`` whose
    placement changed from ``old`` to ``new`` — whole-pool batched
    placements diffed as arrays, sparse overrides re-checked scalar.
    Bit-identical to :func:`affected_pgs_scalar` (tier-1 gate)."""
    have_old = pool_id in old.pools
    have_new = pool_id in new.pools
    if not have_new:
        return set(range(old.pools[pool_id].pg_num)) if have_old else set()
    if not have_old:
        return set(range(new.pools[pool_id].pg_num))
    delta = placement_delta(placement_snapshot(old, pool_id, batch_min),
                            placement_snapshot(new, pool_id, batch_min))
    if delta is None:
        return set(range(new.pools[pool_id].pg_num))
    return delta


def affected_pgs_scalar(old: OSDMap, new: OSDMap, pool_id: int) -> set:
    """The per-PG-scan anchor: compare the full scalar placement chain
    seed by seed.  O(pg_num) Python per epoch — exactly the cost the
    vectorized path exists to avoid; kept as the bit-exactness oracle."""
    have_old = pool_id in old.pools
    have_new = pool_id in new.pools
    if not have_new:
        return set(range(old.pools[pool_id].pg_num)) if have_old else set()
    if not have_old:
        return set(range(new.pools[pool_id].pg_num))
    pool = new.pools[pool_id]
    if old.pools[pool_id].size != pool.size:
        return set(range(pool.pg_num))  # width change: everything re-peers
    changed = set()
    for seed in range(pool.pg_num):
        pgid = PGid(pool_id, seed)
        a = _norm_placement(pool.size, pool.can_shift_osds(),
                            *old.pg_to_up_acting_osds(pgid))
        b = _norm_placement(pool.size, pool.can_shift_osds(),
                            *new.pg_to_up_acting_osds(pgid))
        if a != b:
            changed.add(seed)
    return changed


def build_simple_osdmap(n_osds: int = 16, osds_per_host: int = 4,
                        pg_num: int = 64, pool_type: int = POOL_TYPE_REPLICATED,
                        size: int = 3, ec_profile: Optional[Dict] = None):
    """Dev helper: hierarchy + one pool (the vstart analog)."""
    from ceph_tpu.crush.types import build_hierarchy

    cmap, ruleno = build_hierarchy(
        n_hosts=max(1, n_osds // osds_per_host),
        osds_per_host=osds_per_host,
        numrep=size,
        firstn=pool_type == POOL_TYPE_REPLICATED,
    )
    m = OSDMap(cmap)
    m.add_pool(PGPool(pool_id=1, type=pool_type, size=size,
                      min_size=max(1, size - 1), pg_num=pg_num,
                      pgp_num=pg_num, crush_rule=ruleno,
                      ec_profile=ec_profile or {}, name="rbd"))
    return m
