"""Cluster map: pools, PG -> OSD placement pipeline, epochs."""

from ceph_tpu.osdmap.osdmap import OSDMap, PGPool, PGid  # noqa: F401
