"""Upmap balancer: compute pg_upmap_items that flatten PG-per-OSD skew.

Behavioral analog of OSDMap::calc_pg_upmaps
(/root/reference/src/osd/OSDMap.cc:3771): iterate — measure per-OSD
deviation from the weight-proportional target, move PGs off the fullest
OSDs onto the least-full ones, record the moves as pg_upmap_items —
until the worst deviation ratio is under threshold.

TPU-first: the expensive part of every iteration is the WHOLE-MAP
placement, which here is the batched `pool_mapping` dispatch (one
TensorMapper run per pool per iteration; the reference walks
crush_do_rule per PG).  Deviation/target math is vectorized numpy.
Candidate validity preserves the rule's failure domain: a replacement
OSD must not share the chooseleaf-domain (e.g. host) with any other
member of the PG — the constraint try_remap_rule enforces via CRUSH
(/root/reference/src/osd/OSDMap.cc:3750, try_pg_upmap :3727).

Each iteration moves one PG per overfull OSD (a batched generalization
of the reference's one-change-per-pass restart loop) so large maps
converge in few placement dispatches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.crush.types import (
    CRUSH_ITEM_NONE,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
)
from ceph_tpu.osdmap.osdmap import OSDMap, PGid


def _failure_domains(m: OSDMap, ruleno: int) -> Dict[int, int]:
    """osd -> failure-domain id for the rule's chooseleaf type (osd id
    itself for osd-granularity rules)."""
    rule = m.crush.rules[ruleno]
    dom_type = 0
    for op, _arg1, arg2 in rule.steps:
        if op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP,
                  RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP):
            dom_type = arg2
            break
    parent: Dict[int, int] = {}
    for bid, b in m.crush.buckets.items():
        for item in b.items:
            parent[item] = bid
    out: Dict[int, int] = {}
    for osd in range(m.max_osd):
        node = osd
        dom = osd
        seen = 0
        while node in parent and seen < 64:
            node = parent[node]
            btype = m.crush.buckets[node].type
            if btype == dom_type:
                dom = node
                break
            seen += 1
        out[osd] = dom if dom_type > 0 else osd
    return out


def calc_pg_upmaps(m: OSDMap, pool_ids: Optional[List[int]] = None,
                   max_deviation_ratio: float = 0.05,
                   max_iterations: int = 30,
                   ) -> Dict[PGid, List[Tuple[int, int]]]:
    """Compute new pg_upmap_items (OSDMap.cc:3771).  Mutates ``m``'s
    pg_upmap_items with the chosen moves and also returns them (the
    caller commits them as an Incremental / writes the map back)."""
    pools = pool_ids if pool_ids is not None else list(m.pools)
    changes: Dict[PGid, List[Tuple[int, int]]] = {}
    domains_by_pool = {pid: _failure_domains(m, m.pools[pid].crush_rule)
                       for pid in pools}

    for _ in range(max_iterations):
        # one batched placement per pool: the whole-map dispatch
        placements = {}
        counts = np.zeros(m.max_osd, dtype=np.int64)
        total_slots = 0
        for pid in pools:
            up, _upp = m.pool_mapping(pid)
            placements[pid] = up
            valid = up[(up >= 0) & (up < m.max_osd)]
            counts += np.bincount(valid, minlength=m.max_osd)
            total_slots += int((up != CRUSH_ITEM_NONE).sum())

        weights = np.asarray(m.osd_weight[: m.max_osd], dtype=np.float64)
        weights = weights * np.asarray(m.osd_exists[: m.max_osd],
                                       dtype=np.float64)
        wtotal = weights.sum()
        if wtotal <= 0 or total_slots == 0:
            break
        target = weights / wtotal * total_slots
        in_osds = weights > 0
        deviation = np.where(in_osds, counts - target, 0.0)
        ratio = np.where(target > 0, deviation / np.maximum(target, 1e-9), 0)

        overfull = [int(o) for o in np.argsort(-deviation)
                    if deviation[o] >= 1.0
                    and ratio[o] > max_deviation_ratio]
        underfull = [int(o) for o in np.argsort(deviation)
                     if deviation[o] <= -0.999 and in_osds[o]]
        if not overfull or not underfull:
            break

        moved_any = False
        taken_under: Dict[int, int] = {}
        for osd in overfull:
            move = _move_one_pg(m, pools, placements, osd, underfull,
                                taken_under, deviation, changes,
                                domains_by_pool)
            if move:
                moved_any = True
        if not moved_any:
            break
    return changes


def _move_one_pg(m: OSDMap, pools, placements, src_osd: int,
                 underfull: List[int], taken_under: Dict[int, int],
                 deviation, changes, domains_by_pool) -> bool:
    """Move ONE PG slot off src_osd onto the best valid underfull OSD,
    recording the pg_upmap_items pair (try_pg_upmap analog)."""
    for pid in pools:
        domains = domains_by_pool[pid]
        up = placements[pid]
        rows, cols = np.nonzero(up == src_osd)
        for r, c in zip(rows, cols):
            pgid = PGid(pid, int(r))
            if pgid in m.pg_upmap or pgid in m.pg_upmap_items:
                continue  # already remapped (reference skips these)
            members = [int(v) for v in up[r] if v != CRUSH_ITEM_NONE]
            used_doms = {domains.get(o) for o in members if o != src_osd}
            for dst in underfull:
                # cap how much we pour into one underfull osd this pass
                if taken_under.get(dst, 0) >= max(
                        1, int(-deviation[dst])):
                    continue
                if dst in members:
                    continue
                if domains.get(dst) in used_doms:
                    continue  # would violate the failure domain
                m.pg_upmap_items.setdefault(pgid, []).append(
                    (src_osd, dst))
                changes.setdefault(pgid, []).append((src_osd, dst))
                taken_under[dst] = taken_under.get(dst, 0) + 1
                return True
    return False


def pg_per_osd_stddev(m: OSDMap,
                      pool_ids: Optional[List[int]] = None) -> float:
    """PG-count standard deviation across in OSDs (the balance metric)."""
    pools = pool_ids if pool_ids is not None else list(m.pools)
    counts = np.zeros(m.max_osd, dtype=np.int64)
    for pid in pools:
        up, _ = m.pool_mapping(pid)
        valid = up[(up >= 0) & (up < m.max_osd)]
        counts += np.bincount(valid, minlength=m.max_osd)
    mask = (np.asarray(m.osd_weight[: m.max_osd]) > 0) & \
        np.asarray(m.osd_exists[: m.max_osd], dtype=bool)
    return float(np.std(counts[mask]))
