"""ceph CLI analog: cluster status + per-daemon admin commands.

Reference: src/ceph.in — ``ceph status/health/df``, ``ceph daemon
<name> <cmd>`` (the admin-socket path), and ``ceph daemonperf <name>``
(the rate view over successive perf dumps).

    python -m ceph_tpu.tools.ceph --mon host:port status
    python -m ceph_tpu.tools.ceph --mon host:port daemon osd.0 perf dump
    python -m ceph_tpu.tools.ceph --mon host:port daemonperf osd.0 1 5
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Dict, Optional, Tuple

from ceph_tpu.cluster.objecter import RadosClient
from ceph_tpu.utils import Config


def _parse_addr(s: str) -> Tuple[str, int]:
    host, port = s.rsplit(":", 1)
    return (host, int(port))


def resolve_daemon(objecter, name: str, mon_addrs):
    """Daemon name -> address via the client's cached osdmap (the CLI's
    analog of asok-path resolution: osds/mgr/mds addresses ride the
    map); 'mon' is the mon we are talking to, 'mon.N' indexes the
    --mon list in order."""
    m = objecter.osdmap
    kind, _, num = name.partition(".")
    if kind == "mon":
        if not num:
            return tuple(objecter.mon_addr)
        rank = int(num)
        if rank >= len(mon_addrs):
            raise KeyError(
                f"mon.{rank} not in the --mon list "
                f"({len(mon_addrs)} given; pass every mon to address "
                "one by rank)")
        return tuple(mon_addrs[rank])
    if kind == "osd":
        addr = m.osd_addrs.get(int(num))
        if addr is None:
            raise KeyError(f"{name} has no address in the map")
        return tuple(addr)
    if kind == "mgr":
        if not getattr(m, "mgr_addr", None):
            raise KeyError("no mgr in the map")
        return tuple(m.mgr_addr)
    if kind == "mds":
        addrs = getattr(m, "mds_addrs", {}) or {}
        rank = int(num) if num else 0
        if rank not in addrs:
            raise KeyError(f"{name} has no address in the map")
        return tuple(addrs[rank])
    raise KeyError(f"unknown daemon kind {kind!r}")


def _rate_rows(prev: Dict, cur: Dict, dt: float):
    """Counter deltas/s between two perf dumps (daemonperf's view):
    ints rate; avg dicts rate avgcount and report interval-average
    latency."""
    rows = []
    for section in sorted(cur):
        for name in sorted(cur[section]):
            v1, v0 = cur[section][name], prev.get(section, {}).get(name)
            if isinstance(v1, (int, float)) and \
                    isinstance(v0, (int, float)):
                if v1 != v0:
                    rows.append((f"{section}.{name}",
                                 f"{(v1 - v0) / dt:.1f}/s"))
            elif isinstance(v1, dict) and "avgcount" in v1 and \
                    isinstance(v0, dict):
                dc = v1["avgcount"] - v0.get("avgcount", 0)
                ds = v1["sum"] - v0.get("sum", 0.0)
                if dc:
                    rows.append((f"{section}.{name}",
                                 f"{dc / dt:.1f}/s "
                                 f"avg {ds / dc * 1e3:.2f}ms"))
    return rows


async def daemonperf(objecter, addr, interval: float, count: int) -> None:
    """Poll 'perf dump' and print per-interval rates (reference
    'ceph daemonperf': DaemonWatcher's delta view)."""
    prev = await objecter.daemon_command(addr, {"prefix": "perf dump"})
    t_prev = time.perf_counter()
    for _ in range(count):
        await asyncio.sleep(interval)
        cur = await objecter.daemon_command(addr, {"prefix": "perf dump"})
        now = time.perf_counter()
        rows = _rate_rows(prev, cur, now - t_prev)
        stamp = time.strftime("%H:%M:%S")
        if not rows:
            print(f"{stamp}  (idle)")
        for name, rate in rows:
            print(f"{stamp}  {name:<44} {rate}")
        prev, t_prev = cur, now


async def _run(args) -> int:
    mons = [_parse_addr(a) for a in args.mon.split(",")]
    client = RadosClient(mons if len(mons) > 1 else mons[0],
                         name="cephcli", config=Config())
    await client.connect()
    obj = client.objecter
    try:
        if args.cmd in ("status", "health", "df"):
            print(json.dumps(
                await obj.mon_command({"prefix": args.cmd}), indent=2,
                default=str))
            return 0
        if args.cmd == "log":
            print(json.dumps(await obj.mon_command(
                {"prefix": "log last", "num": args.num}), indent=2))
            return 0
        if args.cmd == "daemon":
            addr = resolve_daemon(obj, args.name, mons)
            cmd = {"prefix": " ".join(args.command)}
            if args.args:
                cmd["args"] = json.loads(args.args)
            data = await obj.daemon_command(addr, cmd,
                                            timeout=args.timeout)
            print(data if isinstance(data, str)
                  else json.dumps(data, indent=2, default=str))
            return 0
        if args.cmd == "daemonperf":
            addr = resolve_daemon(obj, args.name, mons)
            await daemonperf(obj, addr, args.interval, args.count)
            return 0
        return 2
    finally:
        await client.shutdown()


def parse_args(argv=None):
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("--mon", required=True,
                    help="host:port[,host:port..]")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    sub.add_parser("health")
    sub.add_parser("df")
    p = sub.add_parser("log")
    p.add_argument("num", type=int, nargs="?", default=20)
    p = sub.add_parser("daemon",
                       help="admin-socket command on one daemon")
    p.add_argument("name", help="osd.N | mon[.N] | mgr | mds.N")
    p.add_argument("command", nargs="+",
                   help="command words, e.g. perf dump")
    p.add_argument("--args", help="JSON dict of command arguments")
    p.add_argument("--timeout", type=float, default=30.0)
    p = sub.add_parser("daemonperf", help="perf-counter rate view")
    p.add_argument("name")
    p.add_argument("interval", type=float, nargs="?", default=1.0)
    p.add_argument("count", type=int, nargs="?", default=5)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    return asyncio.run(_run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
