"""osdmaptool analog: inspect and simulate OSDMaps.

Reference: src/tools/osdmaptool.cc (--print, --test-map-pgs placement
histograms) and src/tools/psim.cc (whole-cluster placement simulation).
The whole-pool simulation runs through the batched TensorMapper path —
one device dispatch per pool instead of per-PG scalar loops.
"""

from __future__ import annotations

import argparse
import pickle
import sys
from collections import Counter


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="osdmaptool")
    ap.add_argument("mapfn", help="pickled OSDMap")
    ap.add_argument("--print", dest="do_print", action="store_true")
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--pool", type=int, default=None)
    ap.add_argument("--upmap", metavar="OUTFN", default=None,
                    help="compute pg_upmap_items balancing PGs/OSD "
                         "(calc_pg_upmaps, OSDMap.cc:3771) and write the "
                         "rebalanced map")
    ap.add_argument("--upmap-deviation", type=float, default=0.05)
    ap.add_argument("--upmap-max", type=int, default=30)
    args = ap.parse_args(argv)

    m = pickle.loads(open(args.mapfn, "rb").read())
    if args.upmap is not None:
        from ceph_tpu.osdmap import balancer

        pools = [args.pool] if args.pool is not None else None
        before = balancer.pg_per_osd_stddev(m, pools)
        changes = balancer.calc_pg_upmaps(
            m, pools, max_deviation_ratio=args.upmap_deviation,
            max_iterations=args.upmap_max)
        after = balancer.pg_per_osd_stddev(m, pools)
        for pgid, items in sorted(changes.items()):
            pairs = " ".join(f"{a}->{b}" for a, b in items)
            print(f"upmap {pgid.pool}.{pgid.seed} items {pairs}")
        print(f"pgs-per-osd stddev {before:.2f} -> {after:.2f} "
              f"({len(changes)} pg_upmap_items)")
        with open(args.upmap, "wb") as f:
            f.write(pickle.dumps(m))
    if args.do_print:
        print(f"epoch {m.epoch}")
        print(f"max_osd {m.max_osd}")
        for pid, p in m.pools.items():
            kind = "erasure" if p.is_erasure() else "replicated"
            print(f"pool {pid} '{p.name}' {kind} size {p.size} "
                  f"pg_num {p.pg_num} crush_rule {p.crush_rule}")
        for o in range(m.max_osd):
            state = "up" if m.osd_up[o] else "down"
            inout = "in" if m.osd_weight[o] > 0 else "out"
            print(f"osd.{o} {state} {inout} weight "
                  f"{m.osd_weight[o] / 0x10000:.4f}")
    if args.test_map_pgs:
        pools = [args.pool] if args.pool is not None else list(m.pools)
        for pid in pools:
            pool = m.pools[pid]
            counts = Counter()
            primaries = Counter()
            from ceph_tpu.osdmap.osdmap import PGid

            try:
                # whole-pool placement in ONE batched device dispatch
                up_arr, upp_arr = m.pool_mapping(pid)
                for seed in range(pool.pg_num):
                    for o in up_arr[seed]:
                        if 0 <= int(o) < m.max_osd:
                            counts[int(o)] += 1
                    if int(upp_arr[seed]) >= 0:
                        primaries[int(upp_arr[seed])] += 1
            except (NotImplementedError, AssertionError):
                for seed in range(pool.pg_num):
                    up, upp, acting, actp = m.pg_to_up_acting_osds(
                        PGid(pid, seed))
                    for o in acting:
                        if o >= 0:
                            counts[o] += 1
                    if actp >= 0:
                        primaries[actp] += 1
            avg = sum(counts.values()) / max(1, len(counts))
            print(f"pool {pid} pg_num {pool.pg_num}")
            for o in sorted(counts):
                print(f"  osd.{o}\t{counts[o]}\tprimary {primaries.get(o, 0)}")
            print(f"  avg {avg:.1f} | max/avg "
                  f"{max(counts.values()) / avg:.2f}" if counts else "  empty")
    return 0


if __name__ == "__main__":
    sys.exit(main())
