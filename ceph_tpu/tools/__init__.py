"""Operator tools (reference src/tools/): crushtool, osdmaptool, rados,
objectstore-tool analogs, runnable as ``python -m ceph_tpu.tools.<name>``."""
