"""rados CLI analog: object ops + bench against a live cluster.

Reference: src/tools/rados/rados.cc (put/get/ls/df/bench subcommands).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

from ceph_tpu.cluster.objecter import RadosClient
from ceph_tpu.utils import Config


def _parse_addr(s: str):
    host, port = s.rsplit(":", 1)
    return (host, int(port))


async def _run(args) -> int:
    mons = [_parse_addr(a) for a in args.mon.split(",")]
    client = RadosClient(mons if len(mons) > 1 else mons[0],
                         name="radoscli", config=Config())
    await client.connect()
    try:
        if args.cmd == "lspools":
            status = await client.status()
            for name, info in status["pools"].items():
                print(f"{info['id']} {name}")
            return 0
        pool = int(args.pool) if args.pool and args.pool.isdigit() else None
        if pool is None:
            status = await client.status()
            match = [i["id"] for n, i in status["pools"].items()
                     if n == args.pool]
            if not match:
                print(f"no pool {args.pool}", file=sys.stderr)
                return 1
            pool = match[0]
        io = client.ioctx(pool)
        if args.cmd == "put":
            data = open(args.infile, "rb").read() if args.infile else \
                sys.stdin.buffer.read()
            await io.write_full(args.obj, data)
        elif args.cmd == "get":
            data = await io.read(args.obj)
            if args.outfile:
                open(args.outfile, "wb").write(data)
            else:
                sys.stdout.buffer.write(data)
        elif args.cmd == "rm":
            await io.remove(args.obj)
        elif args.cmd == "ls":
            for oid in await io.list_objects():
                print(oid)
        elif args.cmd == "stat":
            print(args.obj, "size", await io.stat(args.obj))
        elif args.cmd == "bench":
            secs = args.seconds
            size = args.block_size
            blob = b"\xa5" * size
            n = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < secs:
                await io.write_full(f"bench_{n}", blob)
                n += 1
            dt = time.perf_counter() - t0
            print(f"wrote {n} x {size} B in {dt:.2f}s = "
                  f"{n * size / dt / 1e6:.1f} MB/s, {n / dt:.1f} iops")
            for i in range(n):
                await io.remove(f"bench_{i}")
        else:
            return 2
        return 0
    finally:
        await client.shutdown()


def parse_args(argv=None):
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("--mon", required=True, help="host:port[,host:port..]")
    ap.add_argument("-p", "--pool", help="pool name or id")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lspools")
    p = sub.add_parser("put"); p.add_argument("obj"); p.add_argument("infile", nargs="?")
    p = sub.add_parser("get"); p.add_argument("obj"); p.add_argument("outfile", nargs="?")
    p = sub.add_parser("rm"); p.add_argument("obj")
    sub.add_parser("ls")
    p = sub.add_parser("stat"); p.add_argument("obj")
    p = sub.add_parser("bench")
    p.add_argument("seconds", type=float)
    p.add_argument("--block-size", type=int, default=65536)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    return asyncio.run(_run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
