"""rados CLI analog: object ops + bench against a live cluster.

Reference: src/tools/rados/rados.cc (put/get/ls/df/bench subcommands).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

from ceph_tpu.cluster.objecter import RadosClient
from ceph_tpu.utils import Config


def _parse_addr(s: str):
    host, port = s.rsplit(":", 1)
    return (host, int(port))


async def _run(args) -> int:
    mons = [_parse_addr(a) for a in args.mon.split(",")]
    client = RadosClient(mons if len(mons) > 1 else mons[0],
                         name="radoscli", config=Config())
    await client.connect()
    try:
        if args.cmd == "lspools":
            status = await client.status()
            for name, info in status["pools"].items():
                print(f"{info['id']} {name}")
            return 0
        pool = int(args.pool) if args.pool and args.pool.isdigit() else None
        if pool is None:
            status = await client.status()
            match = [i["id"] for n, i in status["pools"].items()
                     if n == args.pool]
            if not match:
                print(f"no pool {args.pool}", file=sys.stderr)
                return 1
            pool = match[0]
        io = client.ioctx(pool)
        if args.cmd == "put":
            data = open(args.infile, "rb").read() if args.infile else \
                sys.stdin.buffer.read()
            await io.write_full(args.obj, data)
        elif args.cmd == "get":
            data = await io.read(args.obj)
            if args.outfile:
                open(args.outfile, "wb").write(data)
            else:
                sys.stdout.buffer.write(data)
        elif args.cmd == "rm":
            await io.remove(args.obj)
        elif args.cmd == "ls":
            for oid in await io.list_objects():
                print(oid)
        elif args.cmd == "stat":
            print(args.obj, "size", await io.stat(args.obj))
        elif args.cmd == "bench":
            report = await bench(io, args.seconds, args.mode,
                                 concurrency=args.t,
                                 block_size=args.block_size,
                                 cleanup=not args.no_cleanup)
            print(f"{report['mode']}: {report['ops']} x "
                  f"{report['block_size']} B in {report['seconds']:.2f}s")
            print(f"  bandwidth: {report['mbps']:.1f} MB/s   "
                  f"iops: {report['iops']:.1f}")
            print(f"  latency ms: avg {report['lat_avg_ms']:.2f}  "
                  f"p50 {report['lat_p50_ms']:.2f}  "
                  f"p95 {report['lat_p95_ms']:.2f}  "
                  f"max {report['lat_max_ms']:.2f}")
        else:
            return 2
        return 0
    finally:
        await client.shutdown()


async def bench(io, seconds: float, mode: str = "write",
                concurrency: int = 16, block_size: int = 65536,
                cleanup: bool = True) -> dict:
    """The reference `rados bench` engine (src/tools/rados/rados.cc:103
    obj_bencher write/seq/rand): `concurrency` in-flight ops for
    `seconds`, returning bandwidth + latency percentiles.

    write: distinct objects; seq: read the bench objects in written
    order; rand: uniform random reads over them.  seq/rand write a
    seeding set first when none exists."""
    import random

    blob = b"\xa5" * block_size
    lats: list = []
    counter = {"n": 0}

    existing: list = []
    if mode in ("seq", "rand"):
        existing = [o for o in await io.list_objects()
                    if o.startswith("bench_")]
        if not existing:
            # seed enough objects to read back
            existing = [f"bench_{i}" for i in range(concurrency * 4)]
            await asyncio.gather(*(io.write_full(o, blob)
                                   for o in existing))

    deadline = time.perf_counter() + seconds
    rng = random.Random(0)

    async def worker(wid: int):
        i = wid
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            if mode == "write":
                await io.write_full(f"bench_{i}", blob)
            elif mode == "seq":
                await io.read(existing[i % len(existing)])
            else:
                await io.read(existing[rng.randrange(len(existing))])
            lats.append(time.perf_counter() - t0)
            counter["n"] += 1
            i += concurrency

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(w) for w in range(concurrency)))
    dt = max(time.perf_counter() - t0, 1e-9)
    n = counter["n"]
    lats.sort()

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))] * 1e3 \
            if lats else 0.0

    report = {
        "mode": mode, "ops": n, "block_size": block_size, "seconds": dt,
        "mbps": n * block_size / dt / 1e6, "iops": n / dt,
        "lat_avg_ms": (sum(lats) / len(lats) * 1e3) if lats else 0.0,
        "lat_p50_ms": pct(0.50), "lat_p95_ms": pct(0.95),
        "lat_max_ms": lats[-1] * 1e3 if lats else 0.0,
    }
    if cleanup and mode == "write":
        names = [o for o in await io.list_objects()
                 if o.startswith("bench_")]
        await asyncio.gather(*(io.remove(o) for o in names))
    return report


def parse_args(argv=None):
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("--mon", required=True, help="host:port[,host:port..]")
    ap.add_argument("-p", "--pool", help="pool name or id")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lspools")
    p = sub.add_parser("put"); p.add_argument("obj"); p.add_argument("infile", nargs="?")
    p = sub.add_parser("get"); p.add_argument("obj"); p.add_argument("outfile", nargs="?")
    p = sub.add_parser("rm"); p.add_argument("obj")
    sub.add_parser("ls")
    p = sub.add_parser("stat"); p.add_argument("obj")
    p = sub.add_parser("bench")
    p.add_argument("seconds", type=float)
    p.add_argument("mode", nargs="?", default="write",
                   choices=("write", "seq", "rand"))
    p.add_argument("-t", type=int, default=16, help="concurrent ops")
    p.add_argument("--block-size", type=int, default=65536)
    p.add_argument("--no-cleanup", action="store_true")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    return asyncio.run(_run(parse_args(argv)))


if __name__ == "__main__":
    sys.exit(main())
