"""ceph-objectstore-tool analog: offline FileStore inspection.

Reference: src/tools/ceph_objectstore_tool.cc (--op list / info / dump
against a stopped OSD's store).
"""

from __future__ import annotations

import argparse
import pickle
import sys

from ceph_tpu.cluster.filestore import FileStore


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="objectstore-tool")
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--op", choices=["list", "info", "dump", "meta"],
                    default="list")
    ap.add_argument("--collection")
    ap.add_argument("--object")
    args = ap.parse_args(argv)

    store = FileStore(args.data_path)
    store.mount()
    try:
        if args.op == "list":
            for coll in store.list_collections():
                if args.collection and coll != args.collection:
                    continue
                for oid in store.list_objects(coll):
                    print(f"{coll}/{oid}")
        elif args.op == "info":
            size = store.stat(args.collection, args.object)
            ver = store.get_version(args.collection, args.object)
            xattrs = store.get_xattrs(args.collection, args.object)
            print(f"{args.collection}/{args.object} size {size} "
                  f"version {ver} xattrs {sorted(xattrs)}")
        elif args.op == "dump":
            sys.stdout.buffer.write(store.read(args.collection, args.object))
        elif args.op == "meta":
            # the persisted pg log of a collection (pgmeta omap)
            from ceph_tpu.cluster.osd import PGMETA

            coll = args.collection
            lu = store.getattr(coll, PGMETA, "last_update")
            print("last_update", pickle.loads(lu) if lu else None)
            for k, v in sorted(store.omap_get(coll, PGMETA).items()):
                e = pickle.loads(v)
                print(f"  {e.version} {e.op} {e.oid}")
        return 0
    finally:
        store.umount()


if __name__ == "__main__":
    sys.exit(main())
