"""crushtool analog: build, decompile, and test CRUSH maps.

Reference: src/tools/crushtool.cc (--test drives CrushTester::test,
crushtool.cc:1024; --compile/--decompile the text map grammar).  Our map
interchange format is JSON (the text-map analog); --build constructs a
map from a simple spec, --test reports distribution stats.
"""

from __future__ import annotations

import argparse
import json
import pickle
import sys

from ceph_tpu.crush.tester import CrushTester
from ceph_tpu.crush.types import (
    Bucket,
    CrushMap,
    Rule,
    Tunables,
)


def map_to_json(cmap: CrushMap) -> dict:
    return {
        "tunables": vars(cmap.tunables),
        "buckets": [
            {"id": b.id, "type": b.type, "alg": b.alg, "items": b.items,
             "weights": b.weights,
             "name": cmap.item_names.get(b.id)}
            for b in cmap.buckets.values()],
        "rules": [{"steps": [list(s) for s in r.steps], "type": r.type}
                  for r in cmap.rules],
        "types": cmap.type_names,
        "device_class": cmap.device_class,
    }


def map_from_json(d: dict) -> CrushMap:
    cmap = CrushMap(Tunables(**d.get("tunables", {})))
    for b in d["buckets"]:
        cmap.add_bucket(Bucket(id=b["id"], type=b["type"],
                               alg=b.get("alg", "straw2"),
                               items=b["items"], weights=b["weights"]),
                        name=b.get("name"))
    for r in d.get("rules", []):
        cmap.add_rule(Rule(steps=[tuple(s) for s in r["steps"]],
                           type=r.get("type", 1)))
    for dev, cls in d.get("device_class", {}).items():
        cmap.set_device_class(int(dev), cls)
    return cmap


def load_map(path: str) -> CrushMap:
    blob = open(path, "rb").read()
    if blob[:1] in (b"{", b"["):
        return map_from_json(json.loads(blob))
    if blob[:1] == b"\x80":
        # pickle protocol 2+ magic: the binary map form
        return pickle.loads(blob)
    # anything else textual is the operator map language
    from ceph_tpu.crush.compiler import compile_text

    return compile_text(blob.decode())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crushtool")
    ap.add_argument("-i", "--infn", help="input map (json or pickled)")
    ap.add_argument("-o", "--outfn", help="output file")
    ap.add_argument("--compile", action="store_true",
                    help="text/json map -> pickled binary map "
                         "(crushtool -c)")
    ap.add_argument("--decompile", action="store_true",
                    help="binary map -> operator TEXT map (crushtool -d; "
                         "--json for the json form)")
    ap.add_argument("--json", action="store_true",
                    help="decompile to json instead of the text language")
    ap.add_argument("--test", action="store_true",
                    help="batch placement test (CrushTester)")
    ap.add_argument("--rule", type=int, default=0)
    ap.add_argument("--num-rep", type=int, default=3)
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--show-utilization", action="store_true")
    ap.add_argument("--show-mappings", action="store_true")
    args = ap.parse_args(argv)

    if not args.infn:
        ap.error("-i required")
    cmap = load_map(args.infn)

    if args.compile:
        with open(args.outfn or "crush.bin", "wb") as f:
            pickle.dump(cmap, f)
        return 0
    if args.decompile:
        if args.json:
            out = json.dumps(map_to_json(cmap), indent=2)
        else:
            from ceph_tpu.crush.compiler import decompile

            out = decompile(cmap)
        if args.outfn:
            open(args.outfn, "w").write(out)
        else:
            print(out)
        return 0
    if args.test:
        tester = CrushTester(cmap)
        if args.show_mappings:
            from ceph_tpu.crush.scalar import ScalarMapper

            sm = ScalarMapper(cmap)
            w = [0x10000] * cmap.max_devices
            for x in range(args.min_x, args.max_x + 1):
                out = sm.do_rule(args.rule, x, args.num_rep, w)
                print(f"CRUSH rule {args.rule} x {x} {out}")
        report = tester.test(args.rule, args.num_rep,
                             args.min_x, args.max_x)
        print(report.summary() if args.show_utilization else
              f"tested {report.n_inputs} inputs: "
              f"{len(report.bad_mappings)} bad mappings, "
              f"max deviation {report.max_deviation:.3f}")
        return 1 if report.bad_mappings else 0
    ap.error("one of --compile/--decompile/--test required")
    return 2


if __name__ == "__main__":
    sys.exit(main())
