"""ceph_tpu — a TPU-native distributed-storage framework.

A ground-up rebuild of the capabilities of Ceph (reference: fullerdj/ceph
v12.1.2) designed for TPU hardware: the dense-compute hot paths — GF(2^8)
Reed-Solomon erasure coding, CRUSH placement, crc32c checksumming — run as
batched JAX/XLA/Pallas kernels, and the cluster around them (monitors, OSDs,
object store, messenger, client) is rebuilt as an async control plane that
feeds fixed-shape device batches.

Subpackages
-----------
ops       Kernel substrate: GF(2^8) tensor arithmetic, rjenkins1 hashing,
          crc32c, bit-matrix matmuls on the MXU.
ec        Erasure-code framework: ErasureCodeInterface semantics, plugin
          registry, jerasure/isa/lrc/shec codec families.
crush     CRUSH placement: map data structures, straw2, vmapped crush_do_rule.
osdmap    Cluster map: pools, PG -> OSD placement pipeline, upmaps.
cluster   Mini-RADOS: messenger, monitor, OSD daemons, object stores, client.
parallel  Device-mesh sharding helpers (stripe-batch sharding over ICI).
utils     Config schema, perf counters, misc runtime.
"""

__version__ = "0.1.0"
