"""graft-chaos: deterministic fault injection for the mini-cluster.

Four injector families behind the existing config/admin-socket seams —
net (messenger interposition), disk (store-level faults), daemons
(kill/revive/restart), clock (per-daemon skewable time) — plus a
declarative scenario runner that interleaves workload with seeded fault
schedules and judges durability invariants after convergence.  Every
random decision derives from per-injector streams of one seed
(chaos/rng.py), so a failing run replays bit-identically from
``--seed``; with every injector disabled the hot paths pay a single
``is None`` test (``chaos report`` / ``chaos_total()`` prove it).
"""

import asyncio as _asyncio


class ChaosCrash(_asyncio.CancelledError):
    """Raised by an armed crash point (OSD._chaos_point): unwinds the
    current coroutine exactly like a task dying mid-await — the closest
    in-process model of 'the process ceased at this instant'.  A
    CancelledError subclass so every ``except asyncio.CancelledError:
    raise`` hygiene path propagates it and the dying tasks never warn
    about unretrieved exceptions."""


from ceph_tpu.chaos.clock import ChaosClock  # noqa: F401
from ceph_tpu.chaos.points import (  # noqa: F401
    ChaosInterrupt,
    maybe_interrupt,
)
from ceph_tpu.chaos.counters import (  # noqa: F401
    CHAOS,
    chaos_report,
    chaos_total,
)
from ceph_tpu.chaos.daemons import (  # noqa: F401
    DaemonInjector,
    heal_partitions,
    partition,
    zero_rates,
)
from ceph_tpu.chaos.disk import DiskInjector  # noqa: F401
from ceph_tpu.chaos.net import NetInjector, ensure_injector  # noqa: F401
from ceph_tpu.chaos.rng import derive_seed, stream  # noqa: F401
from ceph_tpu.chaos.scenario import (  # noqa: F401
    Event,
    Scenario,
    Verdict,
    build_schedule,
    builtin_scenarios,
    ev,
    run_scenario,
)
from ceph_tpu.chaos.frontdoor import (  # noqa: F401
    FrontdoorScenario,
    FrontdoorState,
    frontdoor_scenarios,
    run_frontdoor,
)
