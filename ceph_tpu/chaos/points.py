"""Named chaos seams for CLIENT LIBRARIES (round 15).

The round-12 ``chaos_crash_point`` machinery power-cuts a *daemon* at a
named seam (``OSD._chaos_point``).  The L8 front doors are different:
librbd and the RGW core are LIBRARIES living inside a client process —
there is no daemon to kill, and "crash" means *the application died
mid-transaction and a restarted application retries (or never does)*.

``maybe_interrupt`` is that model: when the client config's
``chaos_crash_point`` matches the named seam, it raises
``ChaosInterrupt`` — the library op unwinds at this instant, exactly as
if the process had ceased, but the event loop (the "machine") survives.
The armed point is ONE-SHOT: it clears itself on firing, so the
scenario's retry (the restarted application) runs clean and a seeded
schedule resolves exactly one interruption per armed event.

No-op contract: library call sites guard with a single falsy test on
``config.chaos_crash_point`` before importing this module, mirroring
the OSD seam — an unarmed front-door op pays one attribute read.

MDS points are NOT here: the MDS is a daemon, so its seams crash it
through the vstart callback like an OSD (``MDSDaemon._chaos_point``).
"""

from __future__ import annotations

from ceph_tpu.chaos.counters import CHAOS


class ChaosInterrupt(Exception):
    """An armed client-library chaos point fired: the front-door op is
    cut at this instant.  A plain Exception (NOT CancelledError): the
    client process "died", but the scenario runner — the outside world
    observing it — keeps running and decides whether a restarted client
    retries the transaction or abandons it mid-flight."""


def resolve_fire(config, name: str) -> bool:
    """THE armed-point resolution, shared by the client seam below and
    the MDS daemon seam (``MDSDaemon._chaos_point``): chain-head match,
    seeded skip countdown (decremented through the config so a retry's
    traversals continue it), and pop-and-rearm of the chain remainder.
    Returns True when the point fires; the CALLER performs its seam
    action (raise ChaosInterrupt, or crash the daemon).  The armed
    value may be a comma-separated CHAIN: firing pops the head and
    arms the remainder, so one event can cut a transaction, then cut
    its retry (or the next incarnation's replay) at a later seam; an
    empty remainder disarms (one-shot per chain link).

    (``OSD._chaos_point`` keeps its own resolution on purpose: OSD skip
    state is instance-level and observer-re-armable — round-12
    semantics the seeded batch scenarios replay against.)
    """
    cp = config.chaos_crash_point
    if not cp:
        return False
    chain = cp.split(",")
    if chain[0] != name:
        return False
    skip = config.chaos_crash_point_skip
    if skip > 0:
        config.set("chaos_crash_point_skip", skip - 1)
        return False
    config.set("chaos_crash_point", ",".join(chain[1:]))
    return True


def maybe_interrupt(config, name: str) -> None:
    """Fire the armed interrupt seam if it matches ``name``."""
    if resolve_fire(config, name):
        CHAOS.inc("interrupt_points_fired")
        raise ChaosInterrupt(f"chaos interrupt point {name!r} fired")
