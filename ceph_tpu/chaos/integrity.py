"""Integrity scenarios (round 16): verified reads under live corruption
and cluster-full graceful degradation, both seeded and replayable.

Two acceptance shapes ride here:

- ``bitrot-under-load`` — a plain chaos :class:`Scenario` (built by
  ``integrity_scenarios``) driving a read-heavy graft-load window over
  an EC pool while seeded at-rest bit flips land on acked objects after
  every round's writes, with the scheduled deep scrubber running
  concurrently.  The verdict: zero wrong-bytes acks (``durability``
  reads every acked payload back bit-identical — verify-on-read decodes
  AROUND the corruption), every injected flip detected and healed
  (``repair`` + ``scrub``), and the whole run replays bit-identically
  from its seed.

- ``disk-fill-drain`` — a dedicated phased runner (:func:`run_fill_drain`
  over a :class:`FillScenario`): seeded writes exhaust the stores'
  enforced capacity; the run asserts explicit ENOSPC (never a timeout),
  the mon's full flag + OSD_FULL/HEALTH_ERR raising, deletes STILL
  admitted while full (the dig-yourself-out contract), flags clearing as
  space frees, and service resuming — with zero acked-then-lost writes
  across the whole cycle.  Phases are resolved from the seed, so the
  plan (and the verdict's replay key) is bit-identical across runs.

Like chaos/frontdoor.py, the runner reuses the shared heal/converge/
judge seams from chaos/scenario.py — composition, not reimplementation.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.daemons import DaemonInjector
from ceph_tpu.chaos.rng import stream
from ceph_tpu.chaos.scenario import (
    Scenario,
    Verdict,
    ev,
    heal_cluster,
    judge_invariants,
    wait_converged,
)
from ceph_tpu.ops import crc32c as crcmod


def integrity_scenarios(scale: float = 1.0) -> Dict[str, object]:
    """The round-16 integrity library, sized by ``scale`` (1.0 = the
    full acceptance shape, slow; small fractions run the same code
    paths at tier-1 size — the storm_scenarios convention)."""
    from ceph_tpu.load.driver import LoadSpec

    s = max(0.03, min(1.0, scale))
    full = s >= 1.0
    rounds = 4 if full else 2
    flips_per_round = 2 if full else 1
    load = LoadSpec(
        name="bitrot-read", clients=max(8, int(48 * s)), sessions=4,
        rate=1.2, duration=3.0 if full else 1.5,
        objects=24, payload=4096, op_deadline=25.0,
        osds=4, pool_kind="erasure", pool_size=3, pg_num=8,
        ec_profile=(("plugin", "jerasure"),
                    ("technique", "reed_sol_van"),
                    ("k", "2"), ("m", "1")),
        # read-heavy: the verified-read path IS the thing under test
        verbs=(("write", 2.0), ("read", 6.0), ("append", 0.5)))
    events = tuple(
        ev(r, "bitrot", after_writes=True)
        for r in range(rounds) for _ in range(flips_per_round))
    return {
        # seeded at-rest corruption injected while graft-load reads at
        # rate, the jittered deep scrubber running concurrently: zero
        # wrong-bytes acks, every flip detected + repaired, replayable
        "bitrot-under-load": Scenario(
            name="bitrot-under-load", osds=4, pool_kind="erasure",
            pg_num=8, rounds=rounds,
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            load=load, events=events,
            durability_mode="attempted",
            # the scheduled scrubber runs DURING the load windows (the
            # scrub-concurrent mix) and owns flips the reads miss
            config=(("osd_scrub_interval", 1.0),),
            # scrub BEFORE repair: the scrub invariant's repairing
            # pass owns any flip the run's reads never touched, so the
            # repair invariant judges a fully-swept cluster
            invariants=("durability", "scrub", "repair", "acting",
                        "health", "lockdep"),
            converge_timeout=90.0 if full else 60.0),
        "disk-fill-drain": FillScenario(
            name="disk-fill-drain",
            fill_max_writes=160 if full else 80,
            payload=32768),
    }


# ------------------------------------------------------------ fill-drain


@dataclass(frozen=True)
class FillScenario:
    """Cluster-full acceptance shape: fill to ENOSPC, drain, resume.
    ``device_bytes`` sizes every OSD's enforced MemStore capacity; the
    ratios are the config defaults (full at 95%)."""

    name: str
    osds: int = 3
    pool_size: int = 3
    pg_num: int = 4
    device_bytes: int = 1 << 20
    payload: int = 32768
    fill_max_writes: int = 80
    enospc_needed: int = 3          # distinct ENOSPC rejections to see
    drain_frac: float = 0.75
    post_writes: int = 4
    flag_timeout: float = 20.0
    converge_timeout: float = 60.0
    invariants: Tuple[str, ...] = ("durability", "acting", "health",
                                   "lockdep")
    config: Tuple[Tuple[str, object], ...] = ()
    store: str = "mem"              # scripts/chaos.py tmpdir contract
    rounds: int = 1                 # `list` display only


def build_fill_plan(sc: FillScenario, seed: int) -> List[Dict]:
    """The seed-deterministic phase plan (the replay witness): which
    objects the fill writes, in which order the drain deletes.  Actual
    ack/reject splits are runtime outcomes — counters, not plan."""
    rng = stream(seed, "fill")
    oids = [f"fill{i}" for i in range(sc.fill_max_writes)]
    drain = sorted(oids, key=lambda _o: rng.random())
    return [
        {"phase": "fill", "oids": oids, "payload": sc.payload},
        {"phase": "assert_full"},
        {"phase": "drain", "order": drain, "frac": sc.drain_frac},
        {"phase": "assert_clear"},
        {"phase": "resume",
         "oids": [f"post{i}" for i in range(sc.post_writes)]},
    ]


async def run_fill_drain(sc: FillScenario, seed: int,
                         tmpdir: Optional[str] = None) -> Verdict:
    """Boot, fill to the enforced capacity, assert the full-flag
    response, drain, assert clearance + resumed service, judge."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    plan = build_fill_plan(sc, seed)
    wl = stream(seed, "payload")
    cfg = _fast_config()
    cfg.chaos_seed = seed
    cfg.memstore_device_bytes = sc.device_bytes
    cfg.mon_osd_down_out_interval = 120.0
    for k, v in sc.config:
        cfg.set(k, v)
    counters0 = dict(CHAOS.dump()["chaos"])
    cluster = await start_cluster(sc.osds, config=cfg)
    dmn = DaemonInjector(cluster)
    failures: List[str] = []
    stats: Dict[str, int] = {}
    acked: Dict[str, bytes] = {}
    acked_crcs: Dict[str, int] = {}
    loop = asyncio.get_event_loop()

    def _payload(oid: str) -> bytes:
        tag = f"{oid}-{wl.randrange(1 << 30)}-".encode()
        return tag * max(1, sc.payload // len(tag))

    async def _flag(on: bool, timeout: float) -> bool:
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            if ("full" in cluster.mon.osdmap.flags) == on:
                return True
            await asyncio.sleep(0.1)
        return False

    try:
        client = await cluster.client()
        pool = await client.pool_create(
            "fill_drain", "replicated", pg_num=sc.pg_num,
            size=sc.pool_size)
        io = client.ioctx(pool)

        # -- FILL: write until the capacity protection pushes back ----
        enospc = 0
        fill = plan[0]
        for oid in fill["oids"]:
            data = _payload(oid)
            try:
                await io.write_full(oid, data, timeout=20)
            except OSError as e:
                if getattr(e, "errno", None) == 28:
                    enospc += 1
                    if enospc >= sc.enospc_needed:
                        break
                    await asyncio.sleep(0.2)
                    continue
                failures.append(
                    f"fill: {oid} failed with a NON-ENOSPC error "
                    f"under capacity pressure: {e!r}")
                break
            acked[oid] = data
            acked_crcs[oid] = crcmod.crc32c(0xFFFFFFFF, data)
        stats["fill_acked"] = len(acked)
        stats["fill_enospc"] = enospc
        if not enospc:
            failures.append("fill: capacity never pushed back ENOSPC")

        # -- ASSERT FULL: flag committed, health ERR, writes rejected -
        if not await _flag(True, sc.flag_timeout):
            failures.append("full: map flag never raised after ENOSPC")
        else:
            health = cluster.mon._health_data()
            if "OSD_FULL" not in health["checks"] or \
                    health["status"] != "HEALTH_ERR":
                failures.append(f"full: health did not reflect the "
                                f"full state: {health}")
            # a write against the committed flag must reject PROMPTLY
            # with explicit ENOSPC (not burn a timeout)
            t0 = loop.time()
            try:
                await io.write_full("flagged", _payload("flagged"),
                                    timeout=20)
                failures.append("full: write admitted under the flag")
            except OSError as e:
                if getattr(e, "errno", None) != 28:
                    failures.append(f"full: flagged write failed with "
                                    f"{e!r}, want ENOSPC")
                elif loop.time() - t0 > 5.0:
                    failures.append("full: ENOSPC took longer than a "
                                    "prompt rejection should")
        stats["full_rejects"] = sum(
            o.perf.get("osd_full_rejects")
            for o in cluster.osds.values())

        # -- DRAIN: deletes admitted WHILE full; flags clear after ----
        drain = plan[2]
        doomed = [o for o in drain["order"] if o in acked]
        doomed = doomed[: max(1, int(len(doomed) * drain["frac"]))]
        for oid in doomed:
            try:
                await io.remove(oid, timeout=20)
                acked.pop(oid, None)
                acked_crcs.pop(oid, None)
            except (IOError, OSError) as e:
                failures.append(f"drain: delete {oid} refused while "
                                f"full: {e!r} — the cluster cannot "
                                f"dig itself out")
        stats["drained"] = len(doomed)
        if not await _flag(False, sc.flag_timeout):
            failures.append("drain: full flag never cleared after "
                            "space freed")

        # -- RESUME: writes flow again ----------------------------------
        await cluster.wait_for_epoch(cluster.mon.osdmap.epoch,
                                     timeout=10)
        for oid in plan[4]["oids"]:
            data = _payload(oid)
            try:
                await io.write_full(oid, data, timeout=30)
            except (IOError, OSError) as e:
                failures.append(
                    f"resume: {oid} still refused after drain: {e!r}")
                continue
            acked[oid] = data
            acked_crcs[oid] = crcmod.crc32c(0xFFFFFFFF, data)

        # -- heal + converge + judge (the shared seams) ----------------
        await heal_cluster(cluster, dmn)
        await wait_converged(cluster, sc.converge_timeout)
        failures += await judge_invariants(
            cluster, dmn, io, sc.invariants, acked,
            mode="acked", timeout=sc.converge_timeout,
            acked_crcs=acked_crcs)
    finally:
        await cluster.stop()
    counters1 = CHAOS.dump()["chaos"]
    delta = {k: counters1[k] - counters0.get(k, 0) for k in counters1
             if counters1[k] - counters0.get(k, 0)}
    delta.update(stats)
    # the replay key hashes the PLAN (seed-pure), never the runtime
    # ack/reject splits (those ride counters, like chaos Verdicts)
    schedule = [{"round": i, "action": p["phase"],
                 "args": {k: v for k, v in p.items() if k != "phase"}}
                for i, p in enumerate(plan)]
    return Verdict(name=sc.name, seed=seed, schedule=schedule,
                   passed=not failures, failures=failures,
                   acked_objects=len(acked), counters=delta)
