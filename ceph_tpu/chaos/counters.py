"""Process-wide chaos perf counters (the injected-fault telemetry feed).

One shared ``PerfCounters`` registry, like the device-kernel ``KERNELS``
registry in utils/perf.py: every injector increments it, every daemon's
admin socket serves it via ``chaos report``, and bench.py checks it so a
benchmark run that ate injected faults can never masquerade as a clean
number.  ``chaos_total() == 0`` is the machine-checkable form of the
no-op contract: with all injectors disabled, nothing in the hot path
ever reaches an increment.
"""

from __future__ import annotations

from typing import Dict

from ceph_tpu.utils.perf import PerfCounters

CHAOS = PerfCounters("chaos")

for _name, _desc in (
    ("net_drops", "frames dropped on the virtual wire"),
    ("net_dups", "frames duplicated on the virtual wire"),
    ("net_delays", "frames delayed in flight"),
    ("net_reorders", "frames deferred past later traffic"),
    ("net_resets", "sessions force-reset after a send"),
    ("net_partition_blocks", "connect attempts refused by a partition"),
    ("disk_read_errors", "reads failed with injected EIO"),
    ("disk_write_errors", "transactions failed with injected ENOSPC"),
    ("disk_bitrot_flips", "silent bit flips written to stored objects"),
    ("disk_crashes", "stores crash-stopped (journal tail at risk)"),
    ("disk_torn_journals", "journal tails torn mid-frame at crash"),
    ("disk_lost_frames", "committed journal frames discarded at crash"),
    ("daemon_kills", "daemons hard-stopped by the daemon injector"),
    ("daemon_revives", "daemons revived by the daemon injector"),
    ("daemon_restarts", "daemons bounced keeping their store"),
    ("clock_skews", "clock-skew changes applied to a daemon time source"),
    ("net_batch_item_drops",
     "sub-write items dropped INSIDE a delivered batch frame"),
    ("net_batch_ack_dups", "batched-ack result entries duplicated"),
    ("net_batch_ack_reorders", "batched-ack result lists shuffled"),
    ("crash_points_fired",
     "daemons power-cut at an armed tick/commit crash seam"),
    ("interrupt_points_fired",
     "client-library front-door ops cut at an armed interrupt seam"),
    ("interrupt_retries",
     "front-door transactions retried by a 'restarted' client"),
    ("mds_crash_points_fired",
     "MDS daemons crashed at an armed journal/replay seam"),
):
    CHAOS.add_u64(_name, desc=_desc)


def chaos_total() -> int:
    """Sum of every chaos counter — 0 proves no injector ever fired."""
    return sum(CHAOS.dump()["chaos"].values())


def chaos_report(config=None) -> Dict:
    """The ``chaos report`` admin-command payload: global fault counters
    plus this daemon's active chaos options (config-driven injectors are
    fully described by their chaos_* values)."""
    opts = {}
    if config is not None:
        opts = {k: v for k, v in config.show().items()
                if k.startswith("chaos_")}
    active = any(v for k, v in opts.items() if k != "chaos_seed")
    return {"counters": CHAOS.dump()["chaos"], "options": opts,
            "active": active}
