"""Declarative chaos scenarios: seeded fault schedules over a workload.

The teuthology-thrasher analog, made deterministic: a ``Scenario``
declares a cluster shape, a write workload, a list of fault ``Event``s
pinned to workload rounds, and the invariants that must hold after
convergence.  ``build_schedule(scenario, seed)`` resolves every random
choice (victims, partition halves, skew magnitudes) from the seed's
``schedule`` stream — so the same ``--seed`` produces a bit-identical
fault schedule, and a failure run replays exactly.

Run shape::

    for each round:            # rounds interleave workload and faults
        apply this round's events (mid-write events race a write burst)
        write the round's objects, recording acked payload + crc
        snapshot (optional)
    heal everything            # zero rates, drop partitions, revive dead
    wait for convergence       # all OSDs up, epoch settled
    check invariants           # chaos/invariants.py
    -> Verdict

Event actions:

====================  ======================================================
``kill_osd``          hard-stop an OSD (store lost, like a dead host)
``crash_osd``         power-cut stop; FileStore/BlueStore may tear or lose
                      the journal tail (``torn_tail`` / ``lose_frames``)
``revive_osd``        bring a downed OSD back (crash victims keep their
                      store and replay; kill victims boot empty)
``restart_osd``       bounce keeping the store (delta-resync via pg log)
``net``               set chaos_net_* rates on the target daemon(s)
``disk``              set chaos_disk_* rates on the target daemon(s)
``clock_skew``        skew the target daemon's time source (seconds)
``partition``         split the OSDs into two halves (or explicit sides)
``heal_partition``    drop every partition edge
``bitrot``            flip one stored bit of one acked object replica
``kill_mon``          hard-stop a monitor (default target: the current
                      Paxos leader, resolved at apply time)
``revive_mon``        restart a killed monitor rank (rejoins elections,
                      catches up through collect + map subscription)
====================  ======================================================

Targets: ``osd.N`` / ``mon.N`` pin a daemon; ``random_osd`` resolves
from the schedule stream (never dropping live OSDs below the pool
size); ``random_down_osd`` picks a dead one; ``all_osds`` / ``cluster``
fan out.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.chaos import invariants as inv
from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.daemons import (
    DaemonInjector,
    heal_partitions,
    partition,
    zero_rates,
)
from ceph_tpu.chaos.disk import DiskInjector
from ceph_tpu.chaos.rng import stream
from ceph_tpu.ops import crc32c as crcmod


@dataclass(frozen=True)
class Event:
    round: int
    action: str
    target: str = "random_osd"
    args: Tuple[Tuple[str, object], ...] = ()
    during_writes: bool = False
    # apply AFTER the round's writes land (corruption events: a
    # pre-write bitrot on a reused oid would just be overwritten, and
    # the scrub invariant would pass without ever seeing a flipped bit)
    after_writes: bool = False

    def arg(self, key: str, default=None):
        return dict(self.args).get(key, default)


def ev(round: int, action: str, target: str = "random_osd",
       during_writes: bool = False, after_writes: bool = False,
       **args) -> Event:
    """Sugar: ``ev(1, "crash_osd", torn_tail=True)``."""
    return Event(round=round, action=action, target=target,
                 during_writes=during_writes, after_writes=after_writes,
                 args=tuple(sorted(args.items())))


@dataclass(frozen=True)
class Scenario:
    name: str
    osds: int = 3
    pool_kind: str = "replicated"            # "replicated" | "erasure"
    pool_size: int = 3
    pg_num: int = 8
    ec_profile: Optional[Tuple[Tuple[str, str], ...]] = None
    rounds: int = 3
    objects_per_round: int = 6
    payload_repeat: int = 60
    snapshots: bool = False
    events: Tuple[Event, ...] = ()
    invariants: Tuple[str, ...] = ("durability", "acting", "health",
                                   "lockdep")
    durability_mode: str = "acked"           # "acked" | "attempted"
    store: str = "mem"                       # "mem" | "file" | "blue"
    config: Tuple[Tuple[str, object], ...] = ()
    write_timeout: float = 60.0
    converge_timeout: float = 60.0
    # overload workload shape (round 10): "zipf" picks oids from a
    # zipfian hot-object distribution (same oid may be written
    # concurrently — use durability_mode="attempted"); burst_concurrency
    # fires every round's writes concurrently (an offered-load burst
    # against the admission budget); op_deadline bounds each write's
    # client budget (> 0 arms the "deadline" invariant's bookkeeping:
    # an ack arriving after its deadline is a failure)
    workload: str = "seq"                    # "seq" | "zipf"
    burst_concurrency: int = 0
    op_deadline: float = 0.0
    # control-plane storm shape (round 14): a Paxos mon quorum, rounds
    # driven by the graft-load open-loop driver instead of the put loop
    # (``load`` is a LoadSpec; one drive() window per round, mid-round
    # events race the in-flight traffic), and two judged gates —
    # bounded time-to-HEALTH_OK after heal and a floor on map epochs/s
    # generated while the storm ran (0 = gate off)
    mons: int = 1
    load: Optional[object] = None            # ceph_tpu.load LoadSpec
    health_ok_bound: float = 0.0
    epochs_floor: float = 0.0


@dataclass
class Verdict:
    name: str
    seed: int
    schedule: List[Dict]
    passed: bool
    failures: List[str]
    acked_objects: int = 0
    counters: Dict[str, int] = field(default_factory=dict)
    # artifact traceability (round 17): observed-vs-threshold rows for
    # the judged gates and the graft-blackbox bundle path a conviction
    # triggered.  Excluded from replay_key like counters: gate VALUES
    # are wire-level timings, and the bundle path only exists when the
    # recorder is on.
    gates: List[Dict] = field(default_factory=list)
    postmortem: Optional[str] = None

    def replay_key(self) -> Tuple:
        """The parts of a verdict that must be identical across two runs
        of the same seed: the resolved fault schedule and the outcome.
        (Raw counters are wire-level and vary with async timing.)"""
        sched = tuple(tuple(sorted(e.items())) for e in self.schedule)
        return (self.name, self.seed, sched, self.passed,
                tuple(sorted(self.failures)))

    def as_dict(self) -> Dict:
        return {"name": self.name, "seed": self.seed,
                "passed": self.passed, "failures": self.failures,
                "acked_objects": self.acked_objects,
                "schedule": self.schedule, "counters": self.counters,
                "gates": self.gates, "postmortem": self.postmortem}


# --------------------------------------------------------------- schedule


def build_schedule(scenario: Scenario, seed: int) -> List[Dict]:
    """Resolve every event to a concrete, seed-deterministic plan.
    Victim picks track which OSDs the plan has already killed so a
    ``revive_osd`` targets an actually-dead daemon and kills never plan
    to drop live OSDs below the pool size."""
    rng = stream(seed, "schedule")
    alive = set(range(scenario.osds))
    dead: List[int] = []
    plan: List[Dict] = []
    for i, e in enumerate(sorted(scenario.events,
                                 key=lambda e: (e.round,))):
        entry: Dict = {"round": e.round, "action": e.action,
                       "during_writes": e.during_writes,
                       "after_writes": e.after_writes,
                       "args": dict(e.args)}
        target = e.target
        if e.action == "crash_point":
            # arm a named tick/commit crash seam on one daemon: it
            # power-cuts itself when its write path next passes the
            # point.  Planned as a probable kill (floor bookkeeping);
            # the skip count resolves from the seeded stream so WHICH
            # traversal dies replays bit-identically.
            #
            # Round 15: "client" targets arm a LIBRARY interrupt seam
            # (no daemon dies — the front-door op unwinds and the
            # workload's retry models a restarted application) and
            # "mds.N" targets crash that MDS rank (restarted by the
            # front-door babysitter, never an OSD) — neither touches
            # the OSD alive/dead bookkeeping.
            if target == "client" or target.startswith("mds"):
                if entry["args"].get("at") is None:
                    entry["args"]["at"] = rng.randrange(0, 3)
                entry["target"] = target
                entry["seq"] = i
                plan.append(entry)
                continue
            if target == "random_osd":
                pool = sorted(alive)
                if len(pool) <= scenario.pool_size:
                    continue
                target = f"osd.{rng.choice(pool)}"
            if entry["args"].get("at") is None:
                entry["args"]["at"] = rng.randrange(0, 3)
            osd_id = int(target.split(".")[1])
            alive.discard(osd_id)
            dead.append(osd_id)
        elif e.action in ("kill_osd", "crash_osd", "restart_osd"):
            if target == "random_osd":
                floor = scenario.pool_size if e.action != "restart_osd" \
                    else 1
                pool = sorted(alive)
                if e.action != "restart_osd" and len(pool) <= floor:
                    continue            # plan refuses to wedge the pool
                target = f"osd.{rng.choice(pool)}"
            osd_id = int(target.split(".")[1])
            if e.action != "restart_osd":
                alive.discard(osd_id)
                dead.append(osd_id)
        elif e.action == "revive_osd":
            if target in ("random_osd", "random_down_osd"):
                if not dead:
                    continue
                target = f"osd.{rng.choice(sorted(dead))}"
            osd_id = int(target.split(".")[1])
            if osd_id in dead:
                dead.remove(osd_id)
            alive.add(osd_id)
        elif e.action == "partition":
            if not e.arg("a"):
                half = sorted(rng.sample(sorted(alive),
                                         max(1, len(alive) // 2)))
                rest = sorted(alive - set(half))
                entry["args"]["a"] = [f"osd.{o}" for o in half]
                entry["args"]["b"] = [f"osd.{o}" for o in rest]
            target = "cluster"
        elif e.action == "clock_skew":
            if target == "random_osd":
                target = f"osd.{rng.choice(sorted(alive))}"
            if entry["args"].get("skew") is None:
                entry["args"]["skew"] = round(rng.uniform(-2.0, 2.0), 3)
        elif e.action in ("net", "disk"):
            if target == "random_osd":
                target = f"osd.{rng.choice(sorted(alive))}"
        elif e.action == "bitrot":
            # victim object/osd resolve at apply time (needs the live
            # acked set); the pick still comes from the seeded stream
            target = target if target != "random_osd" else "runtime"
        elif e.action == "kill_mon":
            # the victim is WHOEVER leads at apply time (killing a
            # follower proves nothing): symbolic target, runtime
            # resolution — the plan itself stays bit-identical
            if target == "random_osd":
                target = "mon_leader"
        elif e.action == "revive_mon":
            if target == "random_osd":
                target = "mon_down"
        entry["target"] = target
        entry["seq"] = i
        plan.append(entry)
    return plan


# --------------------------------------------------------------- running


def _payload(rng, oid: str, gen: int, repeat: int) -> bytes:
    tag = f"{oid}-g{gen}-{rng.randrange(1 << 30)}-"
    return tag.encode() * repeat


# the one seeded zipfian sampler lives in load/dist.py (round 13);
# same stream consumption (one rng.random() per pick), so seeded
# scenarios recorded before the move replay bit-identically
from ceph_tpu.load.dist import zipf_pick as _zipf_pick  # noqa: E402


def _store_factory(scenario: Scenario, tmpdir: Optional[str]):
    if scenario.store == "mem":
        return None
    import os

    from ceph_tpu.cluster.bluestore import BlueStore
    from ceph_tpu.cluster.filestore import FileStore

    # honor a scenario-configured capacity on file stores too, so the
    # round-16 enforcement doesn't silently diverge by store backend
    cap = int(dict(scenario.config).get("memstore_device_bytes",
                                        1 << 30))

    def factory(osd_id: int):
        path = os.path.join(tmpdir, f"osd{osd_id}")
        if scenario.store == "file":
            return FileStore(path, checkpoint_every=64,
                             device_bytes=cap)
        return BlueStore(path, size=64 << 20, checkpoint_every=64)

    return factory


async def heal_cluster(cluster, dmn: DaemonInjector) -> None:
    """Fault-free the cluster before judging: crash-point teardowns
    still in flight must finish first (or the revive sweep races a
    daemon mid-power-cut), every injector rate zeroes, dead monitors
    rejoin the quorum (OSDs must boot against a healthy mon), and the
    dead OSDs revive with whatever durable store survived them.  Shared
    with the graft-load soak runner — one heal sequence, not two."""
    await cluster.drain_chaos()
    zero_rates(cluster)
    if len(cluster.mons) > 1:
        for m_ in list(cluster.mons):
            if m_.stopped:
                await cluster.revive_mon(m_.rank)
        await cluster.wait_for_leader()
    for osd_id in sorted(set(cluster.osd_configs) - set(cluster.osds)):
        await dmn.revive_osd(osd_id,
                             with_store=osd_id in cluster.osd_stores)


async def judge_invariants(cluster, dmn: DaemonInjector, io,
                           invariants, acked: Dict[str, bytes],
                           attempted: Optional[Dict[str, set]] = None,
                           mode: str = "acked", timeout: float = 60.0,
                           acked_crcs: Optional[Dict[str, int]] = None,
                           snaps: Optional[Dict] = None,
                           deadline_misses: Optional[List[str]] = None,
                           frontdoor=None,
                           ) -> List[str]:
    """THE invariant dispatch table, shared by chaos scenarios,
    graft-load soaks, and front-door scenarios (an invariant added here
    is immediately nameable from all three; a run naming one this table
    lacks fails loudly).  ``frontdoor`` is the application-level
    bookkeeping a FrontdoorState carries (chaos/frontdoor.py) — the
    snapshot/multipart/namespace invariants judge against it."""
    failures: List[str] = []
    for name in invariants:
        if name == "durability":
            failures += await inv.check_durability(
                io, acked, attempted=attempted, mode=mode,
                acked_crcs=acked_crcs, timeout=timeout)
        elif name == "health":
            failures += await inv.check_health(cluster, timeout=timeout)
        elif name == "acting":
            failures += await inv.check_acting(cluster, timeout=timeout)
        elif name == "snapshots":
            failures += await inv.check_snapshots(io, snaps or {},
                                                  timeout=timeout)
        elif name == "scrub":
            failures += await inv.check_scrub(cluster,
                                              timeout=timeout * 1.5)
        elif name == "lockdep":
            failures += inv.check_lockdep()
        elif name == "deadline":
            # recorded inline by the workload driver: every ack past
            # its client deadline is one failure line
            failures += list(deadline_misses or ())
        elif name == "shed":
            failures += inv.check_shed(cluster)
        elif name == "repair":
            failures += await inv.check_repair(cluster, timeout=timeout)
        elif name == "frontier":
            failures += await inv.check_frontier(
                cluster, marks=dmn.frontier_marks, timeout=timeout)
        elif name == "batch":
            failures += inv.check_batch(cluster)
        elif name in ("snapshot", "multipart", "namespace"):
            if frontdoor is None:
                failures.append(f"{name}: invariant requires a "
                                f"front-door workload context")
            elif name == "snapshot":
                failures += await inv.check_snapshot(frontdoor,
                                                     timeout=timeout)
            elif name == "multipart":
                failures += await inv.check_multipart(frontdoor,
                                                      timeout=timeout)
            else:
                failures += await inv.check_namespace(frontdoor,
                                                      timeout=timeout)
        else:
            failures.append(f"unknown invariant {name!r}")
    return failures


async def run_scenario(scenario: Scenario, seed: int,
                       tmpdir: Optional[str] = None) -> Verdict:
    """Boot, thrash, heal, converge, judge.  Pure asyncio — callers
    wrap with ``asyncio.run`` (or the CLI does)."""
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    schedule = build_schedule(scenario, seed)
    wl = stream(seed, "workload")
    rot = stream(seed, "bitrot")
    cfg = _fast_config()
    cfg.mon_osd_down_out_interval = 120.0    # scenarios bounce, not drain
    cfg.chaos_seed = seed
    for k, v in scenario.config:
        cfg.set(k, v)
    counters0 = dict(CHAOS.dump()["chaos"])
    cluster = await start_cluster(
        scenario.osds, config=cfg, n_mons=scenario.mons,
        with_mgr=scenario.load is not None,
        store_factory=_store_factory(scenario, tmpdir))
    dmn = DaemonInjector(cluster)
    acked: Dict[str, bytes] = {}
    acked_crcs: Dict[str, int] = {}
    attempted: Dict[str, set] = {}
    snaps: Dict[int, Dict[str, bytes]] = {}
    failures: List[str] = []
    gate_stats: Dict[str, int] = {}
    gate_rows: List[Dict] = []
    postmortem_path: Optional[str] = None
    ctx = None
    try:
        if scenario.load is not None:
            # storm scenarios (round 14): traffic comes from the
            # graft-load open-loop driver — one drive() window per
            # round, the soak composition inverted into the chaos
            # runner so scripts/chaos.py owns the storm library
            from ceph_tpu.load.driver import LoadContext

            ctx = await LoadContext.create(scenario.load, seed,
                                           cluster=cluster)
            client = ctx.sessions[0]
            pool = ctx.pool
            io = ctx.io(0)
        else:
            client = await cluster.client()
            if scenario.pool_kind == "erasure":
                pool = await client.pool_create(
                    f"chaos_{scenario.name}"[:24], "erasure",
                    pg_num=scenario.pg_num,
                    ec_profile=dict(scenario.ec_profile or ()))
            else:
                pool = await client.pool_create(
                    f"chaos_{scenario.name}"[:24], "replicated",
                    pg_num=scenario.pg_num, size=scenario.pool_size)
            io = client.ioctx(pool)

        deadline_misses: List[str] = []
        loop = asyncio.get_event_loop()
        storm_t0 = loop.time()
        storm_epoch0 = cluster.mon.osdmap.epoch

        async def put(i: int, gen: int, timeout: float) -> None:
            if scenario.workload == "zipf":
                # zipfian hot objects: concurrent writers may race on
                # one oid — attempted-mode durability judges those
                oid = f"obj{_zipf_pick(wl, scenario.objects_per_round)}"
            else:
                oid = f"obj{i}"
            data = _payload(wl, oid, gen, scenario.payload_repeat)
            attempted.setdefault(oid, set()).add(data)
            t0 = loop.time()
            try:
                await io.write_full(oid, data, timeout=timeout)
            except (IOError, OSError, TimeoutError):
                return
            if scenario.op_deadline:
                elapsed = loop.time() - t0
                if elapsed > timeout + 0.25:
                    # the zero-acked-but-expired acceptance criterion:
                    # an ack arriving after the client's deadline means
                    # deadline shedding failed somewhere in the stack
                    deadline_misses.append(
                        f"deadline: {oid} acked {elapsed:.2f}s after "
                        f"submit, past its {timeout}s deadline")
            acked[oid] = data
            acked_crcs[oid] = crcmod.crc32c(0xFFFFFFFF, data)

        put_timeout = scenario.op_deadline or scenario.write_timeout
        for rnd in range(scenario.rounds):
            evs = [e for e in schedule if e["round"] == rnd]
            for e in [e for e in evs if not e["during_writes"]
                      and not e.get("after_writes")]:
                await _apply_event(cluster, dmn, client, io, e, rot,
                                   acked, pool)
            mid = [e for e in evs if e["during_writes"]]
            if scenario.load is not None:
                from ceph_tpu.load.driver import build_plan, drive

                plan = build_plan(scenario.load, seed + rnd * 1000003)
                window = loop.create_task(
                    drive(ctx, scenario.load, seed, plan=plan,
                          record_acked=True))
                try:
                    if mid:
                        await asyncio.sleep(0.15 + wl.random() * 0.2)
                        for e in mid:
                            await _apply_event(cluster, dmn, client, io,
                                               e, rot, acked, pool)
                            # staggered AND overlapping: a seeded beat
                            # between storm events so each bounce races
                            # the previous one's re-peering, all under
                            # the in-flight load window
                            await asyncio.sleep(wl.random() * 0.25)
                    result = await window
                except BaseException:
                    # a failed mid-round injection must not orphan the
                    # in-flight load window (the soak rule)
                    window.cancel()
                    try:
                        await window
                    except (asyncio.CancelledError, Exception):
                        pass
                    raise
                deadline_misses += result.late_acks
                for oid, data in result.acked.items():
                    acked[oid] = data
                    acked_crcs[oid] = crcmod.crc32c(0xFFFFFFFF, data)
                for oid, tries in result.attempted.items():
                    attempted.setdefault(oid, set()).update(tries)
            elif mid:
                burst = asyncio.gather(
                    *[put(i, rnd,
                          timeout=scenario.op_deadline or 20.0)
                      for i in range(scenario.objects_per_round)],
                    return_exceptions=True)
                await asyncio.sleep(wl.random() * 0.05)
                for e in mid:
                    await _apply_event(cluster, dmn, client, io, e, rot,
                                       acked, pool)
                for r in await burst:
                    # put() absorbs expected I/O failures itself —
                    # anything else escaping a racing write is a
                    # runner bug and must surface, not vanish
                    if isinstance(r, BaseException) and \
                            not isinstance(r, asyncio.CancelledError):
                        raise r
            elif scenario.burst_concurrency:
                # offered-load burst bounded at burst_concurrency
                # in-flight writes — the overload regime the admission
                # budget absorbs
                gate = asyncio.Semaphore(scenario.burst_concurrency)

                async def bounded_put(i, gen):
                    async with gate:
                        await put(i, gen, timeout=put_timeout)

                burst_res = await asyncio.gather(
                    *[bounded_put(i, rnd)
                      for i in range(scenario.objects_per_round)],
                    return_exceptions=True)
                for r in burst_res:
                    # put() absorbs expected I/O failures itself —
                    # anything else is a runner bug and must surface
                    if isinstance(r, BaseException) and \
                            not isinstance(r, asyncio.CancelledError):
                        raise r
            else:
                for i in range(scenario.objects_per_round):
                    await put(i, rnd, timeout=put_timeout)
            for e in [e for e in evs if e.get("after_writes")]:
                await _apply_event(cluster, dmn, client, io, e, rot,
                                   acked, pool)
            if scenario.snapshots:
                sid = await io.snap_create(f"chaos_s{rnd}")
                snaps[sid] = dict(acked)

        # -- storm gates (round 14): epochs/s generated while the fault
        #    schedule ran — a churn burst the control plane cannot keep
        #    up with shows as a collapsed rate (coalescing keeps the
        #    COUNT low by design, so the floor judges rate, not count)
        storm_wall = max(1e-6, loop.time() - storm_t0)
        epochs_generated = cluster.mon.osdmap.epoch - storm_epoch0
        gate_stats["epochs_generated"] = epochs_generated
        gate_stats["storm_wall_ms"] = int(storm_wall * 1000)
        if scenario.epochs_floor > 0:
            rate = epochs_generated / storm_wall
            gate_rows.append({"gate": "epochs",
                              "value": round(rate, 3),
                              "threshold": scenario.epochs_floor,
                              "passed": rate >= scenario.epochs_floor})
            if rate < scenario.epochs_floor:
                failures.append(
                    f"epochs: {epochs_generated} epochs in "
                    f"{storm_wall:.1f}s = {rate:.2f}/s < floor "
                    f"{scenario.epochs_floor}/s")

        # -- heal + converge + judge (shared with graft-load soak) ------
        await heal_cluster(cluster, dmn)
        heal_t0 = loop.time()
        await _converge(cluster, scenario.converge_timeout)
        if scenario.health_ok_bound > 0:
            # bounded time-to-HEALTH_OK measured from the heal point:
            # the cluster must not merely converge eventually, it must
            # converge in bounded time after the storm stops
            ok_deadline = heal_t0 + max(scenario.converge_timeout,
                                        scenario.health_ok_bound)
            health_ok_s = None
            while loop.time() < ok_deadline:
                if cluster.mon._health_data()["status"] == "HEALTH_OK":
                    health_ok_s = loop.time() - heal_t0
                    break
                await asyncio.sleep(0.2)
            gate_rows.append(
                {"gate": "health_time",
                 "value": None if health_ok_s is None
                 else round(health_ok_s, 3),
                 "threshold": scenario.health_ok_bound,
                 "passed": health_ok_s is not None
                 and health_ok_s <= scenario.health_ok_bound})
            if health_ok_s is None:
                failures.append(
                    f"health_time: no HEALTH_OK within "
                    f"{ok_deadline - heal_t0:.0f}s of heal")
            else:
                gate_stats["health_ok_ms"] = int(health_ok_s * 1000)
                if health_ok_s > scenario.health_ok_bound:
                    failures.append(
                        f"health_time: HEALTH_OK took "
                        f"{health_ok_s:.1f}s > bound "
                        f"{scenario.health_ok_bound}s")
        inv_failures = await judge_invariants(
            cluster, dmn, io, scenario.invariants, acked,
            attempted=attempted, mode=scenario.durability_mode,
            timeout=scenario.converge_timeout, acked_crcs=acked_crcs,
            snaps=snaps, deadline_misses=deadline_misses)
        failures += inv_failures
        gate_rows.append({"gate": "invariants",
                          "value": len(inv_failures), "threshold": 0,
                          "passed": not inv_failures})
        if failures and getattr(cfg, "blackbox_enabled", 0):
            # graft-blackbox: a convicted scenario triggers a bundle
            # BEFORE teardown, while the breach evidence is still in
            # the daemons' rings.  The reason carries only the failure
            # HEAD (the gate/invariant name): the full failure strings
            # embed wall timings and live in the detail — the reason
            # feeds replay_key, which must be bit-identical across two
            # runs of one seed
            pm_rec = await cluster.blackbox_trigger(
                "chaos_conviction",
                f"scenario {scenario.name} seed={seed} convicted: "
                f"{failures[0].split(':', 1)[0]}",
                detail={"scenario": scenario.name, "seed": seed,
                        "gates": [g for g in gate_rows
                                  if not g["passed"]],
                        "failures": list(failures)},
                clients=(ctx.sessions if ctx is not None else ()))
            postmortem_path = (pm_rec or {}).get("path")
    finally:
        if ctx is not None:
            await ctx.close()  # no-op: the scenario owns the cluster
        await cluster.stop()
    counters1 = CHAOS.dump()["chaos"]
    delta = {k: counters1[k] - counters0.get(k, 0) for k in counters1
             if counters1[k] - counters0.get(k, 0)}
    delta.update(gate_stats)
    return Verdict(name=scenario.name, seed=seed, schedule=schedule,
                   passed=not failures, failures=failures,
                   acked_objects=len(acked), counters=delta,
                   gates=gate_rows, postmortem=postmortem_path)


async def _apply_event(cluster, dmn: DaemonInjector, client, io,
                       e: Dict, rot, acked: Dict[str, bytes],
                       pool: int) -> None:
    action, target, args = e["action"], e["target"], e["args"]
    if action == "kill_osd":
        osd_id = int(target.split(".")[1])
        if osd_id in cluster.osds:
            await dmn.kill_osd(osd_id)
    elif action == "crash_osd":
        osd_id = int(target.split(".")[1])
        if osd_id in cluster.osds:
            await dmn.crash_osd(osd_id,
                                torn_tail=bool(args.get("torn_tail")),
                                lose_frames=int(args.get("lose_frames",
                                                         0)))
    elif action == "revive_osd":
        osd_id = int(target.split(".")[1])
        if osd_id not in cluster.osds:
            await dmn.revive_osd(
                osd_id, with_store=osd_id in cluster.osd_stores)
    elif action == "restart_osd":
        osd_id = int(target.split(".")[1])
        if osd_id in cluster.osds:
            await dmn.restart_osd(osd_id)
    elif action == "crash_point":
        for cfg in _target_configs(cluster, target):
            cfg.injectargs({
                "chaos_crash_point": args["point"],
                "chaos_crash_point_skip": int(args.get("at", 0))})
    elif action in ("net", "disk"):
        for cfg in _target_configs(cluster, target):
            cfg.injectargs({k: v for k, v in args.items()
                            if k.startswith("chaos_")})
    elif action == "clock_skew":
        for cfg in _target_configs(cluster, target):
            cfg.injectargs({"chaos_clock_skew": args["skew"]})
    elif action == "crash_mds":
        # power-cut an MDS rank (round 15): its journal + dirfrags live
        # in RADOS; the restarted rank's boot replay is the recovery
        rank = int(target.split(".")[1]) if "." in target else 0
        if (cluster.mdss or {}).get(rank) is not None:
            await cluster.crash_mds(rank)
            CHAOS.inc("daemon_kills")
    elif action == "revive_mds":
        rank = int(target.split(".")[1]) if "." in target else 0
        pools = cluster.mds_pools.get(rank)
        if pools is not None and (cluster.mdss or {}).get(rank) is None:
            await cluster.start_mds(pools[0], pools[1], rank=rank)
            CHAOS.inc("daemon_revives")
    elif action == "kill_mon":
        rank = None
        if target == "mon_leader":
            rank = next((m_.rank for m_ in cluster.mons
                         if m_.is_leader), None)
        else:
            rank = int(target.split(".")[1])
        if rank is not None and not cluster.mons[rank].stopped:
            await dmn.kill_mon(rank)
    elif action == "revive_mon":
        if target == "mon_down":
            rank = next((m_.rank for m_ in cluster.mons
                         if m_.stopped), None)
        else:
            rank = int(target.split(".")[1])
        if rank is not None and cluster.mons[rank].stopped:
            await cluster.revive_mon(rank)
    elif action == "partition":
        partition(cluster, list(args["a"]), list(args["b"]),
                  symmetric=bool(args.get("symmetric", True)))
    elif action == "heal_partition":
        heal_partitions(cluster)
    elif action == "bitrot":
        await _apply_bitrot(cluster, client, e, rot, acked, pool)
    else:
        raise ValueError(f"unknown chaos action {action!r}")


def _target_configs(cluster, target: str):
    if target in ("all_osds", "cluster"):
        for o in cluster.osds.values():
            yield o.config
        if target == "cluster":
            for m in cluster.mons:
                yield m.config
    elif target.startswith("osd."):
        osd = cluster.osds.get(int(target.split(".")[1]))
        if osd is not None:
            yield osd.config
    elif target.startswith("mds"):
        _, _, num = target.partition(".")
        daemon = (cluster.mdss or {}).get(int(num) if num else 0)
        if daemon is not None:
            yield daemon.config
    elif target.startswith("mon"):
        _, _, num = target.partition(".")
        rank = int(num) if num else 0
        if rank < len(cluster.mons):
            yield cluster.mons[rank].config
    elif target == "client":
        for c in cluster.clients:
            yield c.objecter.config


async def _apply_bitrot(cluster, client, e: Dict, rot,
                        acked: Dict[str, bytes], pool: int) -> None:
    """Flip one bit of one acked object on one acting member, straight
    into the store behind the OSD's back — silent corruption that only
    scrub (or a csum-verifying read) can see."""
    if not acked:
        return
    oid = rot.choice(sorted(acked))
    pgid = client.objecter.object_pgid(pool, oid)
    coll = f"pg_{pgid.pool}_{pgid.seed}"
    _, _, acting, _ = client.objecter.osdmap.pg_to_up_acting_osds(pgid)
    live = [o for o in acting if o >= 0 and o in cluster.osds]
    if not live:
        return
    victim = rot.choice(live)
    inj = DiskInjector(rot)
    try:
        inj.flip_bit(cluster.osds[victim].store, coll, oid,
                     bit=e["args"].get("bit"))
    except (FileNotFoundError, ValueError):
        pass


async def _converge(cluster, timeout: float) -> None:
    """All OSDs up in the mon map and every daemon caught up to the
    epoch (best-effort: invariants do the real judging)."""
    deadline = asyncio.get_event_loop().time() + timeout
    n = cluster.mon.osdmap.max_osd
    while asyncio.get_event_loop().time() < deadline:
        if all(cluster.mon.osdmap.osd_up[o] for o in range(n)):
            break
        await asyncio.sleep(0.1)
    try:
        await cluster.wait_for_epoch(cluster.mon.osdmap.epoch,
                                     timeout=max(
                                         1.0, deadline -
                                         asyncio.get_event_loop().time()))
    except TimeoutError:
        pass


# public seams for graft-load soak composition (round 13): the soak
# runner applies the SAME resolved fault plans through the same
# machinery, so "load + chaos" is composition, not reimplementation
apply_event = _apply_event
wait_converged = _converge
store_factory_for = _store_factory


# --------------------------------------------------------------- builtins


def storm_scenarios(scale: float = 1.0) -> Dict[str, Scenario]:
    """The round-14 control-plane storm library, sized by ``scale``.

    1.0 is the full acceptance shape (slow: hundreds of OSD bounces /
    a Paxos leader killed mid-epoch-burst, both under sustained
    graft-load traffic); ``scripts/chaos.py --scale`` and the tier-1
    smoke tests run a small fraction of it on the same code paths.
    Bounces are staggered (seeded beats between events) AND overlapping
    (mid-window, racing the load driver's in-flight traffic and each
    other's re-peering).  The gates: bounded time-to-HEALTH_OK after
    heal, and an epochs/s floor while the storm ran — the full-size
    floor is real; scaled runs keep a token floor (wall time on the
    load-sensitive bench host would make a tight scaled floor flappy,
    BENCH_NOTES round 14)."""
    from ceph_tpu.load.driver import LoadSpec

    s = max(0.03, min(1.0, scale))
    full = s >= 1.0
    bounces = max(4, int(round(100 * s)))
    osds = max(5, min(12, int(round(12 * s))))
    rounds = max(2, min(10, (bounces + 11) // 12))
    per = [bounces // rounds + (1 if r < bounces % rounds else 0)
           for r in range(rounds)]
    rr_events = tuple(
        ev(r, "restart_osd", during_writes=bool(i % 2))
        for r, n in enumerate(per) for i in range(n))
    rr_load = LoadSpec(
        name="rr100", clients=max(8, int(48 * s)), sessions=4,
        rate=1.0, duration=2.0, objects=24, payload=1024,
        op_deadline=20.0, osds=osds, pg_num=16, store="file",
        verbs=(("write", 4.0), ("read", 3.0), ("append", 1.0)))
    mb_rounds = 4 if full else 3
    mb_osds = max(5, min(8, int(round(8 * s))))
    mb_events = tuple(
        ev(r, "restart_osd", during_writes=True)
        for r in range(mb_rounds)
        for _ in range(max(1, int(round(3 * s))))
    ) + (
        # the leader dies MID-epoch-burst (during_writes, while the
        # round's restarts are churning map epochs through Paxos)
        ev(1, "kill_mon", target="mon_leader", during_writes=True),
        ev(min(2, mb_rounds - 1), "revive_mon", target="mon_down"),
    )
    mb_load = LoadSpec(
        name="monbounce", clients=max(8, int(32 * s)), sessions=4,
        rate=1.0, duration=2.0, objects=24, payload=1024,
        op_deadline=20.0, osds=mb_osds, pg_num=16, store="file",
        verbs=(("write", 4.0), ("read", 3.0), ("append", 1.0)))
    common = dict(
        pool_size=3, pg_num=16, store="file",
        durability_mode="attempted",
        invariants=("durability", "frontier", "acting", "health",
                    "lockdep"),
        # storms outlive the default 120s down-out window; a bounced
        # OSD must never be auto-outed before its own revive
        config=(("mon_osd_down_out_interval", 600.0),),
        # the full-size bound sits ABOVE the worst client-budget tail:
        # an op admitted just before heal may legitimately retry to the
        # 90s rados budget, holding SLOW_OPS (and so HEALTH_WARN) that
        # long — 180s = budget tail + markdown/boot margin on the
        # load-sensitive host (measured 112s; BENCH_NOTES round 14)
        health_ok_bound=180.0 if full else 60.0,
        epochs_floor=0.3 if full else 0.02,
        write_timeout=60.0,
        converge_timeout=180.0 if full else 90.0)
    return {
        # hundreds of staggered+overlapping OSD bounces under sustained
        # load-driver traffic (ROADMAP item 4's acceptance shape)
        "rolling-restart-100": Scenario(
            name="rolling-restart-100", osds=osds, rounds=rounds,
            load=rr_load, events=rr_events, **common),
        # Paxos leader killed mid-epoch-burst while OSD churn keeps the
        # map service hot; the quorum must fail over, keep marking
        # downs/ups, and converge in bounded time
        "mon-bounce-under-churn": Scenario(
            name="mon-bounce-under-churn", osds=mb_osds, mons=3,
            rounds=mb_rounds, load=mb_load, events=mb_events, **common),
    }


def builtin_scenarios() -> Dict[str, Scenario]:
    """The shipped scenario library (scripts/chaos.py `list`)."""
    out = storm_scenarios(1.0)
    out.update(_base_scenarios())
    return out


def _base_scenarios() -> Dict[str, Scenario]:
    return {
        # tier-1 smoke: one OSD killed and revived under 10% drop
        "smoke": Scenario(
            name="smoke", osds=4, pool_size=3, pg_num=4, rounds=2,
            objects_per_round=4, payload_repeat=20,
            events=(
                ev(0, "net", target="all_osds", chaos_net_drop=0.10),
                ev(0, "kill_osd"),
                ev(1, "revive_osd"),
            ),
            invariants=("durability", "acting", "health", "lockdep"),
            converge_timeout=45.0),
        # the acceptance gate: partition + kill + torn-write journal
        "partition-kill-torn": Scenario(
            name="partition-kill-torn", osds=5, pool_size=3, pg_num=8,
            rounds=3, objects_per_round=5, store="file",
            events=(
                ev(0, "partition"),
                ev(1, "heal_partition"),
                ev(1, "crash_osd", torn_tail=True),
                ev(2, "revive_osd"),
            ),
            invariants=("durability", "acting", "health", "scrub",
                        "lockdep"),
            converge_timeout=90.0),
        # per-daemon clock skew vs heartbeats/leases
        "clock-skew": Scenario(
            name="clock-skew", osds=3, pool_size=3, pg_num=4, rounds=2,
            objects_per_round=4,
            events=(
                ev(0, "clock_skew"),
                ev(1, "clock_skew", skew=0.0),
            ),
            invariants=("durability", "acting", "health", "lockdep"),
            converge_timeout=45.0),
        # silent bit-rot found and repaired by scrub
        "bitrot-scrub": Scenario(
            name="bitrot-scrub", osds=3, pool_size=3, pg_num=4,
            rounds=2, objects_per_round=4,
            # after_writes: the flip must land on bytes nothing will
            # overwrite again, or scrub has nothing real to find.
            # scrub runs FIRST: it must repair the flip (majority
            # authoritative copy) before durability reads the object —
            # a read routed to the corrupt replica would otherwise fail
            # the run that scrub was about to heal
            events=(ev(1, "bitrot", after_writes=True),),
            invariants=("scrub", "durability", "acting", "health",
                        "lockdep"),
            converge_timeout=60.0),
        # replicated thrash: restart bounces under load, snapshots on
        "thrash-replicated": Scenario(
            name="thrash-replicated", osds=5, pool_size=3, pg_num=8,
            rounds=4, objects_per_round=8, snapshots=True,
            events=(
                ev(0, "restart_osd"),
                ev(1, "restart_osd"),
                ev(2, "restart_osd"),
                ev(3, "restart_osd"),
            ),
            invariants=("durability", "snapshots", "acting", "health",
                        "scrub", "lockdep"),
            converge_timeout=60.0),
        # graceful degradation under overload (round 10 acceptance
        # gate): zipfian write bursts at 4x the admission budget with a
        # shard holder killed mid-run.  Verdict = durability + "no
        # acked op exceeded its deadline" + "shed count > 0" + HEALTH
        # converging clear of a SLOW_OPS storm.  Slow-marked (see
        # overload-smoke for the tier-1 variant).
        "overload-shed": Scenario(
            name="overload-shed", osds=4, pool_kind="erasure",
            pool_size=3, pg_num=4,
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            rounds=3, objects_per_round=24, payload_repeat=40,
            durability_mode="attempted", workload="zipf",
            burst_concurrency=24, op_deadline=20.0,
            config=(("osd_op_throttle_ops", 6),),   # 24 offered vs 6
            events=(
                ev(1, "kill_osd"),
                ev(2, "revive_osd"),
            ),
            invariants=("durability", "deadline", "shed", "acting",
                        "health", "lockdep"),
            converge_timeout=90.0),
        # tier-1 smoke variant: one small 4x burst, no faults, purely
        # structural assertions (shed fired, nothing acked late, the
        # cluster converges) — the bench host is load-sensitive, so no
        # timing thresholds here
        "overload-smoke": Scenario(
            name="overload-smoke", osds=3, pool_size=3, pg_num=4,
            rounds=1, objects_per_round=12, payload_repeat=20,
            durability_mode="attempted", workload="zipf",
            burst_concurrency=12, op_deadline=25.0,
            config=(("osd_op_throttle_ops", 3),),   # 12 offered vs 3
            invariants=("durability", "deadline", "shed", "acting",
                        "health", "lockdep"),
            converge_timeout=45.0),
        # tier-1 batch-chaos smoke (round 12): seeded per-item frame
        # drops + duplicated/shuffled batched acks on every daemon,
        # plus one tick-boundary crash point, under concurrent EC
        # writes on a durable store.  Verdict: durability + the new
        # frontier invariant (no open entry survives convergence, the
        # persisted watermark matches memory and never regressed) +
        # batch (the coalesced plane actually ran).
        "batch-smoke": Scenario(
            name="batch-smoke", osds=4, pool_kind="erasure",
            pool_size=3, pg_num=8, store="file",
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            rounds=2, objects_per_round=12, payload_repeat=30,
            durability_mode="attempted", burst_concurrency=12,
            events=(
                ev(0, "net", target="all_osds",
                   chaos_net_batch_item_drop=0.15,
                   chaos_net_batch_ack_dup=0.2,
                   chaos_net_batch_ack_reorder=0.2),
                ev(0, "crash_point", point="commit_mid_fanout"),
                ev(1, "revive_osd"),
            ),
            invariants=("durability", "frontier", "batch", "acting",
                        "health", "lockdep"),
            converge_timeout=60.0),
        # tick-boundary crash points across the commit pipeline + a
        # peer killed mid-tick applying a batch frame (slow)
        "batch-kill-midtick": Scenario(
            name="batch-kill-midtick", osds=5, pool_kind="erasure",
            pool_size=3, pg_num=8, store="file",
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            rounds=4, objects_per_round=8, payload_repeat=40,
            durability_mode="attempted", burst_concurrency=8,
            events=(
                ev(0, "net", target="all_osds",
                   chaos_net_batch_item_drop=0.1),
                ev(0, "crash_point", point="batch_apply_mid"),
                ev(1, "revive_osd"),
                ev(1, "crash_point", point="tick_post_encode"),
                ev(2, "revive_osd"),
                ev(2, "crash_point", point="frontier_pre_done"),
                ev(3, "revive_osd"),
            ),
            invariants=("durability", "frontier", "batch", "acting",
                        "health", "scrub", "lockdep"),
            converge_timeout=120.0),
        # ROADMAP item-5 flavored (slow): bounce several OSDs under
        # sustained writes on the sharded WQ; time-to-HEALTH_OK is
        # bounded by the health invariant's converge_timeout, with
        # zero durability/frontier violations
        "rolling-restart-sharded": Scenario(
            name="rolling-restart-sharded", osds=6, pool_size=3,
            pg_num=16, rounds=4, objects_per_round=10,
            payload_repeat=40, durability_mode="attempted",
            store="file",
            events=(
                ev(0, "restart_osd", during_writes=True),
                ev(1, "restart_osd", during_writes=True),
                ev(2, "restart_osd", during_writes=True),
                ev(3, "restart_osd", during_writes=True),
            ),
            invariants=("durability", "frontier", "acting", "health",
                        "scrub", "lockdep"),
            converge_timeout=90.0),
        # EC primaries crashed mid-write (the rewind thrasher)
        "thrash-ec-midwrite": Scenario(
            name="thrash-ec-midwrite", osds=4, pool_kind="erasure",
            pg_num=4,
            ec_profile=(("plugin", "jerasure"),
                        ("technique", "reed_sol_van"),
                        ("k", "2"), ("m", "1")),
            rounds=3, objects_per_round=4, durability_mode="attempted",
            events=(
                ev(0, "restart_osd", during_writes=True),
                ev(1, "restart_osd", during_writes=True),
                ev(2, "restart_osd", during_writes=True),
            ),
            invariants=("durability", "scrub", "acting", "health",
                        "lockdep"),
            converge_timeout=90.0),
    }
