"""Seeded RNG stream derivation: the determinism root of graft-chaos.

Every injector draws from its own named stream derived from the single
scenario seed, so (a) two runs with the same ``--seed`` make identical
random decisions per injector, and (b) adding or removing one injector
never perturbs the streams of the others (the classic shared-RNG replay
bug: one extra ``random()`` call shifts every later decision).  The
reference's teuthology thrashers seed one ``random.Random`` per task for
the same reason.
"""

from __future__ import annotations

import hashlib
import random


def derive_seed(seed: int, name: str) -> int:
    """A 64-bit child seed for stream ``name``; stable across runs,
    processes, and Python versions (sha256, not ``hash()``, which is
    salted per-process)."""
    h = hashlib.sha256(f"{seed}:{name}".encode()).digest()
    return int.from_bytes(h[:8], "little")


def stream(seed: int, name: str) -> random.Random:
    """An independent deterministic RNG stream for one injector."""
    return random.Random(derive_seed(seed, name))
