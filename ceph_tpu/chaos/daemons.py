"""Daemon injector: kill / revive / restart / crash-stop cluster daemons.

The thrasher layer (reference qa/tasks/thrashosds.py + ceph_manager.py
kill_osd/revive_osd), built on the vstart Cluster's daemon lifecycle.
Every action ticks the chaos counters; random victims are resolved by
``scenario.build_schedule`` from its seeded stream BEFORE the run, so a
scenario's kill sequence replays exactly.

``crash_osd`` is the power-cut variant: the store is closed WITHOUT its
clean-shutdown checkpoint and may tear or lose its journal tail
(FileStore/BlueStore ``crash()``), so the revived daemon exercises
torn-tail replay; a crashed MemStore comes back empty, like RAM.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.net import ensure_injector


class DaemonInjector:
    def __init__(self, cluster):
        self.cluster = cluster
        # frontier monotonicity marks (round 12): (osd_id, pgid) ->
        # the PERSISTED last_complete right before a store-preserving
        # bounce.  The frontier invariant asserts the revived daemon's
        # watermark never regressed below it — a reloaded watermark
        # ahead of (or behind) what the store actually holds is exactly
        # the crash bug class the reconstruction prevents.  Not
        # recorded for torn/lost-tail crashes (tail loss is the
        # injected fault) and dropped when a daemon revives empty.
        self.frontier_marks: Dict[Tuple[int, object], tuple] = {}

    def _mark_frontier(self, osd_id: int) -> None:
        osd = self.cluster.osds.get(osd_id)
        if osd is None:
            return
        for pgid in list(osd.pgs):
            try:
                self.frontier_marks[(osd_id, pgid)] = \
                    osd._load_last_complete(pgid)
            except Exception:
                pass

    # -- lifecycle ----------------------------------------------------------

    async def kill_osd(self, osd_id: int) -> None:
        await self.cluster.kill_osd(osd_id)
        CHAOS.inc("daemon_kills")

    async def crash_osd(self, osd_id: int, torn_tail: bool = False,
                        lose_frames: int = 0) -> None:
        if not torn_tail and not lose_frames:
            self._mark_frontier(osd_id)
        await self.cluster.crash_osd(osd_id, torn_tail=torn_tail,
                                     lose_frames=lose_frames)
        CHAOS.inc("daemon_kills")
        CHAOS.inc("disk_crashes")

    async def revive_osd(self, osd_id: int,
                         with_store: bool = False) -> None:
        if not with_store:
            # booting empty: the recorded watermark no longer binds
            for key in [k for k in self.frontier_marks
                        if k[0] == osd_id]:
                del self.frontier_marks[key]
        await self.cluster.revive_osd(osd_id, with_store=with_store)
        CHAOS.inc("daemon_revives")

    async def restart_osd(self, osd_id: int) -> None:
        self._mark_frontier(osd_id)
        await self.cluster.restart_osd(osd_id)
        CHAOS.inc("daemon_restarts")

    async def kill_mon(self, rank: int) -> None:
        await self.cluster.kill_mon(rank)
        CHAOS.inc("daemon_kills")


# -- partitions (cluster-level, name-addressed) -----------------------------


def _messengers(cluster, names: List[str]):
    for name in names:
        kind, _, num = name.partition(".")
        if kind == "osd":
            osd = cluster.osds.get(int(num))
            if osd is not None:
                yield osd.messenger
        elif kind == "mon":
            rank = int(num) if num else 0
            if rank < len(cluster.mons):
                yield cluster.mons[rank].messenger
        elif kind == "mgr" and cluster.mgr is not None:
            yield cluster.mgr.messenger
        elif kind == "mds":
            daemon = (cluster.mdss or {}).get(int(num) if num else 0)
            if daemon is not None:
                yield daemon.messenger


def _addrs(cluster, names: List[str]) -> List[Tuple[str, int]]:
    out = []
    for name in names:
        try:
            out.append(tuple(cluster.daemon_addr(name)))
        except KeyError:
            pass
    return out


def partition(cluster, side_a: List[str], side_b: List[str],
              symmetric: bool = True) -> None:
    """Block side_a -> side_b traffic (and the reverse when symmetric):
    each named daemon's net injector gains the other side's addrs.
    Asymmetric partitions model one-way link failures — A's sends fail
    while B still reaches A."""
    b_addrs = _addrs(cluster, side_b)
    for msgr in _messengers(cluster, side_a):
        ensure_injector(msgr).partition(*b_addrs)
    if symmetric:
        a_addrs = _addrs(cluster, side_a)
        for msgr in _messengers(cluster, side_b):
            ensure_injector(msgr).partition(*a_addrs)


def heal_partitions(cluster) -> None:
    """Drop every partition edge on every live daemon messenger."""
    for msgr in _all_messengers(cluster):
        if msgr.chaos is not None:
            msgr.chaos.heal()


def _all_messengers(cluster):
    for m in cluster.mons:
        yield m.messenger
    for o in cluster.osds.values():
        yield o.messenger
    if cluster.mgr is not None:
        yield cluster.mgr.messenger
    for d in (cluster.mdss or {}).values():
        yield d.messenger
    for c in cluster.clients:
        yield c.objecter.messenger


def zero_rates(cluster) -> None:
    """Heal-all: zero every chaos_* rate on every daemon config (clock
    skew included) and clear partitions — the scenario runner calls this
    before checking invariants so convergence runs fault-free."""
    zeros = {
        "chaos_net_drop": 0.0, "chaos_net_dup": 0.0,
        "chaos_net_delay": 0.0, "chaos_net_delay_prob": 0.0,
        "chaos_net_reorder": 0.0, "chaos_net_reset": 0.0,
        "chaos_net_partition": "",
        "chaos_net_batch_item_drop": 0.0,
        "chaos_net_batch_ack_dup": 0.0,
        "chaos_net_batch_ack_reorder": 0.0,
        "chaos_crash_point": "", "chaos_crash_point_skip": 0,
        "chaos_disk_read_err": 0.0, "chaos_disk_enospc": 0.0,
        "chaos_disk_bitrot": 0.0, "chaos_clock_skew": 0.0,
    }
    configs = [m.config for m in cluster.mons]
    configs += [o.config for o in cluster.osds.values()]
    # dead daemons keep their per-daemon config in osd_configs and
    # resume it on revive — scrub those too, or the heal phase's own
    # revives would resurrect the injected rates mid-invariant-check
    configs += list(cluster.osd_configs.values())
    # crashed MDS ranks resume their remembered config the same way
    configs += list(getattr(cluster, "mds_configs", {}).values())
    if cluster.mgr is not None:
        configs.append(cluster.mgr.config)
    for d in (cluster.mdss or {}).values():
        configs.append(d.config)
    for c in cluster.clients:
        configs.append(c.objecter.config)
    for cfg in configs:
        cfg.injectargs(zeros)
    heal_partitions(cluster)
