"""Front-door chaos scenarios: RBD / RGW / MDS under named crash points.

Round 15 (ROADMAP item 5): the round-12 crash machinery stops at the
librados data plane — no crash point fires inside an RBD copy-up, an
RGW multipart complete, or an MDS journal write, and no invariant can
express "the snapshot read back torn".  This module runs the L8 front
doors as chaos workloads:

- **RBD**: generation writes to fixed regions of a striped image, a
  snapshot per round (``rbd_snap_pre_header`` interrupts between snap-id
  allocation and the header save), a clone from the first snapshot
  (``rbd_clone_mid`` between child registration and the child header)
  whose child writes copy-up under ``rbd_copyup_mid``;
- **RGW**: one multipart upload per round — parts (``rgw_part_mid``
  orphans a payload), then a seeded fate: complete
  (``rgw_complete_mid`` cuts between final payload and index flip),
  abort (``rgw_abort_mid``), or abandon; the heal phase runs the
  ``reclaim_multipart`` pass before judging;
- **MDS**: seeded mkdir/create/rename traffic while ``mds_journal_mid``
  / ``mds_replay_mid`` crash the rank (a daemon — it dies through the
  vstart callback and a babysitter restarts it into journal replay).

Client-library points interrupt-and-retry (``ChaosInterrupt``): the
"application" dies mid-transaction and a seeded coin decides whether a
restarted application retries.  The verdict is judged by the
application-level invariants this PR adds to the shared table
(``snapshot``, ``multipart``, ``namespace`` in
``scenario.judge_invariants``), against the workload's own bookkeeping
(``FrontdoorState``).  Same replay contract as every other scenario:
``build_schedule`` + per-surface seeded streams make a seed's run — and
its verdict — bit-identical.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.daemons import DaemonInjector
from ceph_tpu.chaos.points import ChaosInterrupt
from ceph_tpu.chaos.rng import stream
from ceph_tpu.chaos.scenario import (
    Event,
    Verdict,
    apply_event,
    build_schedule,
    heal_cluster,
    judge_invariants,
    wait_converged,
)


@dataclass(frozen=True)
class FrontdoorScenario:
    """Declarative front-door chaos shape (the Scenario analog; shares
    Event/build_schedule, so schedules resolve identically)."""

    name: str
    osds: int = 3
    pool_size: int = 3
    pg_num: int = 8
    rounds: int = 2
    store: str = "mem"                       # "mem" | "file" | "blue"
    surfaces: Tuple[str, ...] = ("rbd", "rgw", "mds")
    events: Tuple[Event, ...] = ()
    invariants: Tuple[str, ...] = ("snapshot", "multipart", "namespace",
                                   "acting", "health", "lockdep")
    config: Tuple[Tuple[str, object], ...] = ()
    # rbd shape: region_size-aligned whole-region writes are single
    # atomic OSD ops (one extent in one object), so per-region history
    # is judgeable; object_size = 2 regions makes copy-up meaningful
    # (a child write to one region materializes its neighbor)
    regions: int = 6
    region_size: int = 16 << 10
    # rgw shape
    parts_per_upload: int = 3
    part_size: int = 4 << 10
    # mds ops per round
    meta_ops: int = 5
    op_timeout: float = 30.0                 # per front-door op budget
    load: Optional[object] = None            # LoadSpec driven per round
    converge_timeout: float = 60.0


class FrontdoorState:
    """The workload's application-level bookkeeping — the judge context
    the snapshot/multipart/namespace invariants convict against.  The
    invariant checks consume only the attributes/methods below, so the
    synthetic-history unit tests can drive them with fakes."""

    IMAGE = "fdimg"
    CLONE = "fdclone"
    BUCKET = "fdbucket"

    def __init__(self, sc: FrontdoorScenario):
        self.sc = sc
        self.io = None                       # judge-side IoCtx
        self.rgw = None                      # judge-side RGW handle
        self.fsc = None                      # judge-side MDSClient
        self.region_size = sc.region_size
        self.image_name = self.IMAGE
        self.clone_name = self.CLONE
        self.bucket = self.BUCKET
        self.parent_snap = "fs0"
        # rbd history: per-region attempted payload sets + last ack;
        # `dirty` regions had an attempt whose outcome is unknown (a
        # timed-out RADOS op may still land late), so they are never
        # pinned as stable parent-snap content
        self.rbd_attempted: Dict[int, Set[bytes]] = {}
        self.rbd_acked: Dict[int, bytes] = {}
        self.rbd_dirty: Set[int] = set()
        # regions that may legitimately still read as ZEROS: every
        # attempt so far failed, so nothing provably landed — cleared
        # by the first ack (after which zeros can never reappear)
        self.rbd_zero_ok: Set[int] = set()
        self.snaps: Dict[str, Dict[int, frozenset]] = {}
        self.parent_pin: Dict[int, bytes] = {}
        self.clone_attempted: Dict[int, Set[bytes]] = {}
        self.clone_acked: Dict[int, bytes] = {}
        self.clone_expect: Dict[int, frozenset] = {}
        # rgw history
        self.mp_completed: Dict[str, bytes] = {}
        self.mp_pending: Dict[str, bytes] = {}
        # mds history
        self.ns_model: Dict[str, str] = {}
        self.ns_gone: Set[str] = set()

    # -- judge surfaces (duck-typed for the invariant checks) ----------

    async def open_image(self, name: str):
        from ceph_tpu.cluster.rbd import RBD

        return await RBD(self.io).open(name)

    async def part_oids(self) -> List[str]:
        prefix = self.rgw._mp_prefix(self.bucket)
        return [o for o in await self.io.list_objects()
                if o.startswith(prefix)]

    async def fs_stat(self, path: str):
        self.fsc._lease.clear()              # judge reads, not cached
        return await self.fsc.stat(path)

    async def fs_listdir(self, path: str):
        self.fsc._lease.clear()
        return await self.fsc.listdir(path)

    # -- judge-prep ----------------------------------------------------

    def finish_clone_expect(self) -> None:
        """Resolve per-region clone expectations from the recorded
        history: child-acked regions hold the child's bytes (or any
        attempted generation — at-least-once), untouched pinned regions
        fall through to the pinned parent snap, unacked child attempts
        accept either side."""
        for r, pinned in self.parent_pin.items():
            attempted = self.clone_attempted.get(r)
            if r in self.clone_acked:
                self.clone_expect[r] = frozenset(
                    {self.clone_acked[r]} | (attempted or set()))
            elif attempted:
                self.clone_expect[r] = frozenset(attempted | {pinned})
            else:
                self.clone_expect[r] = frozenset({pinned})


def _payload(rng, tag: str, size: int) -> bytes:
    body = f"{tag}-{rng.randrange(1 << 30)}-".encode()
    return (body * (size // len(body) + 1))[:size]


# ------------------------------------------------------------ workloads


class _Runner:
    def __init__(self, sc: FrontdoorScenario, seed: int, cluster,
                 admin, pool: int, meta_pool: int, data_pool: int):
        self.sc = sc
        self.seed = seed
        self.cluster = cluster
        self.admin = admin
        self.pool = pool
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.st = FrontdoorState(sc)
        self.rbd_rng = stream(seed, "fd_rbd")
        self.rgw_rng = stream(seed, "fd_rgw")
        self.mds_rng = stream(seed, "fd_mds")
        self._img = None
        self._clone = None
        self._mds_stop = asyncio.Event()
        self._ns_seq = 0

    # -- setup ---------------------------------------------------------

    async def setup(self) -> None:
        from ceph_tpu.cluster.mds import MDSClient
        from ceph_tpu.cluster.rbd import RBD
        from ceph_tpu.cluster.rgw import RGW

        sc, st = self.sc, self.st
        st.io = self.admin.ioctx(self.pool)
        if "rbd" in sc.surfaces:
            rbd = RBD(st.io)
            await rbd.create(st.IMAGE, sc.regions * sc.region_size,
                             stripe_unit=sc.region_size, stripe_count=1,
                             object_size=2 * sc.region_size)
            self._img = await rbd.open(st.IMAGE)
        if "rgw" in sc.surfaces:
            st.rgw = RGW(st.io)
            await st.rgw.create_bucket(st.BUCKET)
        if "mds" in sc.surfaces:
            await self.cluster.start_mds(self.meta_pool, self.data_pool)
            await self._wait_mds_addr()
            st.fsc = MDSClient(self.admin, self.data_pool,
                               meta_pool=self.meta_pool)
            await st.fsc.mkdir("/fd")
            st.ns_model["/fd"] = "dir"

    async def _wait_mds_addr(self, timeout: float = 15.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            await self.admin.objecter._refresh_map()
            if getattr(self.admin.objecter.osdmap, "mds_addr", None):
                return
            await asyncio.sleep(0.05)
        raise TimeoutError("MDS never registered in the map")

    # -- the MDS babysitter --------------------------------------------
    #
    # MDS crash points kill the daemon; metadata traffic (and the
    # namespace invariant) need the rank back — the babysitter restarts
    # crashed ranks into journal replay.  A rank whose BOOT crashes at
    # an armed mds_replay_mid dies again mid-replay (ChaosCrash out of
    # start_mds); the point is one-shot per config, so the next lap
    # completes the replay.

    async def mds_babysitter(self) -> None:
        from ceph_tpu.chaos import ChaosCrash

        while not self._mds_stop.is_set():
            for rank, pools in list(self.cluster.mds_pools.items()):
                daemon = (self.cluster.mdss or {}).get(rank)
                if daemon is not None and not daemon._stopped:
                    continue
                try:
                    await self.cluster.start_mds(pools[0], pools[1],
                                                 rank=rank)
                except ChaosCrash:
                    continue            # replay-seam crash: next lap
                except (IOError, OSError, TimeoutError,
                        ConnectionError):
                    continue            # cluster still converging
            try:
                await asyncio.wait_for(self._mds_stop.wait(),
                                       timeout=0.15)
            except asyncio.TimeoutError:
                pass

    async def ensure_mds(self, timeout: float = 20.0) -> None:
        """Post-heal: the rank must be up and replayed before judging."""
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            daemon = (self.cluster.mdss or {}).get(0)
            if daemon is not None and not daemon._stopped:
                return
            await asyncio.sleep(0.1)
        raise TimeoutError("MDS rank 0 never came back after heal")

    # -- rbd round -----------------------------------------------------

    async def _reopen_image(self):
        from ceph_tpu.cluster.rbd import RBD

        self._img = await RBD(self.st.io).open(self.st.IMAGE)
        return self._img

    async def _rbd_write(self, img_get, attempted, acked, dirty,
                         region: int, payload: bytes, retry: bool,
                         reopen) -> None:
        """One whole-region write with interrupt-and-retry: the
        ChaosInterrupt is the client process dying; ``retry`` (drawn
        from the seeded stream BEFORE the attempt, so the stream never
        depends on whether the point fired) decides if a restarted
        client re-drives the op against a FRESH handle."""
        attempted.setdefault(region, set()).add(payload)
        if region not in acked and acked is self.st.rbd_acked:
            # nothing has provably landed here yet: a failed attempt
            # leaves the region legitimately zero (judge bookkeeping)
            self.st.rbd_zero_ok.add(region)
        rs = self.sc.region_size
        for attempt in range(2):
            try:
                img = await img_get()
                await img.write(region * rs, payload,
                                timeout=self.sc.op_timeout)
                acked[region] = payload
                dirty.discard(region)
                self.st.rbd_zero_ok.discard(region)
                return
            except ChaosInterrupt:
                if not retry or attempt:
                    break
                CHAOS.inc("interrupt_retries")
                await reopen()
            except (IOError, OSError, TimeoutError):
                break
        dirty.add(region)

    async def rbd_round(self, rnd: int) -> None:
        sc, st, rng = self.sc, self.st, self.rbd_rng
        regs = sorted(rng.sample(range(sc.regions),
                                 max(1, sc.regions // 2)))
        plan = [(r, _payload(rng, f"g{rnd}-reg{r}", sc.region_size),
                 rng.random() < 0.7) for r in regs]
        for region, payload, retry in plan:
            await self._rbd_write(lambda: self._img_get(), st.rbd_attempted,
                                  st.rbd_acked, st.rbd_dirty,
                                  region, payload, retry,
                                  self._reopen_image)
        await self._rbd_snap(rnd)
        if rnd >= 1:
            await self._rbd_clone_phase(rnd)

    async def _img_get(self):
        if self._img is None:
            await self._reopen_image()
        return self._img

    async def _rbd_snap(self, rnd: int) -> None:
        st, rng = self.st, self.rbd_rng
        name = f"fs{rnd}"
        for attempt in range(2):
            try:
                img = await self._img_get()
                await img.snap_create(name, timeout=self.sc.op_timeout)
            except ChaosInterrupt:
                if attempt:
                    return
                CHAOS.inc("interrupt_retries")
                await self._reopen_image()
                continue
            except FileExistsError:
                pass    # the retried create's first half had landed
            except (IOError, OSError, TimeoutError):
                return  # unacked snap: never judged
            break
        # acked: record the point-in-time contract — each judged region
        # must hold ONE whole generation attempted before this instant.
        # Regions where every attempt so far FAILED may legitimately
        # still be zeros (nothing provably landed), so their allowed
        # set includes the virgin states; one ack retires that forever.
        rs = self.sc.region_size
        zero_states = frozenset({b"", b"\x00" * rs})
        st.snaps[name] = {
            r: frozenset(attempts) | (zero_states if r in st.rbd_zero_ok
                                      else frozenset())
            for r, attempts in st.rbd_attempted.items()}

    async def _rbd_clone_phase(self, rnd: int) -> None:
        from ceph_tpu.cluster.rbd import RBD

        sc, st, rng = self.sc, self.st, self.rbd_rng
        if st.parent_snap not in st.snaps:
            return                       # parent snap never acked
        rs = sc.region_size
        if self._clone is None and rnd == 1:
            # pin stable parent-snap content BEFORE any child churn:
            # only clean regions (every attempt acked) are stable
            # against late-landing writes
            img = await self._img_get()
            for r in sorted(set(st.rbd_acked) - st.rbd_dirty):
                if r in st.snaps[st.parent_snap]:
                    try:
                        st.parent_pin[r] = bytes(await img.read(
                            r * rs, rs, snap_name=st.parent_snap,
                            timeout=sc.op_timeout))
                    except (IOError, OSError, TimeoutError,
                            KeyError):
                        pass
            for attempt in range(2):
                try:
                    await RBD(st.io).clone(st.IMAGE, st.parent_snap,
                                           st.CLONE,
                                           timeout=sc.op_timeout)
                except ChaosInterrupt:
                    if attempt:
                        return
                    CHAOS.inc("interrupt_retries")
                    continue
                except FileExistsError:
                    pass
                except (IOError, OSError, TimeoutError):
                    return
                break
            try:
                self._clone = await RBD(st.io).open(st.CLONE)
            except (IOError, OSError, TimeoutError,
                    FileNotFoundError):
                return
        if self._clone is None:
            return

        async def reopen():
            self._clone = await RBD(st.io).open(st.CLONE)

        async def clone_get():
            return self._clone

        pinned = sorted(st.parent_pin)
        if not pinned:
            return
        targets = sorted(rng.sample(pinned,
                                    max(1, len(pinned) // 2)))
        for r in targets:
            payload = _payload(rng, f"child-g{rnd}-reg{r}", rs)
            retry = rng.random() < 0.7
            await self._rbd_write(clone_get, st.clone_attempted,
                                  st.clone_acked, set(), r, payload,
                                  retry, reopen)

    # -- rgw round -----------------------------------------------------

    async def rgw_round(self, rnd: int) -> None:
        sc, st, rng = self.sc, self.st, self.rgw_rng
        key = f"mpk{rnd}"
        fate = rng.choice(["complete", "complete", "abort", "abandon"])
        part_payloads = [_payload(rng, f"mp-r{rnd}-p{n}", sc.part_size)
                         for n in range(1, sc.parts_per_upload + 1)]
        retries = [rng.random() < 0.7
                   for _ in range(sc.parts_per_upload + 1)]
        try:
            uid = await st.rgw.create_multipart(st.BUCKET, key,
                                                timeout=sc.op_timeout)
        except (IOError, OSError, TimeoutError):
            return
        recorded: List[bytes] = []
        for n, payload in enumerate(part_payloads, start=1):
            for attempt in range(2):
                try:
                    await st.rgw.upload_part(st.BUCKET, key, uid, n,
                                             payload,
                                             timeout=sc.op_timeout)
                    recorded.append(payload)
                except ChaosInterrupt:
                    if not retries[n - 1] or attempt:
                        fate = "abandon"   # client died mid-upload
                        break
                    CHAOS.inc("interrupt_retries")
                    continue
                except (IOError, OSError, TimeoutError,
                        FileNotFoundError):
                    fate = "abandon"
                    break
                break
            if fate == "abandon":
                break
        if fate == "complete" and recorded:
            expect = b"".join(recorded)
            try:
                await st.rgw.complete_multipart(st.BUCKET, key, uid,
                                                timeout=sc.op_timeout)
                st.mp_completed[key] = expect
            except ChaosInterrupt:
                # the gateway died mid-complete: all-or-nothing is the
                # judge's to prove after the reclaim pass
                st.mp_pending[key] = expect
            except (IOError, OSError, TimeoutError):
                st.mp_pending[key] = expect
        elif fate == "abort":
            try:
                await st.rgw.abort_multipart(st.BUCKET, key, uid,
                                             timeout=sc.op_timeout)
            except (ChaosInterrupt, IOError, OSError, TimeoutError):
                pass                       # reclaim finishes the abort
        # abandoned uploads are left for the reclaim pass

    # -- mds round -----------------------------------------------------

    async def mds_round(self, rnd: int) -> None:
        sc, st, rng = self.sc, self.st, self.mds_rng
        for _ in range(sc.meta_ops):
            op = rng.choice(["mkdir", "create", "create", "rename"])
            self._ns_seq += 1
            if op == "rename":
                files = sorted(p for p, k in st.ns_model.items()
                               if k == "file")
                if not files:
                    op = "create"
                else:
                    src = rng.choice(files)
                    dst = f"/fd/mv{self._ns_seq}"
                    try:
                        await st.fsc.rename(src, dst)
                    except FileNotFoundError:
                        # our paths are unique: ENOENT on a (possibly
                        # internally retried) rename means the first
                        # send's journalled event already applied
                        pass
                    except (IOError, OSError, TimeoutError,
                            ConnectionError):
                        # outcome unknown: drop src from the model and
                        # do not claim dst (at-least-once ambiguity)
                        st.ns_model.pop(src, None)
                        continue
                    st.ns_model.pop(src, None)
                    st.ns_model[dst] = "file"
                    st.ns_gone.add(src)
                    continue
            path = f"/fd/{'d' if op == 'mkdir' else 'f'}{self._ns_seq}"
            try:
                if op == "mkdir":
                    await st.fsc.mkdir(path)
                else:
                    await st.fsc.create(path)
            except FileExistsError:
                pass    # unique path: the journalled op survived a
                # crash and replay applied it before the retry landed
            except (IOError, OSError, TimeoutError, ConnectionError):
                continue                   # unacked: not judged
            st.ns_model[path] = "dir" if op == "mkdir" else "file"


# --------------------------------------------------------------- runner


async def run_frontdoor(sc: FrontdoorScenario, seed: int,
                        tmpdir: Optional[str] = None) -> Verdict:
    """Boot, drive the front doors under the fault schedule, heal,
    reclaim, converge, judge.  Same shape as scenario.run_scenario —
    shared heal/converge/judge seams, shared Verdict."""
    from ceph_tpu.chaos.scenario import _store_factory
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    schedule = build_schedule(sc, seed)
    wl = stream(seed, "workload")
    cfg = _fast_config()
    cfg.mon_osd_down_out_interval = 600.0
    cfg.chaos_seed = seed
    for k, v in sc.config:
        cfg.set(k, v)
    counters0 = dict(CHAOS.dump()["chaos"])
    cluster = await start_cluster(
        sc.osds, config=cfg, with_mgr=sc.load is not None,
        store_factory=_store_factory(sc, tmpdir))
    dmn = DaemonInjector(cluster)
    failures: List[str] = []
    ctx = None
    babysitter = None
    runner = None
    try:
        admin = await cluster.client()
        pool = await admin.pool_create(
            f"fd_{sc.name}"[:24], "replicated", pg_num=sc.pg_num,
            size=sc.pool_size)
        meta_pool = data_pool = pool
        if "mds" in sc.surfaces:
            meta_pool = await admin.pool_create(
                "fd_meta", "replicated", pg_num=sc.pg_num,
                size=sc.pool_size)
            data_pool = await admin.pool_create(
                "fd_data", "replicated", pg_num=sc.pg_num,
                size=sc.pool_size)
        runner = _Runner(sc, seed, cluster, admin, pool,
                         meta_pool, data_pool)
        await runner.setup()
        st = runner.st
        if "mds" in sc.surfaces:
            babysitter = asyncio.get_event_loop().create_task(
                runner.mds_babysitter())
        if sc.load is not None:
            from ceph_tpu.load.driver import LoadContext

            ctx = await LoadContext.create(sc.load, seed,
                                           cluster=cluster)

        async def surfaces_round(rnd: int) -> None:
            coros = []
            if "rbd" in sc.surfaces:
                coros.append(runner.rbd_round(rnd))
            if "rgw" in sc.surfaces:
                coros.append(runner.rgw_round(rnd))
            if "mds" in sc.surfaces:
                coros.append(runner.mds_round(rnd))
            # each surface draws from its OWN stream, so concurrent
            # execution cannot perturb the seeded histories
            for r in await asyncio.gather(*coros,
                                          return_exceptions=True):
                if isinstance(r, BaseException) and \
                        not isinstance(r, asyncio.CancelledError):
                    raise r

        for rnd in range(sc.rounds):
            evs = [e for e in schedule if e["round"] == rnd]
            for e in [e for e in evs if not e["during_writes"]
                      and not e.get("after_writes")]:
                await apply_event(cluster, dmn, admin, st.io, e, wl,
                                  {}, pool)
            mid = [e for e in evs if e["during_writes"]]
            window = None
            if ctx is not None:
                from ceph_tpu.load.driver import build_plan, drive

                plan = build_plan(sc.load, seed + rnd * 1000003)
                window = asyncio.get_event_loop().create_task(
                    drive(ctx, sc.load, seed, plan=plan))
            work = asyncio.get_event_loop().create_task(
                surfaces_round(rnd))
            try:
                if mid:
                    await asyncio.sleep(0.1 + wl.random() * 0.2)
                    for e in mid:
                        await apply_event(cluster, dmn, admin, st.io,
                                          e, wl, {}, pool)
                        await asyncio.sleep(wl.random() * 0.2)
                await work
                if window is not None:
                    await window
            except BaseException:
                for t in (work, window):
                    if t is not None and not t.done():
                        t.cancel()
                        try:
                            await t
                        except (asyncio.CancelledError, Exception):
                            pass
                raise
            for e in [e for e in evs if e.get("after_writes")]:
                await apply_event(cluster, dmn, admin, st.io, e, wl,
                                  {}, pool)

        # -- heal + reclaim + converge + judge -------------------------
        await heal_cluster(cluster, dmn)
        await wait_converged(cluster, sc.converge_timeout)
        if "mds" in sc.surfaces:
            await runner.ensure_mds()
        if babysitter is not None:
            runner._mds_stop.set()
            await babysitter
            babysitter = None
        if "rgw" in sc.surfaces:
            # the GC/repair pass the multipart invariant judges AFTER:
            # interrupted completes roll forward, aborts finish,
            # orphaned parts are collected, the index matches payloads
            await st.rgw.reclaim_multipart(st.BUCKET, abort_open=True)
        st.finish_clone_expect()
        failures += await judge_invariants(
            cluster, dmn, st.io, sc.invariants, {},
            timeout=sc.converge_timeout, frontdoor=st)
    finally:
        if babysitter is not None:
            runner._mds_stop.set()
            await babysitter
        if ctx is not None:
            await ctx.close()
        await cluster.stop()
    counters1 = CHAOS.dump()["chaos"]
    delta = {k: counters1[k] - counters0.get(k, 0) for k in counters1
             if counters1[k] - counters0.get(k, 0)}
    st = runner.st
    acked = (len(st.rbd_acked) + len(st.clone_acked)
             + len(st.mp_completed) + len(st.ns_model))
    return Verdict(name=sc.name, seed=seed, schedule=schedule,
                   passed=not failures, failures=failures,
                   acked_objects=acked, counters=delta)


# -------------------------------------------------------------- builtins


def frontdoor_scenarios(scale: float = 1.0) -> Dict[str, FrontdoorScenario]:
    """The round-15 front-door scenario library.

    ``frontdoor-smoke`` is the tier-1 gate: all three surfaces, one
    client interrupt or MDS crash per round, MemStore, scaled small.
    The slow trio each focus one surface at full size, composed with
    graft-load traffic and OSD bounces underneath."""
    from ceph_tpu.chaos.scenario import ev
    from ceph_tpu.load.driver import LoadSpec

    s = max(0.1, min(1.0, scale))
    full = s >= 1.0

    def _load(name: str) -> LoadSpec:
        # librados-only mix: background pressure that can never consume
        # a front-door interrupt seam (replay determinism)
        return LoadSpec(
            name=name, clients=max(8, int(24 * s)), sessions=2,
            rate=1.0, duration=1.5, objects=16, payload=1024,
            op_deadline=20.0, osds=4, pg_num=8, store="file",
            verbs=(("write", 4.0), ("read", 3.0), ("append", 1.0)))

    return {
        # tier-1: every front door, one seam per round, bit-identically
        # replayable; the three app-level invariants judge the verdict
        "frontdoor-smoke": FrontdoorScenario(
            name="frontdoor-smoke", osds=3, pool_size=3, pg_num=8,
            rounds=3, store="mem", regions=6, region_size=8 << 10,
            parts_per_upload=3, part_size=4 << 10, meta_ops=4,
            events=(
                # client seams pinned at=0: each fires on its FIRST
                # traversal in the round (one snap/complete/copy-up per
                # round — a seeded skip would outlive the round and be
                # silently re-armed over); the MDS seam sees several
                # mutating ops per round, so its skip stays seeded
                ev(0, "crash_point", target="client",
                   point="rbd_snap_pre_header", at=0),
                ev(0, "crash_point", target="mds.0",
                   point="mds_journal_mid"),
                ev(1, "crash_point", target="client",
                   point="rgw_complete_mid", at=0),
                ev(2, "crash_point", target="client",
                   point="rbd_copyup_mid", at=0),
            ),
            invariants=("snapshot", "multipart", "namespace", "acting",
                        "health", "lockdep"),
            converge_timeout=60.0),
        # RBD snapshots/clones under mid-write interrupts + OSD bounces
        # with sustained librados load underneath (slow)
        "rbd-snap-midwrite": FrontdoorScenario(
            name="rbd-snap-midwrite", osds=int(round(4 + s)),
            pool_size=3, pg_num=16 if full else 8,
            rounds=4 if full else 2, store="file",
            surfaces=("rbd",),
            regions=12 if full else 6, region_size=32 << 10,
            load=_load("rbd-snap-bg") if full else None,
            events=(
                ev(0, "crash_point", target="client",
                   point="rbd_snap_pre_header"),
                ev(1, "crash_point", target="client",
                   point="rbd_clone_mid"),
                ev(1, "restart_osd", during_writes=True),
                ev(2, "crash_point", target="client",
                   point="rbd_copyup_mid"),
                ev(2, "restart_osd", during_writes=True),
                ev(3, "crash_point", target="client",
                   point="rbd_copyup_mid"),
            ) if full else (
                ev(0, "crash_point", target="client",
                   point="rbd_snap_pre_header"),
                ev(1, "crash_point", target="client",
                   point="rbd_copyup_mid"),
            ),
            invariants=("snapshot", "acting", "health", "lockdep"),
            converge_timeout=180.0 if full else 90.0),
        # RGW multipart under part/complete/abort interrupts + an OSD
        # crash, reclaim pass proves all-or-nothing + zero orphans
        "rgw-multipart-crash": FrontdoorScenario(
            name="rgw-multipart-crash", osds=int(round(4 + s)),
            pool_size=3, pg_num=16 if full else 8,
            rounds=4 if full else 2, store="file",
            surfaces=("rgw",),
            parts_per_upload=5 if full else 3,
            part_size=(16 << 10) if full else (4 << 10),
            load=_load("rgw-mp-bg") if full else None,
            events=(
                ev(0, "crash_point", target="client",
                   point="rgw_part_mid"),
                ev(1, "crash_point", target="client",
                   point="rgw_complete_mid"),
                ev(1, "crash_osd", during_writes=True),
                ev(2, "revive_osd"),
                ev(2, "crash_point", target="client",
                   point="rgw_abort_mid"),
                ev(3, "crash_point", target="client",
                   point="rgw_complete_mid"),
            ) if full else (
                ev(0, "crash_point", target="client",
                   point="rgw_part_mid"),
                ev(1, "crash_point", target="client",
                   point="rgw_complete_mid"),
            ),
            invariants=("multipart", "acting", "health", "lockdep"),
            converge_timeout=180.0 if full else 90.0),
        # MDS journal write-ahead + boot replay under daemon crashes:
        # mid-append kills, then an armed mid-replay seam cuts the
        # NEXT boot's replay itself
        "mds-journal-replay": FrontdoorScenario(
            name="mds-journal-replay", osds=int(round(3 + s)),
            pool_size=3, pg_num=8,
            rounds=4 if full else 2, store="file",
            surfaces=("mds",),
            meta_ops=8 if full else 4,
            load=_load("mds-replay-bg") if full else None,
            events=(
                ev(0, "crash_point", target="mds.0",
                   point="mds_journal_mid"),
                # the CHAIN: crash mid-append (one journalled,
                # unapplied event), then crash the restarted rank's
                # boot replay of that very event — the restart resumes
                # the per-rank config, so the chain spans incarnations
                ev(1, "crash_point", target="mds.0",
                   point="mds_journal_mid,mds_replay_mid", at=0),
                ev(2, "crash_mds", target="mds.0",
                   during_writes=True),
                ev(2, "crash_point", target="mds.0",
                   point="mds_journal_mid"),
                ev(3, "restart_osd", during_writes=True),
            ) if full else (
                ev(0, "crash_point", target="mds.0",
                   point="mds_journal_mid"),
                ev(1, "crash_point", target="mds.0",
                   point="mds_journal_mid,mds_replay_mid", at=0),
            ),
            invariants=("namespace", "acting", "health", "lockdep"),
            converge_timeout=180.0 if full else 90.0),
    }
