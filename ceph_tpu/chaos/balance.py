"""Elastic-cluster scenarios (round 21): live expansion, drain, and
balancer convergence as judged, seeded, replayable runs.

Three acceptance shapes ride here, all built by
:func:`elastic_scenarios` and run by :func:`run_elastic` over an
:class:`ElasticScenario`:

- ``expand-drain`` — the full reshape choreography under sustained
  graft-load: boot N OSDs, prefill + keep traffic flowing, grow N->2N
  through the mgr's reshape op (``balance grow`` mints ids + CRUSH
  hosts via one mon Incremental, the runner boots the daemons — the
  operator's half of the handshake), run balancer rounds until the
  data spreads, then drain the grown OSDs back out (``balance drain``:
  out -> wait-clean -> stop daemons -> purge).  The verdict: bounded
  time-to-HEALTH_OK after each reshape, rebalance slot-moves within a
  declared factor of the weight-proportional optimal, every SLO gate
  green over the traffic window, and zero acked-then-lost bytes.

- ``balance-convergence`` — the optimizer alone: a pool whose CRUSH
  placement carries natural straw2 variance, balancer rounds under a
  live load window until the committed move stream dries up.  Judged
  on monotone skew (final pg-per-OSD stddev no worse than initial),
  at least ``balance_moves_min`` committed moves on the SLO scrape,
  and — at full scale — >= ``min_candidates`` candidate maps scored
  per the ``mgr_balancer_candidates`` counter (the >=1000/tick
  acceptance line, counter-verified).

- ``expand-drain-smoke`` — the same expand-drain code path at a fixed
  tier-1 size (seconds, not minutes); scripts/chaos.py lists it as a
  builtin and tests/test_balance_elastic.py runs it in-band.

Phase plans come from :func:`build_elastic_plan` — a pure function of
(scenario, seed) whose encoding is the replay witness, like chaos
schedules and graft-load plan keys.  Runtime outcomes (move counts,
health wait times) ride the verdict's counters, never the plan.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.chaos.counters import CHAOS
from ceph_tpu.chaos.daemons import DaemonInjector
from ceph_tpu.chaos.scenario import (
    Verdict,
    heal_cluster,
    judge_invariants,
    wait_converged,
)
from ceph_tpu.load.driver import LoadSpec, build_plan, drive, plan_key


def elastic_scenarios(scale: float = 1.0) -> Dict[str, "ElasticScenario"]:
    """The round-21 elastic library, sized by ``scale`` (1.0 = the full
    acceptance shape; small fractions run the same code paths at tier-1
    size).  ``expand-drain-smoke`` is ALWAYS the fixed tier-1 shape,
    independent of scale — the listing's cheap entry point."""
    s = max(0.03, min(1.0, scale))
    full = s >= 1.0
    grow_load = LoadSpec(
        name="elastic-grow", clients=max(8, int(64 * s)), sessions=4,
        rate=1.0, duration=10.0 if full else 3.0,
        objects=32, payload=2048, op_deadline=25.0,
        osds=4, pool_size=2, pg_num=32 if full else 16,
        # reshape churn vs the goodput floor: writes + reads only (the
        # durability namespace), generous deadline
        verbs=(("write", 4.0), ("read", 3.0)),
        gates=(("goodput_min_frac", 0.5), ("p99_ms", 5000.0),
               ("cwnd_floor", 2.0), ("qos_reservation_min", 0.0),
               ("balance_moves_min", 1.0)))
    conv_load = LoadSpec(
        name="balance-conv", clients=max(8, int(48 * s)), sessions=4,
        rate=1.0, duration=6.0 if full else 2.0,
        objects=32, payload=2048, op_deadline=25.0,
        osds=5, pool_size=2, pg_num=64 if full else 16,
        verbs=(("write", 4.0), ("read", 3.0)),
        gates=(("goodput_min_frac", 0.5), ("p99_ms", 5000.0),
               ("cwnd_floor", 2.0), ("qos_reservation_min", 0.0),
               ("balance_moves_min", 0.0)))
    lib = {
        "expand-drain": ElasticScenario(
            name="expand-drain", osds=4, grow=4,
            pg_num=32 if full else 16, load=grow_load,
            health_timeout=60.0 if full else 30.0,
            converge_timeout=90.0 if full else 60.0),
        "balance-convergence": ElasticScenario(
            name="balance-convergence", osds=5, grow=0, drain_back=False,
            pg_num=64 if full else 16, load=conv_load,
            min_candidates=1000 if full else 0,
            health_timeout=60.0 if full else 30.0,
            converge_timeout=90.0 if full else 60.0),
        "expand-drain-smoke": ElasticScenario(
            name="expand-drain-smoke", osds=3, grow=3, pg_num=16,
            load=LoadSpec(
                name="elastic-smoke", clients=8, sessions=2, rate=1.0,
                duration=2.0, objects=16, payload=1024,
                op_deadline=25.0, osds=3, pool_size=2, pg_num=16,
                verbs=(("write", 4.0), ("read", 3.0)),
                gates=(("goodput_min_frac", 0.5), ("p99_ms", 5000.0),
                       ("cwnd_floor", 2.0),
                       ("qos_reservation_min", 0.0),
                       ("balance_moves_min", 1.0))),
            health_timeout=30.0, converge_timeout=60.0),
    }
    return lib


@dataclass(frozen=True)
class ElasticScenario:
    """One elastic-reshape acceptance shape.  ``grow`` new OSDs ride in
    through the mgr reshape op; ``drain_back`` sends them back out
    after the rebalance (the full N->2N->N cycle).  ``move_factor``
    bounds observed slot-moves against the weight-proportional optimal
    (straw2 is consistent but not minimal, and upmap corrections add
    their own moves — 3x is the declared envelope)."""

    name: str
    osds: int = 4
    grow: int = 4
    drain_back: bool = True
    pool_size: int = 2
    pg_num: int = 16
    load: LoadSpec = field(default_factory=lambda: LoadSpec(
        name="elastic", clients=8, sessions=2, duration=2.0))
    balancer_rounds: int = 8         # optimize-tick budget per phase
    move_factor: float = 3.0         # moved slots <= factor * optimal
    min_candidates: int = 0          # mgr_balancer_candidates floor
    health_timeout: float = 30.0     # time-to-HEALTH_OK bound per phase
    converge_timeout: float = 60.0
    invariants: Tuple[str, ...] = ("durability", "acting", "health",
                                   "lockdep")
    config: Tuple[Tuple[str, object], ...] = ()
    store: str = "mem"               # scripts/chaos.py tmpdir contract
    rounds: int = 1                  # `list` display only


def build_elastic_plan(sc: ElasticScenario, seed: int) -> List[Dict]:
    """The seed-deterministic phase plan.  The load window's plan_key is
    the graft-load replay witness (pure in (spec, seed)); grow ids are
    symbolic ("the next ``grow`` ids the mon mints") because id minting
    is itself deterministic (base = max_osd).  Runtime outcomes — moves
    committed, health wait — are counters, never plan."""
    phases: List[Dict] = [
        {"phase": "load", "spec": sc.load.name,
         "plan_key": plan_key(build_plan(sc.load, seed))},
    ]
    if sc.grow:
        phases.append({"phase": "grow", "count": sc.grow,
                       "osds_per_host": 1})
    phases.append({"phase": "rebalance", "rounds": sc.balancer_rounds,
                   "move_factor": sc.move_factor})
    if sc.grow and sc.drain_back:
        phases.append({"phase": "drain", "target": "grown"})
    phases.append({"phase": "verify", "invariants": list(sc.invariants)})
    return phases


# ---------------------------------------------------------------- runner


def _mapping_snapshot(m) -> Dict[int, "np.ndarray"]:
    """Per-pool up-mapping arrays — the before/after slot-move ledger."""
    return {pid: np.asarray(m.pool_mapping(pid)[0]).copy()
            for pid in m.pools}


def _moved_slots(before: Dict[int, "np.ndarray"],
                 after: Dict[int, "np.ndarray"]) -> int:
    """PG slots whose placement changed between two snapshots.  Order
    within a PG's up set is placement-relevant (primary), so this is an
    elementwise compare — the same metric placement_delta grades."""
    n = 0
    for pid, b in before.items():
        a = after.get(pid)
        if a is None or a.shape != b.shape:
            # pool reshaped (pg_num change): every slot of the larger
            # shape counts as churn
            n += int(max(a.size if a is not None else 0, b.size))
            continue
        n += int((a != b).any(axis=1).sum())
    return n


async def _wait_health_ok(cluster, timeout: float) -> float:
    """Seconds until the mon reports HEALTH_OK, or -1.0 on timeout."""
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    while loop.time() - t0 < timeout:
        if cluster.mon._health_data()["status"] == "HEALTH_OK":
            return loop.time() - t0
        await asyncio.sleep(0.1)
    return -1.0


async def _optimize_until_dry(cluster, budget: int,
                              timeout: float = 30.0) -> Tuple[int, Dict]:
    """Run balancer rounds until a round commits nothing (or the budget
    runs out).  Throttled rounds — recovery pressure, the cluster still
    digesting the reshape's own backfill — don't consume the round
    budget, only the wall-clock ``timeout``; that throttle-then-proceed
    arc is part of what the scenario exercises.  Returns (total moves
    committed, last round dict)."""
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    moves = 0
    rounds = 0
    last: Dict = {}
    while rounds < max(1, budget) and loop.time() < deadline:
        last = await cluster.daemon_command(
            "mgr", {"prefix": "balance optimize"}, timeout=30.0)
        if last.get("skipped"):
            await asyncio.sleep(0.3)
            continue
        rounds += 1
        if not last.get("committed"):
            break
        moves += int(last.get("moves", 0))
    return moves, last


async def _reshape_wait(cluster, op_id: int, want_phase: str,
                        timeout: float) -> Dict:
    """Poll ``balance status`` (each poll advances open reshape ops —
    the pull-driven contract) until op ``op_id`` reaches ``want_phase``
    or ``done``.  Returns the op's final status dict."""
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    st: Dict = {}
    while loop.time() < deadline:
        status = await cluster.daemon_command(
            "mgr", {"prefix": "balance status"}, timeout=30.0)
        ops = {o["id"]: o for o in status.get("reshape_ops", [])}
        st = ops.get(op_id, {})
        if st.get("phase") in (want_phase, "done"):
            return st
        await asyncio.sleep(0.1)
    return st


async def run_elastic(sc: ElasticScenario, seed: int,
                      tmpdir: Optional[str] = None) -> Verdict:
    """Boot, load, grow, rebalance, drain, judge — the round-21
    acceptance runner."""
    from ceph_tpu.load import slo
    from ceph_tpu.load.driver import LoadContext

    plan = build_elastic_plan(sc, seed)
    # the load context boots the cluster (with_mgr=True — the balance
    # subsystem needs its host daemon) at the SCENARIO's shape
    spec = replace(sc.load, osds=sc.osds, pool_size=sc.pool_size,
                   pg_num=sc.pg_num,
                   config=tuple(sc.load.config) + tuple(sc.config))
    counters0 = dict(CHAOS.dump()["chaos"])
    stats: Dict[str, int] = {}
    failures: List[str] = []
    ctx = await LoadContext.create(spec, seed, tmpdir=tmpdir)
    cluster = ctx.cluster
    dmn = DaemonInjector(cluster)
    load_task = None
    try:
        before = await slo.snapshot(cluster)
        # -- LOAD: one open-loop window spanning the reshape ------------
        load_task = asyncio.get_event_loop().create_task(
            drive(ctx, spec, seed, record_acked=True))
        await asyncio.sleep(0.2)      # let the window open before reshaping

        grown: List[int] = []
        if sc.grow:
            # -- GROW: mgr reshape op mints ids, we boot the daemons ----
            map_before_grow = _mapping_snapshot(cluster.mon.osdmap)
            op = await cluster.daemon_command(
                "mgr", {"prefix": "balance grow", "count": sc.grow},
                timeout=30.0)
            grown = [int(o) for o in op["osds"]]
            await cluster.boot_osds(grown, timeout=sc.health_timeout)
            st = await _reshape_wait(cluster, op["id"], "done",
                                     sc.health_timeout)
            if st.get("phase") != "done":
                failures.append(f"grow: reshape op stuck: {st}")
            # -- REBALANCE: optimize until the move stream dries up -----
            moves, last = await _optimize_until_dry(
                cluster, sc.balancer_rounds, timeout=sc.health_timeout)
            stats["moves_committed"] = moves
            if moves < 1:
                failures.append(
                    f"rebalance: no moves committed onto the grown "
                    f"OSDs (last round: {last})")
            t = await _wait_health_ok(cluster, sc.health_timeout)
            stats["health_ok_after_grow_ms"] = int(max(t, 0) * 1000)
            if t < 0:
                failures.append(
                    f"grow: HEALTH_OK not reached within "
                    f"{sc.health_timeout}s of the reshape")
            # -- MOVE BUDGET: observed churn vs proportional optimal ----
            map_after = _mapping_snapshot(cluster.mon.osdmap)
            moved = _moved_slots(map_before_grow, map_after)
            total_slots = sum(int(a.size) for a in map_after.values())
            frac = sc.grow / (sc.osds + sc.grow)
            optimal = max(1.0, total_slots * frac)
            stats["moved_slots"] = moved
            stats["optimal_slots"] = int(optimal)
            if moved > sc.move_factor * optimal:
                failures.append(
                    f"rebalance: {moved} slots moved for an optimal of "
                    f"~{optimal:.0f} (> declared {sc.move_factor}x "
                    f"envelope)")
        else:
            # -- CONVERGENCE: optimize the natural straw2 variance ------
            skew0 = await cluster.daemon_command(
                "mgr", {"prefix": "balance optimize", "dry_run": True},
                timeout=30.0)
            moves, last = await _optimize_until_dry(
                cluster, sc.balancer_rounds, timeout=sc.health_timeout)
            stats["moves_committed"] = moves
            s_before = float(skew0.get("skew_before", 0.0))
            s_after = float(last.get("skew_after",
                                     last.get("skew_before", 0.0)))
            stats["skew_before_milli"] = int(s_before * 1000)
            stats["skew_after_milli"] = int(s_after * 1000)
            if s_after > s_before + 1e-9:
                failures.append(
                    f"convergence: skew worsened {s_before:.4f} -> "
                    f"{s_after:.4f}")

        result = await load_task
        load_task = None

        if grown and sc.drain_back:
            # -- DRAIN: out -> wait-clean -> stop daemons -> purge ------
            op = await cluster.daemon_command(
                "mgr", {"prefix": "balance drain", "osds": grown},
                timeout=30.0)
            st = await _reshape_wait(cluster, op["id"], "wait-down",
                                     sc.converge_timeout)
            if st.get("phase") not in ("wait-down", "done"):
                failures.append(f"drain: never drained clean: {st}")
            else:
                for o in grown:          # the operator stops the daemons
                    if o in cluster.osds:
                        await cluster.kill_osd(o)
                    cluster.osd_configs.pop(o, None)
                    cluster.osd_stores.pop(o, None)
                st = await _reshape_wait(cluster, op["id"], "done",
                                         sc.converge_timeout)
                if st.get("phase") != "done":
                    failures.append(f"drain: purge never completed: {st}")
                elif any(cluster.mon.osdmap.osd_exists[o] for o in grown):
                    failures.append("drain: purged OSDs still in the map")
            t = await _wait_health_ok(cluster, sc.health_timeout)
            stats["health_ok_after_drain_ms"] = int(max(t, 0) * 1000)
            if t < 0:
                failures.append(
                    f"drain: HEALTH_OK not reached within "
                    f"{sc.health_timeout}s of the drain")

        # -- SLO judge over the whole traffic window --------------------
        after = await slo.snapshot(cluster)
        report = slo.judge(spec, result, before, after)
        gates = report.rows
        if not report.passed:
            failures += [f"slo: {f}" for f in report.failures()]
        if sc.min_candidates:
            scored = slo.counter_sum(after, "ceph_mgr_balancer_candidates",
                                     daemon_prefix="mgr.")
            stats["candidates_scored"] = int(scored)
            if scored < sc.min_candidates:
                failures.append(
                    f"scorer: only {scored:.0f} candidates scored, "
                    f"acceptance floor is {sc.min_candidates}/run")

        # -- heal + converge + judge (the shared seams) ------------------
        await heal_cluster(cluster, dmn)
        await wait_converged(cluster, sc.converge_timeout)
        io = ctx.io(0)
        # attempted-mode durability, like every concurrent-writer chaos
        # scenario: 8 clients race writes to the same oids, and resends
        # under reshape churn ack in dup-protected order — "the last
        # ack the driver SAW" is bookkeeping, not apply order.  Lost
        # data still fails loudly (unreadable / bytes nobody wrote).
        failures += await judge_invariants(
            cluster, dmn, io, sc.invariants, result.acked,
            attempted=result.attempted, mode="attempted",
            timeout=sc.converge_timeout)
        acked_n = len(result.acked)
    finally:
        if load_task is not None and not load_task.done():
            # abnormal exit mid-window: the open-loop ops must not keep
            # firing at a cluster the close below is about to stop
            load_task.cancel()
            try:
                await load_task
            except (asyncio.CancelledError, Exception):
                pass
        await ctx.close()
    counters1 = CHAOS.dump()["chaos"]
    delta = {k: counters1[k] - counters0.get(k, 0) for k in counters1
             if counters1[k] - counters0.get(k, 0)}
    delta.update(stats)
    schedule = [{"round": i, "action": p["phase"],
                 "args": {k: v for k, v in p.items() if k != "phase"}}
                for i, p in enumerate(plan)]
    return Verdict(name=sc.name, seed=seed, schedule=schedule,
                   passed=not failures, failures=failures,
                   acked_objects=acked_n, counters=delta, gates=gates)
