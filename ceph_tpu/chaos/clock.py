"""Per-daemon skewable time source (the clock-skew injector).

Each daemon reads time through its own ``ChaosClock`` instead of the
``time`` module directly; a scenario (or ``injectargs
chaos_clock_skew``) shifts one daemon's view of time without touching
the others.  Heartbeat grace windows, Paxos lease staleness, beacon
timeouts, and op-tracker ages are all computed from this source, so a
skewed daemon really does fire early elections or false failure
reports — the bug class the reference only meets in production when NTP
drifts.

Skew 0.0 (the default) is a plain passthrough: one attribute read and a
float add over ``time.monotonic()`` — the disabled-injector no-op
contract.
"""

from __future__ import annotations

import time


class ChaosClock:
    __slots__ = ("skew",)

    def __init__(self, skew: float = 0.0):
        self.skew = skew

    @classmethod
    def from_config(cls, config) -> "ChaosClock":
        """A clock bound to a daemon's config copy: ``injectargs
        chaos_clock_skew`` retargets it live (and is counted)."""
        clock = cls(config.chaos_clock_skew)

        def _observe(name, value):
            if name == "chaos_clock_skew":
                clock.set_skew(value)

        config.add_observer(_observe)
        return clock

    def set_skew(self, skew: float) -> None:
        if skew != self.skew:
            from ceph_tpu.chaos.counters import CHAOS

            CHAOS.inc("clock_skews")
        self.skew = skew

    def monotonic(self) -> float:
        return time.monotonic() + self.skew

    def time(self) -> float:
        return time.time() + self.skew
