"""Disk injector: store-level fault interposition.

The analog of the reference's ``filestore_debug_inject_read_err`` /
``bluestore_debug_inject_bitrot`` debug options: a store whose owning
daemon carries nonzero ``chaos_disk_*`` rates gets a ``DiskInjector``
on ``store.chaos`` that can

- fail reads with EIO (``chaos_disk_read_err``),
- fail whole transactions with ENOSPC BEFORE any byte lands
  (``chaos_disk_enospc`` — transactions stay atomic: refused, never
  half-applied),
- silently flip stored bits (explicit ``flip_bit`` for targeted
  scrub/repair tests, plus a ``chaos_disk_bitrot`` rate that rots a
  freshly-written object — checksums are NOT updated, so the corruption
  is silent until a csum-verified read or a deep scrub meets it).

Torn/lost writes live on the stores themselves (``FileStore.crash`` /
``BlueStore.crash``): a crash-stop closes the store without the clean
checkpoint and can tear the journal tail mid-frame or discard committed
tail frames, so the next mount exercises the torn-tail replay paths for
real.

Disabled proof: ``store.chaos is None`` with all rates zero — the store
hot paths pay one ``is None`` test.
"""

from __future__ import annotations

from typing import Optional

CONFIG_FIELDS = ("chaos_disk_read_err", "chaos_disk_enospc",
                 "chaos_disk_bitrot")


class DiskInjector:
    def __init__(self, rng, read_err: float = 0.0, enospc: float = 0.0,
                 bitrot: float = 0.0):
        self.rng = rng
        self.read_err = read_err
        self.enospc = enospc
        self.bitrot = bitrot

    @classmethod
    def from_config(cls, config, name: str) -> Optional["DiskInjector"]:
        """``None`` when every rate is zero (the provable-no-op state)."""
        from ceph_tpu.chaos.rng import stream

        if not (config.chaos_disk_read_err or config.chaos_disk_enospc
                or config.chaos_disk_bitrot):
            return None
        return cls(stream(config.chaos_seed, f"disk:{name}"),
                   read_err=config.chaos_disk_read_err,
                   enospc=config.chaos_disk_enospc,
                   bitrot=config.chaos_disk_bitrot)

    # -- store hooks --------------------------------------------------------

    def on_read(self, coll: str, oid: str) -> None:
        """Called at the top of ObjectStore.read: injected media EIO."""
        if self.read_err and self.rng.random() < self.read_err:
            from ceph_tpu.chaos.counters import CHAOS

            CHAOS.inc("disk_read_errors")
            raise IOError(5, f"chaos: injected EIO reading {coll}/{oid}")

    def on_write(self, txn) -> None:
        """Called before a transaction touches journal or state: the
        whole txn is refused (atomicity preserved) with ENOSPC."""
        if self.enospc and self.rng.random() < self.enospc:
            from ceph_tpu.chaos.counters import CHAOS

            CHAOS.inc("disk_write_errors")
            raise OSError(28, "chaos: injected ENOSPC")

    def maybe_rot(self, store, txn) -> None:
        """Rate-driven silent rot: after a transaction commits, flip one
        bit of one object the txn wrote (scrub must find + repair it)."""
        if not self.bitrot or self.rng.random() >= self.bitrot:
            return
        writes = [(op[1], op[2]) for op in txn.ops if op[0] == "write"]
        if not writes:
            return
        coll, oid = writes[self.rng.randrange(len(writes))]
        try:
            self.flip_bit(store, coll, oid)
        except (FileNotFoundError, ValueError):
            pass

    def flip_bit(self, store, coll: str, oid: str,
                 bit: Optional[int] = None) -> int:
        """Flip one stored bit of ``coll/oid`` in ``store`` WITHOUT
        updating any checksum — deterministic from this injector's rng
        stream when ``bit`` is None.  Returns the flipped bit index."""
        from ceph_tpu.chaos.counters import CHAOS

        size = store.stat(coll, oid)
        if not size:
            raise FileNotFoundError(f"{coll}/{oid} empty or missing")
        if bit is None:
            bit = self.rng.randrange(size * 8)
        store.debug_bitrot(coll, oid, bit)
        CHAOS.inc("disk_bitrot_flips")
        return bit
