"""Durability invariants checked after a chaos scenario converges.

The post-conditions that make a fault schedule a TEST instead of a
demolition derby (reference: teuthology's thrasher final checks +
``wait_for_clean``):

- ``durability``: every ACKED write reads back bit-identical and
  checksum-clean (crc32c of the read bytes matches the crc recorded at
  ack time).  ``attempted`` mode (for mid-write primary kills) accepts
  any WHOLE payload ever submitted for the object — a timed-out write
  may legitimately land after its client gave up (at-least-once), but
  torn or mixed-generation bytes never pass.
- ``health``: the cluster reaches HEALTH_OK (no down/out OSDs, no
  slow-op warnings, nothing full).
- ``acting``: no PG is stuck — every PG has a primary and a full acting
  set, and every primary's ``last_complete`` has caught up to
  ``last_update`` (peering finished, nothing left degraded).
- ``snapshots``: every snapshot reads back the contents recorded at
  snap time.
- ``scrub``: a full scrub pass over every primary PG finds zero
  unrepaired inconsistencies (silent divergence / bit-rot is caught and
  fixed, EC shards repair through decode).
- ``lockdep``: the runtime lock-order graph stayed acyclic under the
  fault schedule.

Each check returns a list of human-readable failure strings (empty =
invariant holds); the scenario runner aggregates them into the verdict.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops import crc32c as crcmod


def _crc(data: bytes) -> int:
    return crcmod.crc32c(0xFFFFFFFF, bytes(data))


async def check_durability(io, acked: Dict[str, bytes],
                           attempted: Optional[Dict[str, set]] = None,
                           mode: str = "acked",
                           acked_crcs: Optional[Dict[str, int]] = None,
                           timeout: float = 60.0) -> List[str]:
    failures: List[str] = []
    loop = asyncio.get_event_loop()
    overall = loop.time() + timeout
    for oid, data in sorted(acked.items()):
        want = {data} if mode == "acked" else \
            set((attempted or {}).get(oid, ())) | {data}
        got, err = None, None
        # retry to the shared deadline, but guarantee EVERY object a
        # minimum retry window: recovery may still be rewriting the last
        # objects checked, and a shared budget eaten by the first ones
        # would judge them on a single mid-recovery read
        deadline = max(overall, loop.time() + min(15.0, timeout))
        while asyncio.get_event_loop().time() < deadline:
            try:
                got = await io.read(oid, timeout=30)
                err = None
            except (IOError, OSError, TimeoutError) as e:
                err = e
                await asyncio.sleep(0.5)
                continue
            if got in want:
                break
            await asyncio.sleep(0.5)
        if err is not None:
            failures.append(f"durability: {oid} unreadable: {err!r}")
        elif got is None:
            failures.append(f"durability: {oid} never read back before "
                            "the deadline")
        elif got not in want:
            failures.append(
                f"durability: {oid} holds torn/unknown bytes "
                f"{got[:24]!r}... != acked {data[:24]!r}...")
        elif got == data and acked_crcs and \
                _crc(got) != acked_crcs.get(oid, _crc(data)):
            failures.append(f"durability: {oid} crc diverged from the "
                            "crc recorded at ack time")
    return failures


async def check_health(cluster, timeout: float = 30.0) -> List[str]:
    deadline = asyncio.get_event_loop().time() + timeout
    health = {}
    while asyncio.get_event_loop().time() < deadline:
        health = cluster.mon._health_data()
        if health["status"] == "HEALTH_OK":
            return []
        await asyncio.sleep(0.25)
    return [f"health: {health.get('status')} {health.get('checks')}"]


async def check_acting(cluster, timeout: float = 30.0) -> List[str]:
    deadline = asyncio.get_event_loop().time() + timeout
    failures: List[str] = []
    while asyncio.get_event_loop().time() < deadline:
        failures = _acting_once(cluster)
        if not failures:
            return []
        await asyncio.sleep(0.25)
    return failures


def _acting_once(cluster) -> List[str]:
    from ceph_tpu.osdmap.osdmap import PGid

    failures: List[str] = []
    m = cluster.mon.osdmap
    for pool_id, pool in m.pools.items():
        want = pool.size
        for seed in range(pool.pg_num):
            pgid = PGid(pool_id, seed)
            _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
            live = [o for o in acting if o != CRUSH_ITEM_NONE]
            if primary < 0:
                failures.append(f"acting: pg {pgid} has no primary")
            elif len(live) < want:
                failures.append(
                    f"acting: pg {pgid} undersized {live} < {want}")
            else:
                posd = cluster.osds.get(primary)
                st = posd.pgs.get(pgid) if posd else None
                if st is not None and st.last_complete < st.last_update:
                    failures.append(
                        f"acting: pg {pgid} incomplete "
                        f"({st.last_complete} < {st.last_update})")
    return failures


async def check_snapshots(io, snaps: Dict[int, Dict[str, bytes]],
                          timeout: float = 60.0) -> List[str]:
    failures: List[str] = []
    loop = asyncio.get_event_loop()
    overall = loop.time() + timeout
    for sid, objs in sorted(snaps.items()):
        for oid, data in sorted(objs.items()):
            got = None
            deadline = max(overall, loop.time() + min(10.0, timeout))
            while asyncio.get_event_loop().time() < deadline:
                try:
                    got = await io.read(oid, snapid=sid, timeout=30)
                except (IOError, OSError, TimeoutError):
                    await asyncio.sleep(0.5)
                    continue
                if got == data:
                    break
                await asyncio.sleep(0.5)
            if got != data:
                failures.append(
                    f"snapshots: {oid}@snap{sid} diverged "
                    f"(got {None if got is None else got[:24]!r})")
    return failures


async def check_scrub(cluster, timeout: float = 90.0) -> List[str]:
    deadline = asyncio.get_event_loop().time() + timeout
    bad: List[str] = []
    while True:
        bad = []
        for osd in list(cluster.osds.values()):
            for st in list(osd.pgs.values()):
                if st.primary != osd.osd_id:
                    continue
                try:
                    rep = await osd.scrub_pg(st)
                except Exception as e:
                    bad.append(f"scrub: pg {st.pgid} errored: {e!r}")
                    continue
                bad.extend(f"scrub: {oid} inconsistent in {st.pgid}"
                           for oid in rep["inconsistent"]
                           if oid not in rep["repaired"])
        if not bad or asyncio.get_event_loop().time() > deadline:
            break
        await asyncio.sleep(1.0)
    return bad


def check_shed(cluster) -> List[str]:
    """An overload scenario must actually exercise the shedding
    machinery: at least one throttle pushback, deadline shed, or QoS
    preemption across the cluster — a run where nothing shed means the
    offered load never exceeded the budget and the scenario proved
    nothing.  (SLOW_OPS staying clear is the existing ``health``
    invariant's job at convergence.)"""
    total = 0
    for osd in cluster.osds.values():
        for counter in ("osd_throttle_rejects", "osd_ops_shed_expired",
                        "osd_sub_ops_shed_expired", "osd_qos_preempted"):
            total += osd.perf.get(counter)  # 0 for never-bumped names
    if total:
        return []
    return ["shed: overload run produced zero throttle pushbacks / "
            "deadline sheds / QoS preemptions — budget never saturated"]


async def check_frontier(cluster, marks: Optional[Dict] = None,
                         timeout: float = 30.0) -> List[str]:
    """Commit-frontier consistency after convergence (round 12):

    - no PG keeps an OPEN pipeline/frontier entry (every in-flight or
      crash-reconstructed entry was resolved by acks, peering
      roll-forward, or rewind);
    - ``last_complete`` never exceeds ``last_update``, and on every
      primary the two are EQUAL (nothing left unresolved);
    - the persisted watermark matches the in-memory one (a crash at any
      instant reloads exactly what was blessed, nothing more);
    - across every store-preserving bounce the watermark is MONOTONE:
      the revived daemon's frontier never regressed below the value
      persisted before the crash (``marks`` from DaemonInjector).
    """
    deadline = asyncio.get_event_loop().time() + timeout
    failures: List[str] = []
    while True:
        failures = []
        for osd in list(cluster.osds.values()):
            for pgid, st in list(osd.pgs.items()):
                where = f"osd.{osd.osd_id} pg {pgid}"
                if st.last_complete > st.last_update:
                    failures.append(
                        f"frontier: {where} watermark "
                        f"{st.last_complete} ahead of last_update "
                        f"{st.last_update}")
                if st.primary == osd.osd_id:
                    if st.pipeline_pending:
                        failures.append(
                            f"frontier: {where} still holds open "
                            f"entries {list(st.pipeline_pending)[:4]}")
                    if st.frontier_recovering:
                        failures.append(
                            f"frontier: {where} never resolved "
                            f"crash-reconstructed entries "
                            f"{sorted(st.frontier_recovering)[:4]}")
                    if st.last_complete < st.last_update:
                        failures.append(
                            f"frontier: {where} incomplete "
                            f"({st.last_complete} < {st.last_update})")
        if not failures or \
                asyncio.get_event_loop().time() > deadline:
            break
        await asyncio.sleep(0.25)
    # persistence + monotonicity: checked once, post-convergence
    for osd in list(cluster.osds.values()):
        for pgid, st in list(osd.pgs.items()):
            stored = osd._load_last_complete(pgid)
            if stored != st.last_complete:
                failures.append(
                    f"frontier: osd.{osd.osd_id} pg {pgid} persisted "
                    f"watermark {stored} != in-memory "
                    f"{st.last_complete}")
            mark = (marks or {}).get((osd.osd_id, pgid))
            if mark is not None and st.last_complete < mark:
                failures.append(
                    f"frontier: osd.{osd.osd_id} pg {pgid} watermark "
                    f"regressed across crash-restart "
                    f"({st.last_complete} < pre-crash {mark})")
    return failures


async def check_repair(cluster, timeout: float = 30.0) -> List[str]:
    """A corruption scenario must actually exercise the self-healing
    machinery (round 16): at least one crc/EIO/stale detection AND at
    least one completed repair (verifying read or scrub) across the
    cluster, and zero objects left flagged inconsistent on any
    primary.  Converge-polls to a wall deadline: the detections the
    durability check's own reads just triggered arm ASYNC repairs
    that may still be landing when the judge reaches this invariant.
    Final bit-correctness of the served bytes is the durability
    invariant's job; this one proves detection and healing FIRED and
    CONVERGED."""
    def _once() -> List[str]:
        detected = repaired = 0
        out: List[str] = []
        for osd in cluster.osds.values():
            # NOT osd_scrub_errors: the scrub loop's generic exception
            # handler shares that counter, so a scrub that merely
            # CRASHED would masquerade as a detection.  Scrub-side
            # detections count through their repairs (a detected-but-
            # unrepaired object shows up as a leftover below instead).
            for c in ("osd_read_shard_crc_errors",
                      "osd_read_shard_errors",
                      "osd_scrub_errors_repaired"):
                detected += osd.perf.get(c)
            for c in ("osd_read_repairs", "osd_scrub_errors_repaired"):
                repaired += osd.perf.get(c)
            for pgid, st in osd.pgs.items():
                if st.primary == osd.osd_id and st.inconsistent:
                    out.append(
                        f"repair: osd.{osd.osd_id} pg {pgid} still "
                        f"holds inconsistent "
                        f"{sorted(st.inconsistent)[:4]}")
        if not detected:
            out.append("repair: corruption run produced zero "
                       "crc/EIO/stale detections — nothing verified "
                       "the injected rot")
        if not repaired:
            out.append("repair: zero completed repairs — detections "
                       "never healed")
        return out

    deadline = asyncio.get_event_loop().time() + timeout
    failures = _once()
    while failures and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.25)
        failures = _once()
    return failures


def check_batch(cluster) -> List[str]:
    """A batch-chaos scenario must actually exercise the batched data
    plane: coalesced encode ticks ran (the deterministic signal — any
    concurrent same-profile writes coalesce).  Multi-item FRAME counts
    are left to the test layer: whether same-tick sub-writes share a
    frame depends on transport timing, so a hard per-run requirement
    would make seeded verdicts flappy (the replay contract forbids
    that); the mutator's per-item semantics are proven deterministically
    at unit level instead."""
    ticks = sum(osd.perf.get("osd_batch_ticks")
                for osd in cluster.osds.values())
    if not ticks:
        return ["batch: no coalesced encode tick ever ran — the "
                "scenario never hit the batched plane"]
    return []


# -- front-door invariants (round 15) ---------------------------------------
#
# Application-LEVEL post-conditions for the L8 services, judged against
# the workload's own bookkeeping (a FrontdoorState, chaos/frontdoor.py —
# or any duck-typed stand-in: the synthetic-history unit tests drive
# these checks with hand-built fakes).  Each check takes the surfaces it
# needs as attributes of ``fd`` so the verdict logic is testable without
# a cluster.


async def _read_retry(fn, deadline, *args, **kwargs):
    """Retry transient I/O errors until ``deadline``; returns
    (value, error) — recovery may still be rewriting what we judge."""
    while True:
        try:
            return await fn(*args, **kwargs), None
        except FileNotFoundError as e:
            # meaningful outcome for the caller, never retried away
            return None, e
        except (IOError, OSError, TimeoutError) as e:
            if asyncio.get_event_loop().time() > deadline:
                return None, e
            await asyncio.sleep(0.5)


async def check_snapshot(fd, timeout: float = 60.0) -> List[str]:
    """RBD snapshot/clone consistency:

    - every snapshot read is POINT-IN-TIME: each judged region holds one
      whole generation that had been attempted before the snap acked —
      never post-snap bytes (a COW miss), never a torn mix;
    - clone parents are immutable: the parent snap's bytes pinned at
      clone time read back identical after all child copy-up churn;
    - the clone itself resolves correctly: regions the child acked hold
      the child's bytes, untouched regions fall through to the pinned
      parent snap (copy-up preserved, not clobbered).
    """
    failures: List[str] = []
    deadline = asyncio.get_event_loop().time() + timeout
    rs = fd.region_size
    for snap in sorted(fd.snaps):
        img = await fd.open_image(fd.image_name)
        for region, allowed in sorted(fd.snaps[snap].items()):
            got, err = await _read_retry(img.read, deadline,
                                         region * rs, rs, snap_name=snap)
            if err is not None:
                failures.append(f"snapshot: {fd.image_name}@{snap} "
                                f"region {region} unreadable: {err!r}")
            elif bytes(got) not in allowed:
                failures.append(
                    f"snapshot: {fd.image_name}@{snap} region {region} "
                    f"holds torn or post-snap bytes "
                    f"{bytes(got)[:24]!r}...")
    if fd.parent_pin:
        img = await fd.open_image(fd.image_name)
        for region, pinned in sorted(fd.parent_pin.items()):
            got, err = await _read_retry(
                img.read, deadline, region * rs, rs,
                snap_name=fd.parent_snap)
            if err is not None or bytes(got) != pinned:
                failures.append(
                    f"snapshot: clone parent {fd.image_name}"
                    f"@{fd.parent_snap} region {region} MUTATED under "
                    f"child churn (err={err!r})")
    if fd.clone_expect:
        clone = await fd.open_image(fd.clone_name)
        for region, allowed in sorted(fd.clone_expect.items()):
            got, err = await _read_retry(clone.read, deadline,
                                         region * rs, rs)
            if err is not None:
                failures.append(f"snapshot: clone {fd.clone_name} "
                                f"region {region} unreadable: {err!r}")
            elif bytes(got) not in allowed:
                failures.append(
                    f"snapshot: clone {fd.clone_name} region {region} "
                    f"lost copy-up bytes ({bytes(got)[:24]!r}...)")
    return failures


async def check_multipart(fd, timeout: float = 60.0) -> List[str]:
    """RGW multipart consistency (judged AFTER the reclaim pass):

    - an ACKED complete is fully visible: listed in the bucket index
      and readable with exactly the manifest's bytes;
    - an interrupted (never-acked) complete is ALL-OR-NOTHING: either
      fully visible with exact bytes (reclaim rolled it forward) or
      fully absent (listing and head agree on 404) — never partial;
    - no orphaned part objects survive the reclaim pass;
    - the bucket-index listing matches readable objects: every listed
      key serves its payload.
    """
    failures: List[str] = []
    deadline = asyncio.get_event_loop().time() + timeout
    listing, err = await _read_retry(fd.rgw.list_objects, deadline,
                                     fd.bucket, "", "", 100000)
    if err is not None:
        return [f"multipart: bucket {fd.bucket} unlistable: {err!r}"]
    listed = {m.key for m in listing.keys}
    for key, payload in sorted(fd.mp_completed.items()):
        got, err = await _read_retry(fd.rgw.get_object, deadline,
                                     fd.bucket, key)
        if err is not None:
            failures.append(f"multipart: acked complete {key} "
                            f"unreadable: {err!r}")
        elif got[1] != payload:
            failures.append(f"multipart: acked complete {key} holds "
                            f"wrong bytes ({len(got[1])} != "
                            f"{len(payload)})")
        if key not in listed:
            failures.append(f"multipart: acked complete {key} missing "
                            f"from the bucket listing")
    for key, payload in sorted(fd.mp_pending.items()):
        if key in listed:
            got, err = await _read_retry(fd.rgw.get_object, deadline,
                                         fd.bucket, key)
            if err is not None or got[1] != payload:
                failures.append(
                    f"multipart: interrupted complete {key} is "
                    f"PARTIALLY visible (listed but wrong/unreadable "
                    f"bytes, err={err!r})")
        else:
            _, err = await _read_retry(fd.rgw.head_object, deadline,
                                       fd.bucket, key)
            if not isinstance(err, FileNotFoundError):
                failures.append(
                    f"multipart: interrupted complete {key} not listed "
                    f"but head disagrees (err={err!r})")
    orphans = await fd.part_oids()
    if orphans:
        failures.append(f"multipart: {len(orphans)} orphaned part "
                        f"object(s) survive the reclaim pass: "
                        f"{sorted(orphans)[:4]}")
    for key in sorted(listed):
        _, err = await _read_retry(fd.rgw.get_object, deadline,
                                   fd.bucket, key)
        if err is not None:
            failures.append(f"multipart: listed key {key} is not "
                            f"readable ({err!r}) — index diverged from "
                            f"objects")
    return failures


async def check_namespace(fd, timeout: float = 60.0) -> List[str]:
    """MDS namespace consistency after crash + journal replay:

    - every ACKED metadata op's effect is present post-replay (an acked
      mkdir/create resolves, an acked rename's destination exists) —
      journal trim never ate an unreplayed segment;
    - paths acked as REMOVED (rename source, unlink) stay gone — replay
      never resurrects superseded state;
    - unacked ops may have landed or not (at-least-once journalling),
      but the tree itself must be walkable: every model directory
      lists cleanly.
    """
    failures: List[str] = []
    deadline = asyncio.get_event_loop().time() + timeout
    for path, kind in sorted(fd.ns_model.items()):
        ino, err = await _read_retry(fd.fs_stat, deadline, path)
        if err is not None:
            failures.append(f"namespace: acked {kind} {path} lost "
                            f"post-replay ({err!r})")
        elif getattr(ino, "mode", kind) != kind:
            failures.append(f"namespace: {path} is {ino.mode}, acked "
                            f"as {kind}")
    for path in sorted(fd.ns_gone):
        _, err = await _read_retry(fd.fs_stat, deadline, path)
        if not isinstance(err, FileNotFoundError):
            failures.append(f"namespace: removed path {path} "
                            f"resurrected post-replay (err={err!r})")
    for path, kind in sorted(fd.ns_model.items()):
        if kind != "dir":
            continue
        _, err = await _read_retry(fd.fs_listdir, deadline, path)
        if err is not None:
            failures.append(f"namespace: dir {path} unlistable "
                            f"post-replay ({err!r})")
    return failures


def check_lockdep() -> List[str]:
    """The observed runtime lock graph must be acyclic (the same graph
    `lockdep dump` serves and graftlint merges)."""
    from ceph_tpu.utils.lockdep import LockDep

    edges = LockDep.instance().dump()["edges"]
    state: Dict[str, int] = {}

    def dfs(node, path):
        state[node] = 1
        for nxt in edges.get(node, ()):
            if state.get(nxt) == 1:
                return path + [nxt]
            if state.get(nxt) is None:
                cyc = dfs(nxt, path + [nxt])
                if cyc:
                    return cyc
        state[node] = 2
        return None

    for node in edges:
        if state.get(node) is None:
            cyc = dfs(node, [node])
            if cyc:
                return [f"lockdep: cycle {' -> '.join(cyc)}"]
    return []
