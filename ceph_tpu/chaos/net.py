"""Net injector: messenger-level fault interposition.

The analog of the reference's ``ms_inject_socket_failures`` /
``ms_inject_delay_*`` debug options (src/msg/Messenger.h): a messenger
whose config carries nonzero ``chaos_net_*`` rates owns a ``NetInjector``
that decides, per outgoing session frame, whether to drop, duplicate,
delay, reorder, or follow up with a session reset — plus an asymmetric
partition set that makes chosen peers unreachable from THIS endpoint
only (``A -> B`` blocked while ``B -> A`` flows, the classic one-way
link failure).

Semantics ride the messenger's own reliability machinery rather than
bypassing it: a dropped frame stays in the session's unacked replay
buffer, so it is re-delivered when a later failure forces a
reconnect+replay — exactly a lost packet under retransmission.  A
partitioned connect raises ``ConnectionError`` like a refused TCP
connection, which drives monclient hunting, heartbeat failure reports,
and session replay in the real code paths.

Disabled proof: a messenger with all rates zero and no partitions has
``messenger.chaos is None`` — the hot send path pays one ``is None``
test and nothing else.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Set, Tuple

Addr = Tuple[str, int]

# the config options this injector is built from (messenger observers
# rebuild on any of these)
CONFIG_FIELDS = (
    "chaos_net_drop", "chaos_net_dup", "chaos_net_delay",
    "chaos_net_delay_prob", "chaos_net_reorder", "chaos_net_reset",
    "chaos_net_partition",
)


@dataclass
class FrameFate:
    """Per-frame decision vector (computed once, before the wire)."""

    drop: bool = False
    retransmit: float = 0.0  # drop only: session replay fires after this
    dup: bool = False
    delay: float = 0.0
    reorder: float = 0.0     # >0: defer the frame by this many seconds
    reset: bool = False


def parse_partitions(spec: str) -> Set[Addr]:
    """``"host:port,host:port"`` -> addr set (the injectargs encoding of
    a partition; scenarios resolve daemon names to addrs first)."""
    out: Set[Addr] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.add((host, int(port)))
    return out


class NetInjector:
    def __init__(self, rng, drop: float = 0.0, dup: float = 0.0,
                 delay: float = 0.0, delay_prob: float = 0.0,
                 reorder: float = 0.0, reset: float = 0.0,
                 partitions: Optional[Set[Addr]] = None):
        self.rng = rng
        self.drop = drop
        self.dup = dup
        self.delay = delay
        self.delay_prob = delay_prob
        self.reorder = reorder
        self.reset = reset
        self.partitions: Set[Addr] = set(partitions or ())

    @classmethod
    def from_config(cls, config, name: str,
                    keep_partitions: Optional[Set[Addr]] = None
                    ) -> Optional["NetInjector"]:
        """Build from a daemon's chaos_net_* options; ``None`` when every
        rate is zero and no partition is configured (the provable-no-op
        state).  ``keep_partitions`` preserves programmatically-added
        partitions across an injectargs-triggered rebuild."""
        from ceph_tpu.chaos.rng import stream

        parts = parse_partitions(config.chaos_net_partition)
        if keep_partitions:
            parts |= keep_partitions
        rates = (config.chaos_net_drop, config.chaos_net_dup,
                 config.chaos_net_delay_prob, config.chaos_net_reorder,
                 config.chaos_net_reset)
        if not any(rates) and not parts:
            return None
        return cls(stream(config.chaos_seed, f"net:{name}"),
                   drop=config.chaos_net_drop, dup=config.chaos_net_dup,
                   delay=config.chaos_net_delay,
                   delay_prob=config.chaos_net_delay_prob,
                   reorder=config.chaos_net_reorder,
                   reset=config.chaos_net_reset, partitions=parts)

    # -- partition management (scenario runner API) -------------------------

    def partition(self, *addrs: Addr) -> None:
        self.partitions.update(tuple(a) for a in addrs)

    def heal(self, *addrs: Addr) -> None:
        """Heal specific peers, or everything when called bare."""
        if addrs:
            self.partitions.difference_update(tuple(a) for a in addrs)
        else:
            self.partitions.clear()

    def partitioned(self, addr: Addr) -> bool:
        return tuple(addr) in self.partitions

    # -- messenger hooks ----------------------------------------------------

    def check_connect(self, addr: Addr) -> None:
        """Raises like a refused/blackholed TCP connect when the peer is
        behind a partition (called from Messenger.connect)."""
        if self.partitions and tuple(addr) in self.partitions:
            from ceph_tpu.chaos.counters import CHAOS

            CHAOS.inc("net_partition_blocks")
            raise ConnectionError(f"chaos: partition blocks {addr}")

    def on_frame(self, addr: Addr) -> FrameFate:
        """Decide this frame's fate; counters tick at decision time.
        Each enabled fault family consumes its own rng draws, so
        disabling one family never shifts another's stream."""
        from ceph_tpu.chaos.counters import CHAOS

        fate = FrameFate()
        rng = self.rng
        if self.drop and rng.random() < self.drop:
            fate.drop = True
            # the retransmission timer: the messenger schedules a
            # session replay after this, so loss is transient on a
            # healthy net and real under a partition
            fate.retransmit = rng.uniform(0.02, 0.2)
            CHAOS.inc("net_drops")
            return fate                  # a dropped frame has no other fate
        if self.delay_prob and rng.random() < self.delay_prob:
            fate.delay = rng.uniform(0.0, self.delay or 0.05)
            CHAOS.inc("net_delays")
        if self.reorder and rng.random() < self.reorder:
            fate.reorder = rng.uniform(0.005, max(0.01, self.delay or 0.05))
            CHAOS.inc("net_reorders")
            return fate                  # deferred: dup/reset don't stack
        if self.dup and rng.random() < self.dup:
            fate.dup = True
            CHAOS.inc("net_dups")
        if self.reset and rng.random() < self.reset:
            fate.reset = True
            CHAOS.inc("net_resets")
        return fate


def ensure_injector(messenger) -> NetInjector:
    """The scenario runner's handle on a daemon messenger: returns the
    live injector, creating an all-zero-rate one (for partition-only
    scenarios) when chaos is currently disabled."""
    if messenger.chaos is None:
        from ceph_tpu.chaos.rng import stream

        seed = 0
        cfg = getattr(messenger, "config", None)
        if cfg is not None:
            seed = cfg.chaos_seed
        messenger.chaos = NetInjector(
            stream(seed, f"net:{messenger.name}"))
    return messenger.chaos
