"""Net injector: messenger-level fault interposition.

The analog of the reference's ``ms_inject_socket_failures`` /
``ms_inject_delay_*`` debug options (src/msg/Messenger.h): a messenger
whose config carries nonzero ``chaos_net_*`` rates owns a ``NetInjector``
that decides, per outgoing session frame, whether to drop, duplicate,
delay, reorder, or follow up with a session reset — plus an asymmetric
partition set that makes chosen peers unreachable from THIS endpoint
only (``A -> B`` blocked while ``B -> A`` flows, the classic one-way
link failure).

Semantics ride the messenger's own reliability machinery rather than
bypassing it: a dropped frame stays in the session's unacked replay
buffer, so it is re-delivered when a later failure forces a
reconnect+replay — exactly a lost packet under retransmission.  A
partitioned connect raises ``ConnectionError`` like a refused TCP
connection, which drives monclient hunting, heartbeat failure reports,
and session replay in the real code paths.

Disabled proof: a messenger with all rates zero and no partitions has
``messenger.chaos is None`` — the hot send path pays one ``is None``
test and nothing else.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Set, Tuple

Addr = Tuple[str, int]

# the config options this injector is built from (messenger observers
# rebuild on any of these)
CONFIG_FIELDS = (
    "chaos_net_drop", "chaos_net_dup", "chaos_net_delay",
    "chaos_net_delay_prob", "chaos_net_reorder", "chaos_net_reset",
    "chaos_net_partition", "chaos_net_batch_item_drop",
    "chaos_net_batch_ack_dup", "chaos_net_batch_ack_reorder",
)

# message type names the batch mutator understands (duck-typed so the
# chaos layer never imports cluster wire classes)
_BATCH_FRAME = "MOSDECSubOpWriteBatch"
_BATCH_REPLY = "MOSDECSubOpWriteBatchReply"


@dataclass
class FrameFate:
    """Per-frame decision vector (computed once, before the wire)."""

    drop: bool = False
    retransmit: float = 0.0  # drop only: session replay fires after this
    dup: bool = False
    delay: float = 0.0
    reorder: float = 0.0     # >0: defer the frame by this many seconds
    reset: bool = False


def parse_partitions(spec: str) -> Set[Addr]:
    """``"host:port,host:port"`` -> addr set (the injectargs encoding of
    a partition; scenarios resolve daemon names to addrs first)."""
    out: Set[Addr] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.add((host, int(port)))
    return out


class NetInjector:
    def __init__(self, rng, drop: float = 0.0, dup: float = 0.0,
                 delay: float = 0.0, delay_prob: float = 0.0,
                 reorder: float = 0.0, reset: float = 0.0,
                 partitions: Optional[Set[Addr]] = None,
                 batch_item_drop: float = 0.0,
                 batch_ack_dup: float = 0.0,
                 batch_ack_reorder: float = 0.0):
        self.rng = rng
        self.drop = drop
        self.dup = dup
        self.delay = delay
        self.delay_prob = delay_prob
        self.reorder = reorder
        self.reset = reset
        self.partitions: Set[Addr] = set(partitions or ())
        # batch-frame faults (round 12): per-item loss INSIDE a
        # coalesced tick frame, duplicated/shuffled batched acks
        self.batch_item_drop = batch_item_drop
        self.batch_ack_dup = batch_ack_dup
        self.batch_ack_reorder = batch_ack_reorder

    @classmethod
    def from_config(cls, config, name: str,
                    keep_partitions: Optional[Set[Addr]] = None
                    ) -> Optional["NetInjector"]:
        """Build from a daemon's chaos_net_* options; ``None`` when every
        rate is zero and no partition is configured (the provable-no-op
        state).  ``keep_partitions`` preserves programmatically-added
        partitions across an injectargs-triggered rebuild."""
        from ceph_tpu.chaos.rng import stream

        parts = parse_partitions(config.chaos_net_partition)
        if keep_partitions:
            parts |= keep_partitions
        rates = (config.chaos_net_drop, config.chaos_net_dup,
                 config.chaos_net_delay_prob, config.chaos_net_reorder,
                 config.chaos_net_reset,
                 config.chaos_net_batch_item_drop,
                 config.chaos_net_batch_ack_dup,
                 config.chaos_net_batch_ack_reorder)
        if not any(rates) and not parts:
            return None
        return cls(stream(config.chaos_seed, f"net:{name}"),
                   drop=config.chaos_net_drop, dup=config.chaos_net_dup,
                   delay=config.chaos_net_delay,
                   delay_prob=config.chaos_net_delay_prob,
                   reorder=config.chaos_net_reorder,
                   reset=config.chaos_net_reset, partitions=parts,
                   batch_item_drop=config.chaos_net_batch_item_drop,
                   batch_ack_dup=config.chaos_net_batch_ack_dup,
                   batch_ack_reorder=config.chaos_net_batch_ack_reorder)

    # -- partition management (scenario runner API) -------------------------

    def partition(self, *addrs: Addr) -> None:
        self.partitions.update(tuple(a) for a in addrs)

    def heal(self, *addrs: Addr) -> None:
        """Heal specific peers, or everything when called bare."""
        if addrs:
            self.partitions.difference_update(tuple(a) for a in addrs)
        else:
            self.partitions.clear()

    def partitioned(self, addr: Addr) -> bool:
        return tuple(addr) in self.partitions

    # -- messenger hooks ----------------------------------------------------

    def check_connect(self, addr: Addr) -> None:
        """Raises like a refused/blackholed TCP connect when the peer is
        behind a partition (called from Messenger.connect)."""
        if self.partitions and tuple(addr) in self.partitions:
            from ceph_tpu.chaos.counters import CHAOS

            CHAOS.inc("net_partition_blocks")
            raise ConnectionError(f"chaos: partition blocks {addr}")

    def on_frame(self, addr: Addr) -> FrameFate:
        """Decide this frame's fate; counters tick at decision time.
        Each enabled fault family consumes its own rng draws, so
        disabling one family never shifts another's stream."""
        from ceph_tpu.chaos.counters import CHAOS

        fate = FrameFate()
        rng = self.rng
        if self.drop and rng.random() < self.drop:
            fate.drop = True
            # the retransmission timer: the messenger schedules a
            # session replay after this, so loss is transient on a
            # healthy net and real under a partition
            fate.retransmit = rng.uniform(0.02, 0.2)
            CHAOS.inc("net_drops")
            return fate                  # a dropped frame has no other fate
        if self.delay_prob and rng.random() < self.delay_prob:
            fate.delay = rng.uniform(0.0, self.delay or 0.05)
            CHAOS.inc("net_delays")
        if self.reorder and rng.random() < self.reorder:
            fate.reorder = rng.uniform(0.005, max(0.01, self.delay or 0.05))
            CHAOS.inc("net_reorders")
            return fate                  # deferred: dup/reset don't stack
        if self.dup and rng.random() < self.dup:
            fate.dup = True
            CHAOS.inc("net_dups")
        if self.reset and rng.random() < self.reset:
            fate.reset = True
            CHAOS.inc("net_resets")
        return fate

    def mutate_batch(self, msg) -> None:
        """Per-item batch-frame faults (round 12), applied IN PLACE just
        before the frame is pickled for the wire — so session replay
        re-delivers the same mutated frame (the item loss is real, like
        a torn frame the transport reassembled short):

        - ``batch_item_drop``: each sub-write item inside a multi-item
          MOSDECSubOpWriteBatch is independently dropped while the rest
          of the frame delivers — a PARTIAL tick on the wire.  At least
          one item always survives (whole-frame loss is chaos_net_drop's
          job, with retransmission semantics).
        - ``batch_ack_dup``: entries of a batched ack are duplicated —
          the per-responder ack dedup must absorb them or a duplicate
          would stand in for a shard that never committed.
        - ``batch_ack_reorder``: the batched ack's result order is
          shuffled — ack handling must be order-independent.

        Each family consumes its own rng draws only when enabled, so
        toggling one never shifts another's stream."""
        from ceph_tpu.chaos.counters import CHAOS

        name = type(msg).__name__
        rng = self.rng
        if name == _BATCH_FRAME and self.batch_item_drop and \
                len(msg.items) > 1:
            kept = [it for it in msg.items
                    if rng.random() >= self.batch_item_drop]
            if not kept:
                kept = [msg.items[rng.randrange(len(msg.items))]]
            dropped = len(msg.items) - len(kept)
            if dropped:
                CHAOS.inc("net_batch_item_drops", dropped)
                msg.items = kept
        elif name == _BATCH_REPLY and msg.results:
            if self.batch_ack_dup:
                out = []
                dups = 0
                for entry in msg.results:
                    out.append(entry)
                    if rng.random() < self.batch_ack_dup:
                        out.append(entry)
                        dups += 1
                if dups:
                    CHAOS.inc("net_batch_ack_dups", dups)
                    msg.results = out
            if self.batch_ack_reorder and \
                    rng.random() < self.batch_ack_reorder and \
                    len(msg.results) > 1:
                shuffled = list(msg.results)
                rng.shuffle(shuffled)
                CHAOS.inc("net_batch_ack_reorders")
                msg.results = shuffled


def ensure_injector(messenger) -> NetInjector:
    """The scenario runner's handle on a daemon messenger: returns the
    live injector, creating an all-zero-rate one (for partition-only
    scenarios) when chaos is currently disabled."""
    if messenger.chaos is None:
        from ceph_tpu.chaos.rng import stream

        seed = 0
        cfg = getattr(messenger, "config", None)
        if cfg is not None:
            seed = cfg.chaos_seed
        messenger.chaos = NetInjector(
            stream(seed, f"net:{messenger.name}"))
    return messenger.chaos
