"""graft-trace: causal span tracing across daemons.

The reference threads blkin/Zipkin-style tracepoints through
Messenger -> OSD -> ObjectStore so one client op can be followed as a
single timed tree across daemons (PAPER.md L6).  This is that seam for
the asyncio port: a per-daemon :class:`Tracer` mints spans carrying a
``trace_id`` (the op-lifecycle id the objecter already stamps into
message trace headers) plus a ``span_id``/``parent_id`` chain, and the
message header's ``"span"`` field propagates causality across the wire —
the receiving daemon parents its span under the sender's.

Contract (BENCH_NOTES "zero overhead when disabled"): at default config
(``trace_enabled=0``) ``Tracer.start`` returns the shared
:data:`NULL_SPAN` singleton — no allocation, no retention, no
contextvar churn beyond one ``enabled`` test — and ``Tracer.context()``
returns ``None`` so no message ever grows a span field.  The tracer is
therefore provably a no-op on the bench hot path, the same contract the
chaos injectors honor.

Spans are collected PER DAEMON (each tracer keeps its own completed
spans ring, keyed by trace_id) exactly like a real distributed tracer's
per-process reporter; ``assemble_tree`` merges the per-daemon dumps
into the one cross-daemon tree, and ``ceph_tpu.trace.perfetto`` renders
it for chrome://tracing / Perfetto.
"""

from __future__ import annotations

import contextvars
import itertools
import time
from collections import OrderedDict
from typing import Dict, List, Optional

# the span currently open on this task's context: children parent under
# it and Tracer.context() exports it into message headers.  ContextVars
# keep interleaved ops (and daemons sharing one loop) from cross-linking.
CURRENT_SPAN: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("ceph_tpu_current_span", default=None)


class Span:
    """One timed node of a trace tree.  Usable as a context manager:
    entering installs it as CURRENT_SPAN (so nested spans and outgoing
    messages parent under it), exiting finishes it."""

    __slots__ = ("_tracer", "trace_id", "span_id", "parent_id", "name",
                 "daemon", "start", "end", "meta", "_token")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.daemon = tracer.daemon
        self.start = time.time()
        self.end: Optional[float] = None
        self.meta: Dict = {}
        self._token = None

    def annotate(self, **kv) -> None:
        self.meta.update(kv)

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()
            self._tracer._finished(self)

    def dump(self) -> Dict:
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "daemon": self.daemon,
            "start": self.start,
            "dur": (self.end - self.start) if self.end is not None
            else None,
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        return out

    def __enter__(self) -> "Span":
        self._token = CURRENT_SPAN.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            CURRENT_SPAN.reset(self._token)
            self._token = None
        self.finish()
        return False


class _NullSpan:
    """The disabled-tracing span: every operation is a no-op.  One
    shared instance — the disabled path allocates nothing per op."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def annotate(self, **kv) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


class Tracer:
    """Per-daemon span factory + completed-span collector."""

    def __init__(self, daemon: str, enabled: bool = False,
                 keep: int = 256):
        self.daemon = daemon
        self.enabled = bool(enabled)
        self.keep = keep
        self._seq = itertools.count(1)
        self._tid = itertools.count(1)
        # trace_id -> [span dicts] of COMPLETED spans, oldest trace first
        self._traces: "OrderedDict[str, List[Dict]]" = OrderedDict()

    def mint_trace_id(self) -> str:
        return f"{self.daemon}:t{next(self._tid)}"

    def start(self, name: str, trace_id: Optional[str] = None,
              parent_id: Optional[str] = None):
        """Open a span.  With no explicit parent, nests under the task's
        CURRENT_SPAN (same-daemon causality); with no trace_id, joins
        the parent's trace or mints a fresh one (a root)."""
        if not self.enabled:
            return NULL_SPAN
        if parent_id is None:
            cur = CURRENT_SPAN.get()
            if cur is not None and cur.span_id is not None:
                parent_id = cur.span_id
                if trace_id is None:
                    trace_id = cur.trace_id
        if trace_id is None:
            trace_id = self.mint_trace_id()
        return Span(self, trace_id, f"{self.daemon}:s{next(self._seq)}",
                    parent_id, name)

    def context(self) -> Optional[Dict]:
        """The propagation header for an outgoing message: the current
        span's (trace_id, span_id), or None when tracing is off / no
        span is open — so a disabled tracer never grows a message."""
        if not self.enabled:
            return None
        cur = CURRENT_SPAN.get()
        if cur is None or cur.span_id is None:
            return None
        return {"id": cur.trace_id, "span": cur.span_id}

    def _finished(self, span: Span) -> None:
        self._traces.setdefault(span.trace_id, []).append(span.dump())
        while len(self._traces) > self.keep:
            self._traces.popitem(last=False)

    # -- dump surfaces (admin socket `trace dump` / `trace recent`) --------

    def dump_trace(self, trace_id: str) -> List[Dict]:
        return list(self._traces.get(trace_id, []))

    def dump_recent(self, n: int = 20) -> Dict[str, List[Dict]]:
        tids = list(self._traces)[-n:]
        return {tid: list(self._traces[tid]) for tid in tids}


def assemble_tree(spans: List[Dict]) -> List[Dict]:
    """Merge per-daemon span dumps of ONE trace into the cross-daemon
    tree: returns the root spans, each with a ``children`` list, sorted
    by start time.  Spans whose parent is absent (a daemon's ring
    trimmed it) surface as roots rather than vanishing."""
    by_id = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots: List[Dict] = []
    for s in by_id.values():
        parent = by_id.get(s["parent_id"]) if s["parent_id"] else None
        if parent is not None:
            parent["children"].append(s)
        else:
            roots.append(s)
    def _sort(nodes: List[Dict]) -> None:
        nodes.sort(key=lambda n: n["start"])
        for n in nodes:
            _sort(n["children"])
    _sort(roots)
    return roots
