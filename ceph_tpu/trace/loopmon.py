"""Asyncio event-loop profiler: sampled loop lag + task queue/wall time.

Every daemon here is one asyncio event loop; PR 4's chaos runs proved
head-of-line blocking in the messenger read loop is a real bug class,
and graftlint's asyncio sanitizer only catches the STATIC shape of it.
This is the runtime half: a sampler task measures how late the loop
wakes a timer (loop lag — the time some callback held the loop), and
``wrap()`` instruments spawned per-op tasks with spawn counts, queued
time (create -> first run) and wall time, all as ordinary perf counters
so they ride the existing mgr report / Prometheus / daemonperf paths.

Disabled (``loop_profile_interval=0``, the default) the profiler
declares nothing, samples nothing, and ``wrap()`` returns the coroutine
untouched — the zero-overhead-at-default contract shared with
graft-trace and the chaos injectors.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple

from ceph_tpu.utils import perf as perfmod


class LoopProfiler:
    def __init__(self, perf, interval: float, prefix: str = "loop"):
        self.perf = perf
        self.interval = interval
        self.prefix = prefix
        self.enabled = interval > 0
        self.last_lag = 0.0
        # max lag since the last beacon window reset: the "sustained
        # lag" signal the LOOP_LAG health warning keys off
        self.window_max = 0.0
        if self.enabled:
            perf.add_time(f"{prefix}_lag", prio=perfmod.PRIO_INTERESTING,
                          desc="sampled event-loop wakeup lag")
            perf.add_histogram(
                f"{prefix}_lag_hist", scale=1e6,
                unit=perfmod.UNIT_SECONDS,
                desc="event-loop lag, log2 microsecond buckets")
            perf.add_u64(f"{prefix}_task_spawns",
                         desc="profiled tasks spawned")
            perf.add_time(f"{prefix}_task_queued",
                          desc="task create -> first-run delay")
            perf.add_time(f"{prefix}_task_wall",
                          desc="profiled task wall time")

    async def sample(self) -> None:
        """The sampler coroutine; the owning daemon creates (and tracks)
        the task.  Each round sleeps ``interval`` and records how far
        past the deadline the loop actually woke us."""
        loop = asyncio.get_event_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            lag = max(0.0, loop.time() - t0 - self.interval)
            self.last_lag = lag
            if lag > self.window_max:
                self.window_max = lag
            self.perf.tinc(f"{self.prefix}_lag", lag)
            self.perf.hinc(f"{self.prefix}_lag_hist", lag)

    def lag_report(self) -> Optional[Tuple[float, float]]:
        """(last_sample, window_max) for the beacon, or None when the
        profiler is off (the beacon field stays absent)."""
        if not self.enabled:
            return None
        return (self.last_lag, self.window_max)

    def reset_window(self) -> None:
        """Called after each beacon: the next window measures afresh, so
        a drained stall clears the health warning."""
        self.window_max = 0.0

    def wrap(self, coro):
        """Instrument a to-be-spawned coroutine: spawn count, queued
        delay (create -> first run), wall time.  Identity when off."""
        if not self.enabled:
            return coro
        loop = asyncio.get_event_loop()
        created = loop.time()
        self.perf.inc(f"{self.prefix}_task_spawns")

        async def _run():
            t0 = loop.time()
            self.perf.tinc(f"{self.prefix}_task_queued", t0 - created)
            try:
                return await coro
            finally:
                self.perf.tinc(f"{self.prefix}_task_wall",
                               loop.time() - t0)

        return _run()
