"""graft-blackbox: the always-on per-daemon flight recorder.

A bounded ring of structured events every daemon feeds as it runs —
op-lifecycle samples, queue-depth/admission/cwnd samples, map-epoch
applies and peering kicks, health transitions, chaos injections and
crash points, scrub/repair detections, LOOP_LAG spikes.  The ring is
the cluster's black box: it costs a deque append while everything is
healthy and becomes the postmortem's raw material the moment a gate
breaks (``ceph_tpu/trace/postmortem.py`` snapshots every daemon's ring
into one bundle).

Clock contract: events are stamped on the daemon's OWN (possibly
chaos-skewed) clock, and ``dump()`` records the skew alongside the
events — so a postmortem consumer subtracts it and the rings of a
skewed cluster still merge onto one cluster-wide timeline, exactly the
way the reference correlates daemon logs via their recorded clock
offsets.

No-op contract (the chaos-injector/graft-trace shape): with
``blackbox_enabled=0`` (the default) ``FlightRecorder.from_config``
returns the shared ``NULL_FLIGHT`` singleton — falsy, ``__slots__`` of
nothing, every method a constant — and feed sites guard with one
``if self.flight:`` test, so the disabled hot path allocates nothing
and retains nothing (pinned by tests/test_blackbox.py the way the
NULL_SPAN pin test guards the tracer).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional


class _NullFlight:
    """Shared disabled recorder: one falsy test at every feed site,
    zero allocation, zero retention (the NULL_SPAN analog)."""

    __slots__ = ()

    enabled = False
    daemon = ""
    dropped = 0

    def __bool__(self) -> bool:
        return False

    def record(self, kind: str, **data) -> None:
        pass

    def op_sample(self, desc: str, duration: float,
                  slow: bool = False) -> None:
        pass

    def events(self) -> List:
        return []

    def dump(self) -> Dict:
        return {"enabled": False, "daemon": "", "skew": 0.0,
                "dropped": 0, "capacity": 0, "events": []}


NULL_FLIGHT = _NullFlight()


class FlightRecorder:
    """Bounded per-daemon event ring (the enabled path).

    ``clock`` is the daemon's ChaosClock (or None for clients without
    one — plain wall time, zero skew).  ``capacity`` bounds memory
    hard: the deque drops the oldest event per overflow append and
    ``dropped`` counts what the ring forgot, so a postmortem reader
    knows when the breach outran the box.
    """

    __slots__ = ("daemon", "clock", "ring", "dropped", "sample_every",
                 "_seq", "_op_n")

    enabled = True

    def __init__(self, daemon: str, capacity: int = 512,
                 sample_every: int = 8, clock=None):
        self.daemon = daemon
        self.clock = clock
        self.ring: deque = deque(maxlen=max(1, int(capacity)))
        self.dropped = 0
        self.sample_every = max(1, int(sample_every))
        self._seq = 0
        self._op_n = 0

    def __bool__(self) -> bool:
        return True

    @classmethod
    def from_config(cls, daemon: str, config, clock=None):
        """The per-daemon factory every constructor calls: the shared
        NULL_FLIGHT when ``blackbox_enabled=0`` (provable no-op), a
        real ring sized by ``blackbox_ring`` otherwise."""
        if not getattr(config, "blackbox_enabled", 0):
            return NULL_FLIGHT
        return cls(daemon,
                   capacity=getattr(config, "blackbox_ring", 512),
                   sample_every=getattr(config, "blackbox_sample", 8),
                   clock=clock)

    # -- feeds ---------------------------------------------------------------

    def _now(self) -> float:
        return self.clock.time() if self.clock is not None \
            else time.time()

    def record(self, kind: str, **data) -> None:
        """Append one structured event, stamped on the daemon's own
        (possibly skewed) clock.  Overflow drops the oldest event and
        counts it — memory stays bounded under any flood."""
        self._seq += 1
        if len(self.ring) == self.ring.maxlen:
            self.dropped += 1
        self.ring.append((self._seq, self._now(), kind, data))

    def op_sample(self, desc: str, duration: float,
                  slow: bool = False) -> None:
        """Op-lifecycle feed: every ``sample_every``-th completed op
        (slow ops always — they are exactly what a postmortem wants)."""
        self._op_n += 1
        if slow or self._op_n % self.sample_every == 0:
            self.record("op", desc=desc, dur=round(duration, 6),
                        slow=bool(slow))

    # -- dump surfaces -------------------------------------------------------

    def events(self) -> List:
        return list(self.ring)

    def dump(self) -> Dict:
        """The ``blackbox dump`` admin payload: the ring plus the
        recorded clock offset (``skew``) a consumer subtracts to align
        this daemon's stamps with the rest of the cluster."""
        skew = float(getattr(self.clock, "skew", 0.0)) \
            if self.clock is not None else 0.0
        return {
            "enabled": True,
            "daemon": self.daemon,
            "skew": skew,
            "dropped": self.dropped,
            "capacity": self.ring.maxlen,
            "events": [
                {"seq": seq, "t": round(t, 6), "kind": kind,
                 "data": data}
                for seq, t, kind, data in self.ring],
        }


def merged_timeline(daemon_dumps: Dict[str, Dict],
                    limit: Optional[int] = None) -> List[Dict]:
    """Merge per-daemon ``dump()`` payloads onto one skew-corrected
    cluster timeline (newest-last).  The postmortem report's spine."""
    out: List[Dict] = []
    for name in sorted(daemon_dumps):
        d = daemon_dumps[name] or {}
        skew = float(d.get("skew", 0.0))
        for ev in d.get("events", ()):
            out.append({"t": round(ev["t"] - skew, 6),
                        "daemon": d.get("daemon") or name,
                        "kind": ev["kind"],
                        "data": ev.get("data", {})})
    out.sort(key=lambda e: e["t"])
    return out[-limit:] if limit else out
