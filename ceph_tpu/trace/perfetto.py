"""Perfetto / chrome://tracing JSON export.

Renders graft-trace data in the Chrome Trace Event format (the
``{"traceEvents": [...]}`` JSON both chrome://tracing and Perfetto
load): per-daemon process lanes, one thread lane per op, complete
("ph": "X") slices per stage or span.  Two sources:

- ``chrome_trace_from_dumps``: ``dump_historic_ops`` payloads from one
  or more daemons (always available — the event timeline is always-on);
- ``chrome_trace_from_spans``: completed Tracer spans of one trace
  (available when ``trace_enabled=1``), nested by parent links.

Pure functions over plain dicts so ``scripts/trace.py convert`` works
from a saved dump file with no cluster (and no jax import) in sight.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from ceph_tpu.trace.attribution import spans_from_events


def _meta(pid: int, name: str) -> Dict:
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}


def chrome_trace_from_dumps(dumps: Dict[str, Dict]) -> Dict:
    """``{daemon_name: dump_historic_ops_payload}`` -> chrome trace.

    Each daemon becomes a process lane; each op a thread lane (named by
    its trace id / description); each inter-event stage a slice."""
    events: List[Dict] = []
    for pid, daemon in enumerate(sorted(dumps), start=1):
        events.append(_meta(pid, daemon))
        ops = dumps[daemon].get("ops", [])
        for tid, op in enumerate(ops, start=1):
            label = op.get("trace_id") or op.get("description", f"op{tid}")
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": label}})
            evs = [(e["time"], e["event"])
                   for e in op.get("type_data", {}).get("events", [])]
            for sp in spans_from_events(evs):
                events.append({
                    "name": sp["event"], "cat": sp["stage"], "ph": "X",
                    "pid": pid, "tid": tid,
                    "ts": round(sp["start"] * 1e6, 3),
                    "dur": round(sp["dur"] * 1e6, 3),
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def chrome_trace_from_spans(spans: Sequence[Dict]) -> Dict:
    """Completed span dicts (one trace, any number of daemons) ->
    chrome trace: process lane per daemon, slices at absolute wall
    timestamps so cross-daemon causality lines up on one axis."""
    daemons = sorted({s["daemon"] for s in spans})
    pid_of = {d: i for i, d in enumerate(daemons, start=1)}
    base = min((s["start"] for s in spans), default=0.0)
    events: List[Dict] = [_meta(pid, d) for d, pid in pid_of.items()]
    for s in spans:
        events.append({
            "name": s["name"], "cat": s.get("trace_id", ""), "ph": "X",
            "pid": pid_of[s["daemon"]], "tid": 1,
            "ts": round((s["start"] - base) * 1e6, 3),
            "dur": round((s["dur"] or 0.0) * 1e6, 3),
            "args": {"span_id": s["span_id"],
                     "parent_id": s["parent_id"]},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write(path: str, doc: Dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
