"""graft-blackbox postmortems: triggered bundles + breach attribution.

When a judge convicts — an SLO gate fails, a chaos invariant convicts,
a crash point fires, or the mon transitions to HEALTH_ERR — the cluster
snapshots its black boxes into ONE bundle: every daemon's flight ring
(via the ``blackbox dump`` admin command), every OSD's historic-op
rings, the mgr Prometheus scrape, and the mon's health history.  The
bundle is a plain JSON document (``POSTMORTEM_*.json``) diagnosable
with no cluster in sight.

``breach_report`` reconstructs the breach window from a bundle: the
late/convicted op set, its per-stage wall attribution (reusing
``trace/attribution.py`` — the acceptance bar is wall_coverage >= 0.9
over the breach set), and a top-suspects table (daemon/stage/seconds).
``scripts/blackbox.py report`` renders it; ``chrome_trace`` exports the
bundle's op timelines through the existing Perfetto writer.

Determinism: a bundle's content includes wall stamps (they vary run to
run by construction), so the seeded-replay witness is ``replay_key`` —
a hash over the bundle's deterministic projection (trigger kind+reason,
daemon set, failing gate names/thresholds, seed) — the same contract
chaos ``Verdict.replay_key`` uses to exclude wire-level counters.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Sequence

TRIGGER_KINDS = ("slo_gate", "chaos_conviction", "crash_point",
                 "health_err")

BUNDLE_KIND = "graft-blackbox-postmortem"

# per-daemon admin command timeout during collection: a bundle is taken
# while the cluster may be mid-chaos; a wedged daemon must cost seconds,
# not the default 30s, and its slot records the error instead
_COLLECT_TIMEOUT = 5.0


# ------------------------------------------------------------ collection


async def _cmd(cluster, name: str, cmd) -> Dict:
    """One admin command with the collection timeout; failures become
    data (the daemon may be crashed — that IS postmortem evidence)."""
    try:
        return {"ok": True,
                "data": await cluster.daemon_command(
                    name, cmd, timeout=_COLLECT_TIMEOUT)}
    except Exception as e:  # noqa: BLE001 - a dead daemon is evidence
        return {"ok": False, "error": repr(e)}


async def collect_bundle(cluster, kind: str, reason: str,
                         detail: Optional[Dict] = None,
                         clients: Sequence = ()) -> Dict:
    """Snapshot the cluster's black boxes into one bundle dict.

    ``clients`` are Objecter instances (clients have no admin socket —
    their rings are read directly).  Every per-daemon fetch tolerates
    that daemon being dead: plain chaos scenarios run without a mgr,
    and a crash-point bundle is taken with its victim already down.
    """
    daemons: Dict[str, Dict] = {}
    historic: Dict[str, Dict] = {}
    names = [f"osd.{i}" for i in sorted(cluster.osds)]
    names += [f"mon.{m.rank}" for m in cluster.mons]
    if cluster.mgr is not None:
        names.append("mgr")
    for name in names:
        r = await _cmd(cluster, name, "blackbox dump")
        if r["ok"]:
            # flatten the admin payload to the flight dump shape (the
            # same shape client rings use), critical perf riding along
            data = r["data"] or {}
            daemons[name] = {**(data.get("flight") or {}),
                             "perf_critical": data.get("perf_critical")}
        else:
            daemons[name] = {"error": r["error"]}
        if name.startswith("osd."):
            ops = await _cmd(cluster, name, "dump_historic_ops")
            slow = await _cmd(cluster, name, "dump_historic_slow_ops")
            historic[name] = {
                "ops": r2["data"] if (r2 := ops)["ok"]
                else {"error": r2["error"]},
                "slow": r3["data"] if (r3 := slow)["ok"]
                else {"error": r3["error"]},
            }
    for c in clients:
        # Objecter or its RadosClient wrapper both accepted
        obj = getattr(c, "objecter", c)
        flight = getattr(obj, "flight", None)
        if flight is not None and flight:
            daemons[flight.daemon] = flight.dump()
    scrape = await _cmd(cluster, "mgr", "prometheus metrics") \
        if cluster.mgr is not None else {"ok": False,
                                         "error": "no mgr in cluster"}
    health = await _cmd(cluster, f"mon.{cluster.mons[0].rank}", "health")
    history = await _cmd(cluster, f"mon.{cluster.mons[0].rank}",
                         "health history")
    bundle = {
        "kind": BUNDLE_KIND,
        "trigger": {"kind": kind, "reason": reason,
                    "detail": detail or {}},
        "daemons": daemons,
        "historic_ops": historic,
        "mgr_scrape": scrape["data"] if scrape["ok"]
        else {"error": scrape["error"]},
        "health": health["data"] if health["ok"]
        else {"error": health["error"]},
        "health_history": history["data"] if history["ok"]
        else {"error": history["error"]},
    }
    bundle["breach"] = breach_report(bundle)
    return bundle


def write_bundle(bundle: Dict, out_dir: str,
                 tag: Optional[str] = None) -> str:
    """Write ``POSTMORTEM_<kind>_<tag>.json``.  The name is a pure
    function of the trigger (no wall stamps), so a seeded replay lands
    on the same path — collisions overwrite, which is exactly the
    replay semantics we want."""
    trig = bundle.get("trigger", {})
    if tag is None:
        tag = hashlib.sha256(
            str(trig.get("reason", "")).encode()).hexdigest()[:10]
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", tag)
    path = os.path.join(
        out_dir, f"POSTMORTEM_{trig.get('kind', 'unknown')}_{safe}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(bundle, f, indent=1, sort_keys=True)
    return path


def load_bundle(path: str) -> Dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("kind") != BUNDLE_KIND:
        raise ValueError(f"{path}: not a {BUNDLE_KIND} bundle")
    return doc


# ---------------------------------------------------------- breach report


def _breach_ops(bundle: Dict) -> List[Dict]:
    """The breach set: every historic slow op, else the slowest decile
    (at least one) of completed ops — the late/convicted ops the
    attribution must cover."""
    slow: List[Dict] = []
    normal: List[Dict] = []
    for daemon, h in sorted(bundle.get("historic_ops", {}).items()):
        for bucket, out in (("slow", slow), ("ops", normal)):
            payload = h.get(bucket) or {}
            for op in payload.get("ops", ()) \
                    if isinstance(payload, dict) else ():
                if op.get("duration"):
                    out.append({**op, "daemon": daemon})
    if slow:
        return slow
    normal.sort(key=lambda op: -op["duration"])
    return normal[:max(1, len(normal) // 10)]


def breach_report(bundle: Dict) -> Dict:
    """Per-stage attribution + top suspects over the breach set.

    Reuses ``trace/attribution.py`` exactly as ``bench.py --attribute``
    does: each op's event timeline is sliced into stage deltas;
    ``measured_wall_s`` is the breach set's mean client-visible
    duration, so ``wall_coverage`` reports the fraction of the late
    ops' wall the timelines explain (acceptance: >= 0.9)."""
    from ceph_tpu.trace.attribution import aggregate, attribute_events

    ops = _breach_ops(bundle)
    event_lists = []
    suspects: Dict[tuple, Dict] = {}
    for op in ops:
        evs = [(e["time"], e["event"])
               for e in op.get("type_data", {}).get("events", ())]
        if len(evs) < 2:
            continue
        event_lists.append(evs)
        stages, _total = attribute_events(evs)
        if not stages:
            continue
        top_stage, top_s = max(stages.items(), key=lambda kv: kv[1])
        m = re.search(r"\b(\d+\.[0-9a-fx]+)\b",
                      str(op.get("description", "")))
        key = (op["daemon"], m.group(1) if m else "-", top_stage)
        row = suspects.setdefault(
            key, {"daemon": key[0], "pg": key[1], "stage": key[2],
                  "ops": 0, "seconds": 0.0,
                  "example": op.get("description", "")})
        row["ops"] += 1
        row["seconds"] = round(row["seconds"] + top_s, 6)
    wall = sum(op["duration"] for op in ops) / len(ops) if ops else None
    report = aggregate(event_lists, measured_wall_s=wall)
    ranked = sorted(suspects.values(),
                    key=lambda r: -r["seconds"])[:10]
    return {"breach_ops": len(ops), "attribution": report,
            "suspects": ranked}


def replay_key(bundle: Dict) -> str:
    """Seeded-replay witness: sha256 over the bundle's DETERMINISTIC
    projection.  Wall stamps, durations, and wire-level counters vary
    with async timing (the Verdict.replay_key precedent excludes them);
    what must match bit-for-bit across two runs of one seed is the
    trigger identity, the daemon set, and the failing gates'
    names/thresholds."""
    trig = bundle.get("trigger", {})
    detail = trig.get("detail", {}) or {}
    gates = detail.get("gates", ())
    proj = {
        "kind": trig.get("kind"),
        "reason": trig.get("reason"),
        "daemons": sorted(bundle.get("daemons", {})),
        "gates": sorted(
            (g.get("gate"), g.get("threshold")) for g in gates
            if isinstance(g, dict)),
        "seed": detail.get("seed"),
        "name": detail.get("spec") or detail.get("scenario"),
    }
    blob = json.dumps(proj, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


# -------------------------------------------------------------- rendering


def chrome_trace(bundle: Dict) -> Dict:
    """Perfetto/chrome-trace export of the bundle's op timelines
    through the existing ``trace/perfetto.py`` writer, with the flight
    rings folded in as instant events on each daemon's lane."""
    from ceph_tpu.trace.flight import merged_timeline
    from ceph_tpu.trace.perfetto import chrome_trace_from_dumps

    dumps = {}
    for daemon, h in sorted(bundle.get("historic_ops", {}).items()):
        ops = h.get("ops")
        if isinstance(ops, dict) and "ops" in ops:
            dumps[daemon] = ops
    doc = chrome_trace_from_dumps(dumps)
    timeline = merged_timeline(
        {n: d for n, d in bundle.get("daemons", {}).items()
         if isinstance(d, dict) and d.get("events") is not None})
    base = timeline[0]["t"] if timeline else 0.0
    pids = {}
    for ev in timeline:
        pid = pids.setdefault(ev["daemon"], 1000 + len(pids))
        doc["traceEvents"].append({
            "name": ev["kind"], "ph": "i", "s": "p",
            "pid": pid, "tid": 0,
            "ts": round((ev["t"] - base) * 1e6, 3),
            "args": ev.get("data", {})})
    return doc


def render_report(bundle: Dict, timeline_tail: int = 30) -> str:
    """The human breach report (``scripts/blackbox.py report``)."""
    from ceph_tpu.trace.flight import merged_timeline

    trig = bundle.get("trigger", {})
    lines = [
        f"postmortem: trigger={trig.get('kind')} "
        f"reason={trig.get('reason')}",
        f"replay_key: {replay_key(bundle)[:16]}",
    ]
    detail = trig.get("detail", {}) or {}
    for g in detail.get("gates", ()):
        if isinstance(g, dict):
            lines.append(
                f"  gate {g.get('gate')}: value={g.get('value')} "
                f"threshold={g.get('threshold')}")
    health = bundle.get("health", {})
    if isinstance(health, dict) and health.get("checks"):
        for name, msg in sorted(health["checks"].items()):
            lines.append(f"  health {name}: {msg}")
    breach = bundle.get("breach") or breach_report(bundle)
    rep = breach.get("attribution", {})
    lines.append(
        f"breach set: {breach.get('breach_ops', 0)} op(s), "
        f"wall_coverage={rep.get('wall_coverage', 'n/a')}")
    for stage, row in list(rep.get("stages", {}).items())[:8]:
        lines.append(f"  {stage:<20} {row['s']:>10.4f}s "
                     f"{row['frac'] * 100:5.1f}%")
    if breach.get("suspects"):
        lines.append("top suspects (daemon/pg/stage):")
        for s in breach["suspects"][:5]:
            lines.append(
                f"  {s['daemon']:<8} {s['pg']:<12} {s['stage']:<16} "
                f"{s['ops']} op(s) {s['seconds']:.4f}s")
    timeline = merged_timeline(
        {n: d for n, d in bundle.get("daemons", {}).items()
         if isinstance(d, dict) and d.get("events") is not None},
        limit=timeline_tail)
    if timeline:
        lines.append(f"cluster timeline (last {len(timeline)} events, "
                     f"skew-corrected):")
        base = timeline[0]["t"]
        for ev in timeline:
            data = " ".join(f"{k}={v}" for k, v in
                            sorted(ev["data"].items())[:4])
            lines.append(f"  +{ev['t'] - base:8.3f}s {ev['daemon']:<10} "
                         f"{ev['kind']:<12} {data}")
    return "\n".join(lines)
