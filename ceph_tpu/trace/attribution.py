"""Per-stage wall-time attribution over op event timelines.

A TrackedOp's event list is a monotone timeline from the objecter's
submit stamp to the OSD's ``done``.  Attribution slices that timeline
into consecutive deltas and labels each delta with the STAGE reached by
its closing event, so every traced nanosecond lands in exactly one
bucket — coverage of the traced window is 100% by construction, and the
only unaccounted wall time is outside the instrumented path (reply
flight back to the client + client wakeup), which the caller measures
as ``wall_coverage`` against the client-observed latency.

This is the instrument ROADMAP items 1-2 are blocked on: the
``cluster_io_*`` benches run ~1000x below the device kernels, and this
module answers "where does each millisecond actually go" per stage —
dispatch-queue wait, PG-lock wait, device encode, store commit,
sub-write fan-out — aggregated across completed ops
(``dump_op_attribution`` admin command, ``bench.py --attribute``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

# stage reached by an event (the delta ENDING at that event belongs to
# the stage).  Events absent here fall through the prefix rules below.
EVENT_STAGE = {
    "objecter:submit": "client",
    "objecter:send": "client",
    "osd:arrival": "wire",
    "initiated": "dispatch_queue",
    "dispatched": "dispatch_queue",
    "ec_encode": "op_prepare",
    "ec_encoded": "device_encode",
    "store:journal_queued": "store_commit",
    "store:commit": "store_commit",
    "ec_sub_write_sent": "sub_write_send",
    "sub_op_sent": "sub_write_send",
    "sub_write_acked": "sub_write_wait",
    "sub_op_acked": "sub_write_wait",
    "ec_sub_read_sent": "sub_read_send",
    "sub_read_acked": "sub_read_wait",
    "commit": "commit",
    "done": "reply",
    "dup_reply_from_cache": "dup_cache",
    "dup_refused_from_log": "dup_cache",
    # overload-regime stages (round 10): client congestion-window wait,
    # dead-work shed at dequeue, straggler hedge on degraded EC reads —
    # so wall_coverage holds with backpressure enabled (bench.py
    # --attribute books throttle waits instead of losing them to "wire")
    "objecter:throttle_wait": "throttle_wait",
    "shed_expired": "shed",
    "ec_hedge_sent": "hedge",
    # batched data plane (round 11): an EC write parks at the encode
    # coalescer until its dispatch tick (batch_wait = queued-for-tick +
    # the other ops' share of the coalesced encode) and then books its
    # AMORTIZED share of the tick's device dispatch (batch_encode) —
    # so wall_coverage holds with sharded dispatch + coalescing on
    "batch_parked": "op_prepare",
    "batch_tick": "batch_wait",
    "batch_encoded": "batch_encode",
    # verified batched reads (round 16): the read twin — a gather's
    # decode parks at the read coalescer until its tick and books the
    # amortized share of the fused decode, so wall_coverage holds on
    # the read path with coalescing + verify-on-read enabled
    "read_batch_parked": "op_prepare",
    "read_batch_tick": "read_batch_wait",
    "read_batch_decoded": "batch_decode",
    # reply-leg tail (round 11): the delta from the reply's client-side
    # recv stamp to the caller actually resuming — event-loop wakeup,
    # previously the untraced slice of wall_coverage
    "objecter:complete": "client_wakeup",
    # client-edge batching (round 18): an op parked at the objecter's
    # per-(session, OSD) tick coalescer books queued-for-tick time
    # (client_batch_wait) plus its AMORTIZED share of the tick's frame
    # build/send (client_batch_send) — the client twin of
    # batch_wait/batch_encode, so wall_coverage holds with
    # objecter_batch_tick_ops > 0
    "objecter:batch_tick": "client_batch_wait",
    "objecter:batch_sent": "client_batch_send",
    # planar at rest (round 19): the two SANCTIONED layout hops — the
    # coalesced encode's client-bytes -> planes ingest and the read
    # assemble's planes -> client-bytes egress — book as planar_convert
    # so `bench.py --attribute` shows exactly what the at-rest format
    # costs (steady-state shard traffic between them is conversion-free
    # by contract; the pinned counter proves it)
    "planar_ingest": "planar_convert",
    "planar_egress": "planar_convert",
}


def stage_for(event: str) -> str:
    s = EVENT_STAGE.get(event)
    if s is not None:
        return s
    if event.startswith("lock_acquired:"):
        return f"lock:{event.split(':', 1)[1]}"
    if event.startswith("lock_wait:"):
        # the delta reaching the wait mark is execution BEFORE the lock
        return "exec"
    if event.startswith("throttle:"):
        # messenger byte-throttle acquire stamp (throttle:<daemon>:
        # acquired): the delta from recv to here is budget wait
        return "throttle_wait"
    if event.startswith("msgr:"):
        return "wire" if event.endswith(":recv") else "messenger_send"
    if event.startswith("shard:"):
        # sharded dispatch stamps (shard:<idx>:queued / :tick): the
        # delta reaching the tick stamp is time parked in the shard
        # queue awaiting its dispatch tick
        return "batch_wait" if event.endswith(":tick") \
            else "dispatch_queue"
    return f"other:{event}"


def attribute_events(
        events: Sequence[Tuple[float, str]]) -> Tuple[Dict[str, float], float]:
    """(stage -> seconds, traced_total).  ``events`` are (time, name)
    pairs on one op's timeline (any consistent clock); deltas between
    consecutive events are labeled by the closing event's stage.  The
    stage sums always add up to ``traced_total`` exactly."""
    evs = sorted(events, key=lambda e: e[0])
    stages: "OrderedDict[str, float]" = OrderedDict()
    for (t0, _), (t1, name) in zip(evs, evs[1:]):
        stage = stage_for(name)
        stages[stage] = stages.get(stage, 0.0) + max(0.0, t1 - t0)
    total = max(0.0, evs[-1][0] - evs[0][0]) if len(evs) > 1 else 0.0
    return stages, total


def spans_from_events(
        events: Sequence[Tuple[float, str]]) -> List[Dict]:
    """The timeline as stage-labeled spans (for dump_historic_ops and
    the Perfetto export): one span per inter-event delta, rebased so
    the first event is t=0."""
    evs = sorted(events, key=lambda e: e[0])
    if not evs:
        return []
    base = evs[0][0]
    out: List[Dict] = []
    for (t0, _), (t1, name) in zip(evs, evs[1:]):
        out.append({"stage": stage_for(name), "event": name,
                    "start": round(t0 - base, 6),
                    "dur": round(max(0.0, t1 - t0), 6)})
    return out


def _report(sums: Dict[str, float], total: float, n: int,
            measured_wall_s: Optional[float]) -> Dict:
    """The one report shape (stage sums + fractions + coverage) shared
    by per-daemon aggregation and the cross-daemon merge, so the two
    artifacts can never diverge in rounding or formula."""
    out: Dict = {
        "ops": n,
        "traced_total_s": round(total, 6),
        "stages": OrderedDict(
            (stage, {"s": round(s, 6),
                     "frac": round(s / total, 4) if total else 0.0})
            for stage, s in sorted(sums.items(), key=lambda kv: -kv[1])),
    }
    if measured_wall_s and n:
        out["measured_wall_s"] = round(measured_wall_s, 6)
        out["wall_coverage"] = round((total / n) / measured_wall_s, 4)
    return out


def aggregate(event_lists: Sequence[Sequence[Tuple[float, str]]],
              measured_wall_s: Optional[float] = None) -> Dict:
    """Roll completed-op timelines into one per-stage breakdown.

    ``measured_wall_s``: the externally measured mean per-op wall time
    (client-observed latency); when given, ``wall_coverage`` reports
    what fraction of it the traced timeline accounts for — the
    bench acceptance metric (>= 0.9 on the cluster_io write bench)."""
    sums: "OrderedDict[str, float]" = OrderedDict()
    total = 0.0
    n = 0
    for events in event_lists:
        stages, t = attribute_events(events)
        if t <= 0.0:
            continue
        n += 1
        total += t
        for stage, s in stages.items():
            sums[stage] = sums.get(stage, 0.0) + s
    return _report(sums, total, n, measured_wall_s)


def merge_reports(reports: Sequence[Dict],
                  measured_wall_s: Optional[float] = None) -> Dict:
    """Merge per-daemon ``aggregate`` reports into one breakdown.

    A pool's PGs spread primaries across OSDs, so each daemon's tracker
    holds a DISJOINT slice of the workload's ops — coverage of the
    whole bench window needs the SUM of every daemon's report, not the
    biggest single one (a stage pathology confined to one OSD must not
    vanish from the artifact)."""
    sums: "OrderedDict[str, float]" = OrderedDict()
    total = 0.0
    n = 0
    for rep in reports:
        n += rep.get("ops", 0)
        total += rep.get("traced_total_s", 0.0)
        for stage, row in rep.get("stages", {}).items():
            sums[stage] = sums.get(stage, 0.0) + row["s"]
    return _report(sums, total, n, measured_wall_s)


def aggregate_tracker(tracker, match: Optional[str] = None,
                      measured_wall_s: Optional[float] = None) -> Dict:
    """Aggregate over an OpTracker's completed-op history (the
    ``dump_op_attribution`` admin payload).  ``match`` filters on the
    op description substring (e.g. 'write_full' to isolate the write
    bench from interleaved reads)."""
    ops = [op for op in tracker.history()
           if match is None or match in op.desc]
    return aggregate([op.events for op in ops],
                     measured_wall_s=measured_wall_s)


async def flush_op_history(cluster, size: int) -> None:
    """Empty every OSD's completed-op ring, restoring capacity
    ``size`` (injectargs 0 -> size through the admin socket).  The
    shared warm-up flush for attribution runs: XLA-compile ops from
    cache warming must never be attributed into a timing window
    (bench.py --attribute, scripts/trace.py attribute)."""
    for oid in cluster.osds:
        for n in (0, size):
            await cluster.daemon_command(
                f"osd.{oid}", {"prefix": "injectargs",
                               "args": {"osd_op_history_size": n}})
