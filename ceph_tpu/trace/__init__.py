"""graft-trace: cross-daemon span tracing + event-loop profiling.

The observability instrument for ROADMAP items 1-2 (the ~1000x
cluster/device gap): one client op becomes one cross-daemon tree of
timed spans, its event timeline rolls up into a per-stage wall-time
breakdown, and an asyncio profiler watches the loop the whole daemon
runs on.  Everything is a provable no-op at default config — the same
contract the chaos injectors honor — so the load-sensitive bench trust
model (BENCH_NOTES) is untouched.

- ``span``        Tracer/Span/NULL_SPAN, header propagation, tree assembly.
- ``attribution`` event timeline -> per-stage latency attribution.
- ``loopmon``     sampled event-loop lag + task queue/wall profiling.
- ``perfetto``    chrome://tracing / Perfetto JSON export.
- ``flight``      graft-blackbox per-daemon flight-recorder rings.
- ``postmortem``  triggered POSTMORTEM_* bundles + breach attribution.
"""

from ceph_tpu.trace.span import (  # noqa: F401
    CURRENT_SPAN,
    NULL_SPAN,
    Span,
    Tracer,
    assemble_tree,
)
from ceph_tpu.trace.attribution import (  # noqa: F401
    aggregate,
    aggregate_tracker,
    attribute_events,
    spans_from_events,
    stage_for,
)
from ceph_tpu.trace.loopmon import LoopProfiler  # noqa: F401
from ceph_tpu.trace.flight import (  # noqa: F401
    NULL_FLIGHT,
    FlightRecorder,
    merged_timeline,
)
