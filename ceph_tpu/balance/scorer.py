"""Device-batched upmap scoring: many candidate moves, one objective call.

The scalar anchor (``osdmap/balancer.py::calc_pg_upmaps``) walks overfull
OSDs one at a time and takes the first legal move per pass.  Here the
same per-iteration measurement (``deviation_stats`` reproduces the
anchor's deviation/target math bit-exactly — same dtypes, same op
order) feeds a cross-product candidate generator: every (overfull src,
PG slot on src, underfull dst) triple that the failure-domain walker
admits becomes a row in a flat candidate batch, and ALL rows are scored
in one vectorized call.

The objective is the exact change a single move makes to the balance
energy, closed-form so no re-mapping is needed per candidate:

    sum((counts - target)^2) changes by  2*(dev[dst] - dev[src] + 1)

when one PG slot moves src->dst (counts[src] -= 1, counts[dst] += 1).
Two secondary terms ride along with configurable weights: primary
balance (the same closed form over primary counts, applied when the
moved slot is the PG's primary) and projected-move bytes (a per-move
cost that penalizes churn).  With both weights at 0 — the default, and
the configuration the bit-exactness gate runs — the objective is purely
the anchor's fill-variance energy, so every accepted move strictly
decreases the quantity the anchor greedily descends.

Engine per backend (the crc32c.py idiom): CPU-backend hosts run the
numpy scorer; device backends run the whole batch as one fused jitted
call.  Either way ``KERNELS`` counts candidates scored so tests and the
mgr counter family can prove >= 1000 candidates per tick went through
the batched path.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osdmap.balancer import _failure_domains
from ceph_tpu.osdmap.osdmap import OSDMap, PGid
from ceph_tpu.utils.perf import KERNELS


# ---------------------------------------------------------------------------
# Measurement: bit-exact twin of the anchor's per-iteration math
# ---------------------------------------------------------------------------

@dataclass
class DeviationStats:
    """One iteration's worth of balance measurement.

    ``counts``/``target``/``deviation``/``ratio`` reproduce
    calc_pg_upmaps' arrays bit-exactly on identical inputs (the
    satellite gate); ``primary_counts`` extends the same measurement to
    primaries for the secondary objective term.
    """

    counts: np.ndarray          # (max_osd,) int64 PG slots per OSD
    primary_counts: np.ndarray  # (max_osd,) int64 primary PGs per OSD
    target: np.ndarray          # (max_osd,) float64
    deviation: np.ndarray       # (max_osd,) float64, 0 outside in-set
    ratio: np.ndarray           # (max_osd,) float64
    in_osds: np.ndarray         # (max_osd,) bool (weight > 0)
    total_slots: int
    placements: Dict[int, np.ndarray] = field(default_factory=dict)

    def overfull(self, max_deviation_ratio: float) -> List[int]:
        """Anchor's overfull set, most-deviant first."""
        return [int(o) for o in np.argsort(-self.deviation)
                if self.deviation[o] >= 1.0
                and self.ratio[o] > max_deviation_ratio]

    def underfull(self) -> List[int]:
        """Anchor's underfull set, most-starved first."""
        return [int(o) for o in np.argsort(self.deviation)
                if self.deviation[o] <= -0.999 and self.in_osds[o]]


def deviation_stats(m: OSDMap,
                    pool_ids: Optional[List[int]] = None,
                    ) -> Optional[DeviationStats]:
    """Measure fill deviation exactly as calc_pg_upmaps does.

    Returns None when the map carries no weight or no slots (the
    anchor's early-break condition).
    """
    pools = pool_ids if pool_ids is not None else list(m.pools)
    placements: Dict[int, np.ndarray] = {}
    counts = np.zeros(m.max_osd, dtype=np.int64)
    pcounts = np.zeros(m.max_osd, dtype=np.int64)
    total_slots = 0
    for pid in pools:
        up, upp = m.pool_mapping(pid)
        placements[pid] = up
        valid = up[(up >= 0) & (up < m.max_osd)]
        counts += np.bincount(valid, minlength=m.max_osd)
        pvalid = upp[(upp >= 0) & (upp < m.max_osd)]
        pcounts += np.bincount(pvalid, minlength=m.max_osd)
        total_slots += int((up != CRUSH_ITEM_NONE).sum())

    weights = np.asarray(m.osd_weight[: m.max_osd], dtype=np.float64)
    weights = weights * np.asarray(m.osd_exists[: m.max_osd],
                                   dtype=np.float64)
    wtotal = weights.sum()
    if wtotal <= 0 or total_slots == 0:
        return None
    target = weights / wtotal * total_slots
    in_osds = weights > 0
    deviation = np.where(in_osds, counts - target, 0.0)
    ratio = np.where(target > 0, deviation / np.maximum(target, 1e-9), 0)
    return DeviationStats(counts=counts, primary_counts=pcounts,
                          target=target, deviation=deviation, ratio=ratio,
                          in_osds=in_osds, total_slots=total_slots,
                          placements=placements)


# ---------------------------------------------------------------------------
# Candidate generation: the legal-move cross product, as flat arrays
# ---------------------------------------------------------------------------

@dataclass
class CandidateSet:
    """Flat arrays, one row per legal (pool, pg, src, dst) move."""

    pool: np.ndarray        # (C,) int64
    seed: np.ndarray        # (C,) int64 pg seed within the pool
    src: np.ndarray         # (C,) int64 overfull osd the slot leaves
    dst: np.ndarray         # (C,) int64 underfull osd it lands on
    is_primary: np.ndarray  # (C,) float64 1.0 when the slot is rank 0

    def __len__(self) -> int:
        return int(self.pool.shape[0])


def generate_candidates(m: OSDMap, stats: DeviationStats,
                        domains_by_pool: Dict[int, Dict[int, int]],
                        max_deviation_ratio: float = 0.05,
                        ) -> CandidateSet:
    """Enumerate every move the anchor's validity rules admit.

    A candidate pairs a PG slot on an overfull OSD with an underfull
    destination that (a) is not already a member of the PG and (b) does
    not share a failure domain with any OTHER member (the try_remap_rule
    constraint).  PGs already carrying pg_upmap/pg_upmap_items are
    skipped, exactly as the anchor skips them.
    """
    overfull = stats.overfull(max_deviation_ratio)
    underfull = stats.underfull()
    cpool: List[int] = []
    cseed: List[int] = []
    csrc: List[int] = []
    cdst: List[int] = []
    cprim: List[float] = []
    if not overfull or not underfull:
        return CandidateSet(*(np.zeros(0, dtype=np.int64) for _ in range(4)),
                            is_primary=np.zeros(0, dtype=np.float64))
    over_set = set(overfull)
    for pid, up in stats.placements.items():
        domains = domains_by_pool[pid]
        rows, cols = np.nonzero(np.isin(up, overfull))
        for r, c in zip(rows, cols):
            src = int(up[r, c])
            if src not in over_set:
                continue
            pgid = PGid(pid, int(r))
            if pgid in m.pg_upmap or pgid in m.pg_upmap_items:
                continue
            members = [int(v) for v in up[r] if v != CRUSH_ITEM_NONE]
            used_doms = {domains.get(o) for o in members if o != src}
            for dst in underfull:
                if dst in members:
                    continue
                if domains.get(dst) in used_doms:
                    continue
                cpool.append(pid)
                cseed.append(int(r))
                csrc.append(src)
                cdst.append(dst)
                cprim.append(1.0 if c == 0 else 0.0)
    return CandidateSet(
        pool=np.asarray(cpool, dtype=np.int64),
        seed=np.asarray(cseed, dtype=np.int64),
        src=np.asarray(csrc, dtype=np.int64),
        dst=np.asarray(cdst, dtype=np.int64),
        is_primary=np.asarray(cprim, dtype=np.float64),
    )


# ---------------------------------------------------------------------------
# Scoring: one vectorized objective call over the whole batch
# ---------------------------------------------------------------------------

def _default_engine() -> str:
    try:
        import jax
        return "numpy" if jax.default_backend() == "cpu" else "device"
    except Exception:  # jax absent/unimportable: numpy always works
        return "numpy"


@functools.lru_cache(maxsize=1)
def _device_scorer():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def score(dev_src, dev_dst, prim_src, prim_dst, is_prim, move_bytes,
              primary_weight, move_cost):
        d_fill = 2.0 * (dev_dst - dev_src + 1.0)
        d_prim = primary_weight * is_prim * (prim_dst - prim_src + 1.0)
        return d_fill + d_prim + move_cost * move_bytes

    return score


def score_candidates(stats: DeviationStats, cand: CandidateSet,
                     engine: Optional[str] = None,
                     primary_weight: float = 0.0,
                     move_cost: float = 0.0,
                     pg_bytes: float = 0.0) -> np.ndarray:
    """Objective delta per candidate; negative improves balance.

    With ``primary_weight == move_cost == 0`` this is exactly the change
    each move makes to sum((counts - target)^2) — the energy the scalar
    anchor descends — so the fill term alone decides, bit-exactly on the
    numpy engine.
    """
    n = len(cand)
    if n == 0:
        return np.zeros(0, dtype=np.float64)
    eng = engine or _default_engine()
    dev = stats.deviation
    pdev = stats.primary_counts.astype(np.float64)
    KERNELS.inc("balance_score_calls")
    KERNELS.inc("balance_candidates_scored", n)
    if eng == "device":
        out = _device_scorer()(
            dev[cand.src], dev[cand.dst], pdev[cand.src], pdev[cand.dst],
            cand.is_primary, np.full(n, float(pg_bytes)),
            float(primary_weight), float(move_cost))
        return np.asarray(out, dtype=np.float64)
    d_fill = 2.0 * (dev[cand.dst] - dev[cand.src] + 1.0)
    d_prim = primary_weight * cand.is_primary * \
        (pdev[cand.dst] - pdev[cand.src] + 1.0)
    return d_fill + d_prim + move_cost * float(pg_bytes)


# ---------------------------------------------------------------------------
# Move selection + the full optimizer loop
# ---------------------------------------------------------------------------

def _pick_moves(stats: DeviationStats, cand: CandidateSet,
                scores: np.ndarray, max_moves: int,
                ) -> List[Tuple[int, int, int, int]]:
    """Greedy conflict-aware selection from one scored batch.

    Walk candidates best-score first; accept a move only while its fill
    delta stays negative under the deviations ADJUSTED for moves already
    accepted this round (so a round of moves never overshoots), one move
    per PG, and never pour more into one underfull OSD than its original
    starvation (the anchor's taken_under cap).
    """
    order = np.argsort(scores, kind="stable")
    dev_adj = stats.deviation.copy()
    taken_under: Dict[int, int] = {}
    moved_pgs = set()
    picked: List[Tuple[int, int, int, int]] = []
    for i in order:
        if len(picked) >= max_moves:
            break
        if scores[i] >= 0:
            break  # sorted: nothing after this improves either
        src = int(cand.src[i])
        dst = int(cand.dst[i])
        key = (int(cand.pool[i]), int(cand.seed[i]))
        if key in moved_pgs:
            continue
        if taken_under.get(dst, 0) >= max(
                1, int(-stats.deviation[dst])):
            continue
        if 2.0 * (dev_adj[dst] - dev_adj[src] + 1.0) >= 0:
            continue  # earlier accepts already evened this pair out
        picked.append((key[0], key[1], src, dst))
        moved_pgs.add(key)
        taken_under[dst] = taken_under.get(dst, 0) + 1
        dev_adj[src] -= 1.0
        dev_adj[dst] += 1.0
    return picked


def calc_pg_upmaps_vectorized(
        m: OSDMap, pool_ids: Optional[List[int]] = None,
        max_deviation_ratio: float = 0.05,
        max_iterations: int = 30,
        max_moves: Optional[int] = None,
        engine: Optional[str] = None,
        primary_weight: float = 0.0,
        move_cost: float = 0.0,
        pg_bytes: float = 0.0,
) -> Tuple[Dict[PGid, List[Tuple[int, int]]], int]:
    """Vectorized drop-in for the scalar anchor.

    Mutates ``m.pg_upmap_items`` like the anchor and returns
    ``(changes, candidates_scored)``.  Each iteration re-measures via
    the batched per-pool placement dispatch, enumerates every legal
    move, scores the whole batch in one call, and accepts a
    conflict-free subset — so one iteration does the work of many
    anchor passes.
    """
    pools = pool_ids if pool_ids is not None else list(m.pools)
    changes: Dict[PGid, List[Tuple[int, int]]] = {}
    domains_by_pool = {pid: _failure_domains(m, m.pools[pid].crush_rule)
                       for pid in pools}
    budget = max_moves if max_moves is not None else 1 << 30
    scored_total = 0

    for _ in range(max_iterations):
        if budget <= 0:
            break
        stats = deviation_stats(m, pools)
        if stats is None:
            break
        cand = generate_candidates(m, stats, domains_by_pool,
                                   max_deviation_ratio)
        if len(cand) == 0:
            break
        scores = score_candidates(stats, cand, engine=engine,
                                  primary_weight=primary_weight,
                                  move_cost=move_cost, pg_bytes=pg_bytes)
        scored_total += len(cand)
        picked = _pick_moves(stats, cand, scores, budget)
        if not picked:
            break
        for pid, seed, src, dst in picked:
            pgid = PGid(pid, seed)
            m.pg_upmap_items.setdefault(pgid, []).append((src, dst))
            changes.setdefault(pgid, []).append((src, dst))
        budget -= len(picked)
    return changes, scored_total
