"""Elastic reshape: grow and drain as first-class resumable operations.

The reference reshapes a cluster through a choreography the operator
usually scripts by hand: ``osd crush add`` + boot for growth,
``osd out`` -> wait for clean PGs -> stop daemon -> ``osd purge`` for
removal.  Here each choreography is a ``ReshapeOp`` whose CURRENT PHASE
is recomputed from the observed osdmap every time it is advanced —
nothing but the goal (which OSD ids, which direction) lives in mgr
memory, so a mgr restart, a dropped tick, or a replayed schedule all
resume exactly where the map says the operation stands.

Ops advance when ``advance()`` runs — from the balancer loop when the
subsystem is enabled, and from every ``balance status``/``balance
grow``/``balance drain`` admin command when it is not (pull-driven, so
``mgr_balancer_enabled=0`` still means zero background activity).

Grow:   "osd grow" mon command mints the ids + CRUSH hosts in one
        Incremental -> phase ``waiting-up`` until every new id boots
        (the operator/scenario starts the daemons) -> ``done``.
Drain:  weight->0 via "osd out" (data drains under CRUSH) -> phase
        ``wait-clean`` until no PG maps onto the drained ids and health
        shows no degraded PGs -> ``wait-down`` until the daemons are
        stopped -> "osd purge" -> ``done``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple


@dataclass
class ReshapeOp:
    op_id: int
    kind: str                    # "grow" | "drain"
    osd_ids: Tuple[int, ...]     # grow: minted ids; drain: retiring ids
    phase: str = "created"
    detail: str = ""

    def status(self) -> Dict:
        return {"id": self.op_id, "kind": self.kind,
                "osds": list(self.osd_ids), "phase": self.phase,
                "detail": self.detail, "done": self.phase == "done"}


class Reshaper:
    def __init__(self, mgr):
        self.mgr = mgr
        self.ops: Dict[int, ReshapeOp] = {}
        self._next_id = 0

    # -- op creation ----------------------------------------------------------

    async def grow(self, count: int, osds_per_host: int = 1) -> Dict:
        """Mint ``count`` new OSD ids (+ CRUSH hosts) through the mon.
        Returns the op status carrying the new ids; the caller boots the
        daemons and polls until the op reports done."""
        data = await self.mgr.mon_command(
            {"prefix": "osd grow", "count": int(count),
             "osds_per_host": int(osds_per_host)}, timeout=10.0)
        self._next_id += 1
        op = ReshapeOp(self._next_id, "grow",
                       tuple(data["new_osds"]), phase="waiting-up")
        self.ops[op.op_id] = op
        self.mgr.perf.inc("mgr_reshape_grows")
        await self.advance()
        return op.status()

    async def drain_osds(self, osd_ids: List[int]) -> Dict:
        """Start draining ``osd_ids``: mark them out (weight->0) so CRUSH
        moves their data, then follow the map to purge.  ONE batched
        "osd out" — one epoch — so a PG whose whole acting set drains is
        a visible wholesale replacement the mon answers with a pg_temp
        mint, instead of N epochs whose acting set walks away from the
        data one just-joined survivor at a time.

        Named drain_osds, not drain: the lock-graph linter resolves
        calls by attribute name, and bare ``drain`` is asyncio's
        StreamWriter.drain — awaited under send locks everywhere."""
        await self.mgr.mon_command(
            {"prefix": "osd out", "ids": [int(o) for o in osd_ids]},
            timeout=10.0)
        self._next_id += 1
        op = ReshapeOp(self._next_id, "drain", tuple(int(o) for o in osd_ids),
                       phase="wait-clean")
        self.ops[op.op_id] = op
        self.mgr.perf.inc("mgr_reshape_drains")
        await self.advance()
        return op.status()

    # -- phase derivation ------------------------------------------------------

    async def _backfill_pending(self) -> str:
        """Recovery-health witness: weight->0 remaps PGs off the
        drained OSDs INSTANTLY, but the data only follows via backfill.
        Until PG_RECOVERING (pg_temp handoffs + per-OSD unclean beacons,
        pessimistic across placement epochs) clears, the drained
        daemons may hold the sole replica of acked bytes — stopping
        them then is acked-then-lost.  Unavailable health reads as
        pending (safe)."""
        try:
            health = await self.mgr.mon_command({"prefix": "health"},
                                                timeout=5.0)
        except (RuntimeError, TimeoutError, ConnectionError, OSError):
            return "health unavailable"
        checks = (health or {}).get("checks", {})
        hits = [c for c in ("PG_RECOVERING", "PG_DEGRADED",
                            "PG_UNDERSIZED") if c in checks]
        return ",".join(hits)

    def _pgs_on(self, osds: Tuple[int, ...]) -> int:
        """How many PG slots the current map still places on ``osds`` —
        up placements PLUS live pg_temp references: a temp entry naming
        a drained OSD means some PG's acting data still lives there
        (the handoff backfill hasn't finished), so purging it now is
        acked-then-lost no matter what the up arrays say."""
        m = self.mgr.osdmap
        if m is None:
            return -1
        import numpy as np

        tset = set(int(o) for o in osds)
        targets = np.asarray(osds, dtype=np.int64)
        n = 0
        for pid in m.pools:
            up, _ = m.pool_mapping(pid)
            n += int(np.isin(up, targets).sum())
        for temp in m.pg_temp.values():
            n += sum(1 for o in temp if o in tset)
        return n

    async def advance(self) -> List[Dict]:
        """Recompute every open op's phase from the observed map and
        take at most one mon action per op per call."""
        m = self.mgr.osdmap
        out = []
        for op in self.ops.values():
            if op.phase == "done" or m is None:
                out.append(op.status())
                continue
            if op.kind == "grow":
                # ids past our map's max_osd: the grow Incremental has
                # not reached our subscription yet — treat as not-up
                down = [o for o in op.osd_ids
                        if o >= len(m.osd_up) or not m.osd_up[o]]
                if down:
                    op.phase = "waiting-up"
                    op.detail = f"{len(down)} of {len(op.osd_ids)} not up"
                else:
                    op.phase = "done"
                    op.detail = "all new osds up"
            else:  # drain
                # out-ness is re-derived, not remembered: a mon that lost
                # our "osd out" (or a mgr that restarted mid-drain) gets
                # the command again here
                not_out = [o for o in op.osd_ids
                           if o < len(m.osd_exists) and m.osd_exists[o]
                           and m.osd_weight[o] > 0]
                if not_out:
                    await self.mgr.mon_command(
                        {"prefix": "osd out", "ids": not_out},
                        timeout=10.0)
                remaining = self._pgs_on(op.osd_ids)
                still_up = [o for o in op.osd_ids
                            if o < len(m.osd_exists) and m.osd_exists[o]
                            and m.osd_up[o]]
                # only gate on health while the daemons still run: once
                # they are down the data either followed or didn't, and
                # purge is all that's left
                pending = await self._backfill_pending() \
                    if not remaining and still_up else ""
                if remaining:
                    op.phase = "wait-clean"
                    op.detail = f"{remaining} pg slots still mapped"
                elif pending:
                    op.phase = "wait-clean"
                    op.detail = f"backfill in flight: {pending}"
                elif still_up:
                    op.phase = "wait-down"
                    op.detail = (f"stop daemons: {still_up} drained but "
                                 "still running")
                else:
                    # the mon re-validates down+out under its own map —
                    # OUR map can transiently disagree (a daemon flap,
                    # an epoch of lag).  A refusal is "not yet", never
                    # fatal: stay in wait-down and re-derive next tick.
                    purged = 0
                    refused = None
                    for osd in op.osd_ids:
                        if osd < len(m.osd_exists) and \
                                not m.osd_exists[osd]:
                            purged += 1
                            continue
                        try:
                            await self.mgr.mon_command(
                                {"prefix": "osd purge", "id": osd,
                                 "sure": True}, timeout=10.0)
                            purged += 1
                        except (RuntimeError, TimeoutError,
                                ConnectionError, OSError) as e:
                            refused = f"osd.{osd}: {e}"
                            break
                    if refused is None:
                        op.phase = "done"
                        op.detail = f"purged {purged} osds"
                    else:
                        op.phase = "wait-down"
                        op.detail = f"purge deferred ({refused})"
            out.append(op.status())
        return out

    def status(self) -> List[Dict]:
        return [op.status() for op in self.ops.values()]
