"""graft-balance: the elastic-cluster policy subsystem (round 21).

Three cooperating mgr-hosted loops over the batched CRUSH substrate:

- ``scorer`` / ``balancer``: device-batched upmap optimization — generate
  thousands of candidate ``pg_upmap_items`` edits per round, score them
  all with one vectorized objective (per-OSD fill variance + primary
  balance + projected-move bytes), and commit the best safe move-set to
  the mon as a normal Incremental.  The greedy scalar
  ``osdmap/balancer.py::calc_pg_upmaps`` stays behind
  ``mgr_balancer_vectorized=0`` as the bisection anchor.
- ``autoscaler``: per-pool pg_num targets from observed object load vs
  in-OSD count, driving staged pg_num growth through the mon (and
  ``pg.py::_split_pg`` on the OSDs).
- ``reshape``: ``grow`` (add hosts/OSDs via ``osd grow``) and ``drain``
  (weight->0, wait-clean, purge) as first-class resumable operations
  whose phases are derived from observed map state, never from
  in-memory progress alone.
"""

from ceph_tpu.balance.scorer import (  # noqa: F401
    calc_pg_upmaps_vectorized,
    deviation_stats,
    generate_candidates,
    score_candidates,
)
from ceph_tpu.balance.balancer import UpmapBalancer  # noqa: F401
from ceph_tpu.balance.autoscaler import PgAutoscaler  # noqa: F401
from ceph_tpu.balance.reshape import Reshaper, ReshapeOp  # noqa: F401
