"""UpmapBalancer: the mgr loop that turns scored candidates into commits.

Each tick works on a private deepcopy of the mgr's subscribed osdmap
(the optimizer mutates its scratch map; the authoritative map only
changes when the mon commits the Incremental), runs either the
vectorized scorer (``mgr_balancer_vectorized=1``, the default) or the
scalar anchor (``=0``, the bisection anchor), and commits the chosen
move-set through the ordinary ``osd pg-upmap-items`` mon command — one
Incremental, distributed to subscribers like any other map change.

Safety throttles, checked BEFORE any work:

- ``*full`` flags: a cluster whose OSDs are already backfillfull/full
  must not be asked to move data around (reference balancer module's
  no-op on unhealthy clusters).
- recovery/dmclock pressure: when the summed ``osd_recovery_yields``
  counter moved since the last tick, recovery is actively yielding to
  client QoS — the cluster is busy digesting a previous reshape, so the
  balancer waits (counted as ``mgr_balancer_throttled``).
- degraded health (``mgr_balancer_require_clean``): PG_DEGRADED /
  OSD_DOWN health checks pause optimization.

Every tick updates the ``mgr_balancer_*`` counter family whether or not
it commits, and the whole family is DECLARED at mgr init so a disabled
balancer is visible on the Prometheus scrape as all-zeros — the
provable-no-op contract the SLO balance gate asserts.
"""

from __future__ import annotations

import copy
from typing import Dict, Optional

from ceph_tpu.balance.scorer import calc_pg_upmaps_vectorized
from ceph_tpu.osdmap.balancer import calc_pg_upmaps, pg_per_osd_stddev

# health checks that mean "the cluster is busy recovering": balancing
# on top of an active backfill doubles the data movement for no gain —
# and, worse, can re-move a PG whose previous move is still
# backfilling, walking the acting set away from the only current copy.
# PG_RECOVERING (round 21) is the live feed: mon-side pg_temp entries
# plus per-OSD unclean-primary-PG beacons, pessimistic until every up
# OSD has reported under the latest placement-changing epoch.
_UNCLEAN_CHECKS = ("PG_RECOVERING", "PG_DEGRADED", "OSD_DOWN",
                   "PG_UNDERSIZED")


class UpmapBalancer:
    def __init__(self, mgr):
        self.mgr = mgr
        self.last_round: Dict = {}
        self._last_recovery_yields: Optional[int] = None

    # -- throttles ----------------------------------------------------------

    def _recovery_pressure(self) -> bool:
        """dmclock/backfill pressure proxy: did any OSD's recovery yield
        to client QoS since our last look?"""
        total = 0
        for state in self.mgr.daemons.values():
            v = state["counters"].get("osd_recovery_yields", 0)
            if isinstance(v, (int, float)):
                total += int(v)
        prev = self._last_recovery_yields
        self._last_recovery_yields = total
        return prev is not None and total > prev

    async def _unclean_health(self) -> Optional[str]:
        if not self.mgr.config.mgr_balancer_require_clean:
            return None
        try:
            health = await self.mgr.mon_command({"prefix": "health"},
                                                timeout=5.0)
        except (TimeoutError, RuntimeError, ConnectionError, OSError):
            return "health unavailable"
        checks = (health or {}).get("checks", {})
        hits = [c for c in _UNCLEAN_CHECKS if c in checks]
        return ",".join(hits) if hits else None

    # -- the optimization round ----------------------------------------------

    async def tick(self, dry_run: bool = False) -> Dict:
        """One balancer round: measure, score, commit.  Returns a status
        dict (also kept as ``last_round`` for the admin command)."""
        cfg = self.mgr.config
        perf = self.mgr.perf
        m = self.mgr.osdmap
        result: Dict = {"epoch": m.epoch if m else 0, "moves": 0,
                        "dry_run": dry_run}
        if m is None:
            result["skipped"] = "no osdmap yet"
            self.last_round = result
            return result
        perf.inc("mgr_balancer_rounds")

        full_flags = m.flags & {"nearfull", "backfillfull", "full"}
        if full_flags:
            perf.inc("mgr_balancer_throttled")
            result["skipped"] = f"cluster flags: {sorted(full_flags)}"
            self.last_round = result
            return result
        if self._recovery_pressure():
            perf.inc("mgr_balancer_throttled")
            result["skipped"] = "recovery yielding to client QoS"
            self.last_round = result
            return result
        unclean = await self._unclean_health()
        if unclean:
            perf.inc("mgr_balancer_throttled")
            result["skipped"] = f"unclean health: {unclean}"
            self.last_round = result
            return result

        # scratch map: the optimizer mutates pg_upmap_items as it plans
        scratch = copy.deepcopy(m)
        skew_before = pg_per_osd_stddev(scratch)
        max_moves = int(cfg.mgr_balancer_max_moves)
        ratio = float(cfg.mgr_balancer_max_deviation_ratio)
        if cfg.mgr_balancer_vectorized:
            changes, scored = calc_pg_upmaps_vectorized(
                scratch, max_deviation_ratio=ratio,
                max_moves=max_moves,
                primary_weight=float(cfg.mgr_balancer_primary_weight),
                move_cost=float(cfg.mgr_balancer_move_cost))
            perf.inc("mgr_balancer_candidates", scored)
        else:
            changes = calc_pg_upmaps(scratch, max_deviation_ratio=ratio)
            if len(changes) > max_moves:
                changes = dict(list(changes.items())[:max_moves])
        skew_after = pg_per_osd_stddev(scratch)
        perf.set("mgr_balancer_skew_before_milli", int(skew_before * 1000))
        perf.set("mgr_balancer_skew_after_milli", int(skew_after * 1000))
        perf.inc("mgr_balancer_moves_proposed", len(changes))
        result.update(moves=len(changes),
                      skew_before=round(skew_before, 4),
                      skew_after=round(skew_after, 4))
        if not changes or dry_run:
            self.last_round = result
            return result

        # projected churn: every moved slot rewrites ~one PG's share of
        # the cluster's bytes (uniform estimate; the scenario judge
        # measures the REAL bytes via placement_delta)
        bytes_per_pg = self._bytes_per_pg(m)
        perf.inc("mgr_balancer_bytes_projected",
                 int(len(changes) * bytes_per_pg))

        items = {f"{pg.pool}.{pg.seed}": [list(p) for p in pairs]
                 for pg, pairs in changes.items()}
        try:
            await self.mgr.mon_command(
                {"prefix": "osd pg-upmap-items", "items": items},
                timeout=10.0)
        except (TimeoutError, RuntimeError, ConnectionError, OSError) as e:
            result["commit_error"] = repr(e)
            self.last_round = result
            return result
        perf.inc("mgr_balancer_moves_committed", len(changes))
        result["committed"] = True
        self.last_round = result
        return result

    def _bytes_per_pg(self, m) -> float:
        """Uniform projected bytes per moved PG slot from the reported
        per-OSD used bytes (osd_statfs flows through MMgrReport)."""
        used = 0
        for state in self.mgr.daemons.values():
            v = state["counters"].get("osd_stat_bytes_used", 0)
            if isinstance(v, (int, float)):
                used += int(v)
        pgs = sum(p.pg_num for p in m.pools.values()) or 1
        return used / pgs
