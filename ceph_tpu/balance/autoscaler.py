"""PgAutoscaler: staged pg_num growth from observed per-pool load.

Behavioral analog of the reference pg_autoscaler mgr module
(src/pybind/mgr/pg_autoscaler): each pool gets a pg_num TARGET from its
observed object load and the cluster's in-OSD count, and pools whose
target is at least double their current pg_num grow by one doubling per
tick — never more, because each doubling is a real PG split on the OSDs
(``pg.py::_split_pg``) and the staged walk keeps the split+backfill
work bounded.

Load observation rides the existing MMgrReport stream: every OSD's
heartbeat report carries ``osd_pool_<pid>_objects`` (primary PGs only,
so each object is counted once cluster-wide) — the mgr just sums across
daemons.  Targets honor two ceilings:

- ``mgr_autoscale_objects_per_pg``: grow when PGs get fatter than this
  many objects on average (the reference's target_size bias).
- ``mgr_autoscale_pgs_per_osd``: the cluster-wide PG budget — pool
  pg_num * size summed over pools must stay under budget * in-OSDs
  (mon_max_pg_per_osd analog), whatever the load says.

The split-then-move contract is preserved by issuing pg_num first and
pgp_num only on the NEXT tick once the map shows the split landed —
exactly the two-phase order ``mon._pool_set_pgnum`` enforces.
"""

from __future__ import annotations

from typing import Dict

# keep a pool's pg_num a power of two: seed folding (pg_num_mask) then
# splits PGs exactly in half, and the reference autoscaler does the same
def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _floor_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n.bit_length() - 1)


class PgAutoscaler:
    def __init__(self, mgr):
        self.mgr = mgr
        self.last_round: Dict = {}

    def _pool_objects(self, pid: int) -> int:
        total = 0
        for state in self.mgr.daemons.values():
            v = state["counters"].get(f"osd_pool_{pid}_objects", 0)
            if isinstance(v, (int, float)):
                total += int(v)
        return total

    def pool_targets(self) -> Dict[int, Dict]:
        """Per-pool status rows: current pg_num, observed objects, the
        load-derived target, and the pending pgp_num catch-up if any."""
        m = self.mgr.osdmap
        cfg = self.mgr.config
        if m is None:
            return {}
        n_in = sum(1 for o in range(m.max_osd)
                   if m.osd_exists[o] and m.osd_weight[o] > 0)
        per_pg = max(1, int(cfg.mgr_autoscale_objects_per_pg))
        budget = int(cfg.mgr_autoscale_pgs_per_osd) * max(n_in, 1)
        out: Dict[int, Dict] = {}
        for pid, pool in m.pools.items():
            if pool.is_erasure() or pool.tier_of >= 0:
                continue  # erasure pg_num is frozen; tiers follow base
            objects = self._pool_objects(pid)
            want = _next_pow2(max(1, (objects + per_pg - 1) // per_pg))
            # the budget caps TOTAL slots: this pool may use its share
            other_slots = sum(p.pg_num * p.size for q, p in m.pools.items()
                              if q != pid and not p.is_erasure())
            cap = (budget - other_slots) // max(pool.size, 1)
            target = max(pool.pg_num, min(want, _floor_pow2(max(1, cap))))
            out[pid] = {"pool": pool.name, "pg_num": pool.pg_num,
                        "pgp_num": pool.pgp_num, "objects": objects,
                        "target": target,
                        "split_pending": pool.pgp_num < pool.pg_num}
        return out

    async def tick(self, dry_run: bool = False) -> Dict:
        perf = self.mgr.perf
        m = self.mgr.osdmap
        result: Dict = {"epoch": m.epoch if m else 0, "actions": [],
                        "dry_run": dry_run}
        if m is None:
            result["skipped"] = "no osdmap yet"
            self.last_round = result
            return result
        perf.inc("mgr_autoscale_rounds")
        targets = self.pool_targets()
        for pid, row in targets.items():
            if row["split_pending"]:
                # phase 2 of a previous doubling: let the freshly-split
                # children migrate off their parents' placement
                action = {"pool": pid, "set": "pgp_num",
                          "val": row["pg_num"]}
            elif row["target"] >= 2 * row["pg_num"]:
                action = {"pool": pid, "set": "pg_num",
                          "val": row["pg_num"] * 2}
            else:
                continue
            result["actions"].append(action)
            if dry_run:
                continue
            try:
                await self.mgr.mon_command(
                    {"prefix": "osd pool set", "pool": row["pool"],
                     "var": action["set"], "val": action["val"]},
                    timeout=10.0)
                perf.inc("mgr_autoscale_splits"
                         if action["set"] == "pg_num"
                         else "mgr_autoscale_pgp_bumps")
            except (TimeoutError, RuntimeError, ConnectionError,
                    OSError) as e:
                action["error"] = repr(e)
        result["pools"] = targets
        self.last_round = result
        return result
