"""Erasure-code plugin registry.

Mirrors reference src/erasure-code/ErasureCodePlugin.cc:92-202: a singleton
registry mapping plugin names to factories, instantiating codecs from
profiles.  Where the reference dlopens ``libec_<name>.so`` and calls its
``__erasure_code_init`` entry point, we register Python factories — and
third-party codecs can register the same way (entry-point seam preserved).
"""

from __future__ import annotations

import errno
import threading
from typing import Callable, Dict

from ceph_tpu.ec.interface import ECError, ErasureCodeInterface, ErasureCodeProfile


class ErasureCodePluginRegistry:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._factories: Dict[str, Callable[[ErasureCodeProfile], ErasureCodeInterface]] = {}
        self.disable_dlclose = False

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
                cls._instance._register_builtins()
        return cls._instance

    def _register_builtins(self) -> None:
        from ceph_tpu.ec.jerasure import make_jerasure
        from ceph_tpu.ec.isa import make_isa

        self.add("jerasure", make_jerasure)
        self.add("isa", make_isa)
        # The TPU-native flagship plugin name, so benchmark harnesses can
        # select it like the reference selects --plugin isa/jerasure.
        self.add("jax", make_isa)
        try:
            from ceph_tpu.ec.lrc import make_lrc

            self.add("lrc", make_lrc)
        except ImportError:
            pass
        try:
            from ceph_tpu.ec.shec import make_shec

            self.add("shec", make_shec)
        except ImportError:
            pass

    def add(self, name: str, factory) -> None:
        with self._lock:
            self._factories[name] = factory

    def remove(self, name: str) -> None:
        with self._lock:
            self._factories.pop(name, None)

    def load(self, name: str):
        with self._lock:
            if name not in self._factories:
                raise ECError(errno.ENOENT, f"no erasure-code plugin {name!r}")
            return self._factories[name]

    def factory(self, plugin: str, profile: ErasureCodeProfile) -> ErasureCodeInterface:
        make = self.load(plugin)
        return make(dict(profile))

    def preload(self, plugins) -> None:
        for name in plugins:
            self.load(name)


def factory(profile: ErasureCodeProfile) -> ErasureCodeInterface:
    """Instantiate a codec from a profile's ``plugin`` key (default jerasure)."""
    profile = dict(profile)
    plugin = profile.get("plugin", "jerasure")
    return ErasureCodePluginRegistry.instance().factory(plugin, profile)
