"""Host-side helpers for bit-planar AT-REST shards (round 19).

``ec/planar.py`` made packed bit-planes the TRAVEL format of a stripe
batch; this module makes them the format EC shard objects LIVE in.  An
at-rest planar shard of L bytes is stored as its (8, L/8) packed
bit-plane matrix serialized row-major — exactly L bytes, so store
accounting, capacity admission and wire sizes are unchanged — with
``gf8.bytes_to_planar`` semantics: plane row t, packed byte i holds bit
t of shard bytes 8i..8i+7, byte 8i+u at bit u.

Everything here is plain numpy on shard-sized payloads (the tiny host
mirror of the jitted gf8 kernels, bit-exact with them by construction):
pack/unpack at the sanctioned ingest/egress seams, the GF(2) plane-row
matmul the CPU-backend steady state runs encode/decode/reencode with,
and the column splice RMW/append deltas land through.  Each helper that
crosses the layout boundary books the ``ec_planar_*`` KERNELS counters
(ops/profiling.record_planar_at_rest) — the steady-state contract is
that ``unseamed`` stays 0, pinned by test.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ceph_tpu.ops.profiling import record_planar_at_rest
from ceph_tpu.utils.perf import KERNELS

# the store/wire layout tag carried by Obj.layout / message ``layout``
# fields; None (or "") means classic byte-at-rest
LAYOUT_PLANAR = "planar8"

# planar packing quantum in BYTES: one packed plane byte spans 8 shard
# bytes, so every offset/length crossing the planar store API must be a
# multiple of 8 (EC chunk offsets are stripe-unit multiples, and the
# planar gate requires unit % 8 == 0)
QUANTUM = 8

_SHIFTS = np.arange(8, dtype=np.uint8)
_WEIGHTS = (1 << np.arange(8)).astype(np.uint32)


def rows_to_planes(rows: np.ndarray) -> np.ndarray:
    """(c, L) uint8 byte rows -> (c*8, L/8) packed bit-planes.

    Host-numpy mirror of the jitted ``gf8.bytes_to_planar`` (same
    formula, same LSB-first packing) so the CPU-backend steady state
    never touches the device runtime for a layout change."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    c, l = rows.shape
    if l % 8:
        raise ValueError(f"row length {l} not a multiple of 8")
    nb = l // 8
    d = rows.reshape(c, nb, 8)                                # (c, i, u)
    bits = (d[:, None, :, :] >> _SHIFTS[None, :, None, None]) & 1
    planes = (bits.astype(np.uint32)
              * _WEIGHTS[None, None, None, :]).sum(axis=3)    # (c, t, i)
    return planes.reshape(c * 8, nb).astype(np.uint8)


def planes_to_rows(planes: np.ndarray) -> np.ndarray:
    """(c*8, nb) packed bit-planes -> (c, 8*nb) byte rows (inverse)."""
    planes = np.ascontiguousarray(planes, dtype=np.uint8)
    c8, nb = planes.shape
    c = c8 // 8
    p = planes.reshape(c, 8, nb)                              # (c, t, i)
    bits = (p[:, :, :, None] >> _SHIFTS[None, None, None, :]) & 1
    by = (bits.astype(np.uint32)
          * _WEIGHTS[None, :, None, None]).sum(axis=1)        # (c, i, u)
    return by.reshape(c, nb * 8).astype(np.uint8)


# -- single-shard blob views (the store/wire serialization) -----------------

def shard_to_planes(blob, *, seam: Optional[str] = None) -> np.ndarray:
    """Shard BYTES -> its (8, L/8) at-rest plane matrix.

    This is a layout conversion: callers must name the ``seam`` that
    sanctions it (``ingest``/``egress``/``relayout``/``unseamed``) so
    the conversion books against the right contract counter."""
    row = np.frombuffer(bytes(blob), dtype=np.uint8).reshape(1, -1)
    if seam is not None:
        record_planar_at_rest(seam, row.shape[1])
    return rows_to_planes(row).reshape(8, -1)


def planes_to_shard(planes: np.ndarray, *, seam: Optional[str] = None) -> bytes:
    """(8, nb) plane matrix -> the shard's logical BYTES."""
    planes = np.ascontiguousarray(planes, dtype=np.uint8).reshape(8, -1)
    if seam is not None:
        record_planar_at_rest(seam, planes.size)
    return planes_to_rows(planes).tobytes()


def blob_to_planes(blob) -> np.ndarray:
    """At-rest plane BLOB (row-major serialization) -> (8, L/8) view.

    NOT a layout conversion — the blob already is the plane matrix."""
    arr = np.frombuffer(bytes(blob), dtype=np.uint8)
    if arr.size % 8:
        raise ValueError(f"planar blob size {arr.size} not 8-row")
    return arr.reshape(8, arr.size // 8)


def planes_to_blob(planes: np.ndarray) -> bytes:
    """(8, nb) plane matrix -> its at-rest serialization (row-major)."""
    return np.ascontiguousarray(planes, dtype=np.uint8).tobytes()


# -- plane-domain compute (CPU-backend steady state) ------------------------

def planar_matmul_host(bitmat: np.ndarray, planes: np.ndarray) -> np.ndarray:
    """GF(2) matmul on packed bit-planes, host numpy.

    ``bitmat`` is a {0,1} bit-matrix from ``gf8.expand_bitmatrix`` (or a
    decode bitmat); packed plane bytes are 8 independent bit columns, so
    the mod-2 row combination is a plain XOR-reduce over the selected
    plane rows — bit-exact with ``gf8.planar_matmul`` by GF(2)
    linearity.  Row counts are (k+m)*8-ish (tiny); columns carry the
    payload."""
    bitmat = np.asarray(bitmat, dtype=np.uint8)
    planes = np.ascontiguousarray(planes, dtype=np.uint8)
    KERNELS.inc("ec_host_planar_matmul_calls")
    KERNELS.inc("ec_host_planar_matmul_bytes", int(planes.size))
    out = np.zeros((bitmat.shape[0], planes.shape[1]), dtype=np.uint8)
    for r in range(bitmat.shape[0]):
        sel = np.nonzero(bitmat[r])[0]
        if sel.size:
            out[r] = np.bitwise_xor.reduce(planes[sel], axis=0)
    return out


def splice_columns(old: Optional[np.ndarray], col_off: int,
                   window: np.ndarray, total_cols: int) -> np.ndarray:
    """Land a plane-column window into an at-rest shard plane matrix.

    ``old`` is the current (8, oc) matrix (None when the object is
    new); ``window`` is the delta's (8, wc) planes landing at column
    ``col_off`` (byte offset / 8); the result is zero-extended or
    truncated to ``total_cols`` — the planar analog of the byte path's
    write+truncate pair.  Pure column ops: no layout conversion."""
    window = np.ascontiguousarray(window, dtype=np.uint8).reshape(8, -1)
    wc = window.shape[1]
    out = np.zeros((8, total_cols), dtype=np.uint8)
    if old is not None and old.size:
        oc = min(old.shape[1], total_cols)
        out[:, :oc] = old[:, :oc]
    end = min(col_off + wc, total_cols)
    if end > col_off:
        out[:, col_off:end] = window[:, : end - col_off]
    return out
