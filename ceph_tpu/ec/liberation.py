"""Minimal-density RAID-6 bit-matrix builders: liberation / blaum_roth /
liber8tion.

Behavioral reference: the jerasure native builders the reference's plugin
calls — ``liberation_coding_bitmatrix``, ``blaum_roth_coding_bitmatrix``,
``liber8tion_coding_bitmatrix`` (reference ErasureCodeJerasure.cc:439,463,
494; the jerasure/gf-complete submodules are NOT checked out in the
reference, so the constructions here are re-derived from their published
definitions).  All three are m=2 codes whose coding matrix is a native
(2w, kw) GF(2) bit-matrix — rows 0..w-1 are [I I ... I] (parity P = XOR of
all data), rows w..2w-1 are per-chunk w x w binary blocks X_j
(Q = sum X_j d_j):

- liberation (w prime, k <= w): X_j = cyclic shift of I by j, plus one
  extra bit at (i, (i+j-1) mod w) with i = (j*(w-1)/2) mod w for j > 0 —
  James Plank's Liberation codes ("The RAID-6 Liberation Codes", FAST'08).
- blaum_roth (w+1 prime, k <= w): X_j = multiplication by x^j in the ring
  GF(2)[x] / M_p(x), M_p(x) = 1 + x + ... + x^w, p = w + 1 (Blaum & Roth,
  "On lowest density MDS codes").  The reference tolerates w=7 (p=8 not
  prime) for backward compatibility (ErasureCodeJerasure.cc:446-459); the
  ring construction is still well-defined there, matching that behavior.
- liber8tion (w=8, k <= 8): X_j = the GF(2^8) bit-matrix of multiplying by
  g^j (g = 2, poly 0x11d).  NOTE: Plank's liber8tion matrices were found
  by computer search and are only published inside the jerasure submodule
  this checkout lacks; this deterministic construction has identical
  geometry, profile semantics, and 2-erasure MDS fault tolerance, but its
  parity BYTES differ from jerasure's searched matrices.

MDS for (k<=w, m=2) needs every X_j invertible and every X_i ^ X_j
invertible — asserted exhaustively by tests/test_ec_liberation.py.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops import gf8


def _identity_row(k: int, w: int) -> np.ndarray:
    """(w, kw) block row [I I ... I]."""
    return np.tile(np.eye(w, dtype=np.uint8), (1, k))


def liberation_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, kw) Liberation bit-matrix; requires w prime, 2 < w, k <= w."""
    if k > w:
        raise ValueError(f"liberation requires k <= w (k={k}, w={w})")
    mat = np.zeros((2 * w, k * w), dtype=np.uint8)
    mat[:w] = _identity_row(k, w)
    for j in range(k):
        for i in range(w):
            mat[w + i, j * w + (j + i) % w] = 1
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            mat[w + i, j * w + (i + j - 1) % w] = 1
    return mat


def _mult_by_x_ring(w: int) -> np.ndarray:
    """(w, w) GF(2) matrix of multiply-by-x in GF(2)[x]/M_p(x),
    M_p(x) = 1 + x + ... + x^w (p = w + 1).  Column u = x^(u+1) reduced:
    x^w == 1 + x + ... + x^(w-1)."""
    b = np.zeros((w, w), dtype=np.uint8)
    for u in range(w - 1):
        b[u + 1, u] = 1
    b[:, w - 1] = 1
    return b


def blaum_roth_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, kw) Blaum-Roth bit-matrix; MDS when w+1 is prime and k <= w."""
    if k > w:
        raise ValueError(f"blaum_roth requires k <= w (k={k}, w={w})")
    mat = np.zeros((2 * w, k * w), dtype=np.uint8)
    mat[:w] = _identity_row(k, w)
    b = _mult_by_x_ring(w)
    x = np.eye(w, dtype=np.uint8)
    for j in range(k):
        mat[w:, j * w:(j + 1) * w] = x
        x = (b @ x) & 1
    return mat


def liber8tion_coding_bitmatrix(k: int) -> np.ndarray:
    """(16, 8k) liber8tion-style bit-matrix, w=8, k <= 8 (see module
    docstring for the deviation from Plank's searched matrices)."""
    w = 8
    if k > w:
        raise ValueError(f"liber8tion requires k <= 8 (k={k})")
    mat = np.zeros((2 * w, k * w), dtype=np.uint8)
    mat[:w] = _identity_row(k, w)
    g = 1
    for j in range(k):
        mat[w:, j * w:(j + 1) * w] = gf8.GF_BITMAT[g]
        g = int(gf8.GF_MUL[g, 2])
    return mat
