"""Decode-table LRU cache.

Mirrors the role of the reference's ErasureCodeIsaTableCache
(src/erasure-code/isa/ErasureCodeIsaTableCache.h:48, capacity 2516): decode
matrices are built per erasure-pattern signature and reused.  Ours caches the
bit-expanded decode matrix already resident on device, so a cache hit costs
nothing on the host.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Hashable


class DecodeTableCache:
    DEFAULT_CAPACITY = 2516  # same bound the reference uses

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._od: OrderedDict[Hashable, Any] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable):
        try:
            value = self._od.pop(key)
        except KeyError:
            self.misses += 1
            return None
        self._od[key] = value
        self.hits += 1
        return value

    def put(self, key: Hashable, value) -> None:
        if key in self._od:
            self._od.pop(key)
        elif len(self._od) >= self.capacity:
            self._od.popitem(last=False)
        self._od[key] = value

    def __len__(self) -> int:
        return len(self._od)
