"""The jerasure codec family — all 7 techniques.

Behavioral mirror of reference src/erasure-code/jerasure/
ErasureCodeJerasure.{h,cc} and ErasureCodePluginJerasure.cc:42-56: technique
selection by profile, per-technique alignment/chunk-size rules
(ErasureCodeJerasure.cc:74-97), Vandermonde/RAID-6/Cauchy matrix generation
(:199,245,301), liberation-family bit-matrix preparation (:437-496).

Techniques: reed_sol_van, reed_sol_r6_op (bytewise matrix codes, w in
{8, 16, 32} over gf-complete's default polynomials), cauchy_orig,
cauchy_good (packet-interleaved bit-matrix codes, w in {8,16,32}),
liberation,
blaum_roth, liber8tion (native minimal-density GF(2) bit-matrices with
packetsize semantics — see ceph_tpu.ec.liberation for the constructions
and the liber8tion byte-compat caveat).

Round 6: both halves of the family carry the bit-planar layout contract
(ec/planar.py).  The matrix codes (reed_sol_*) pack chunks into w
bit-planes (``bitpack`` flavor) and their per-technique alignment rules
(k*w*4-byte multiples) already guarantee planar-compatible chunk sizes
for every w in {8, 16, 32}.  The packet-interleaved codes
(cauchy/liberation) ARE bit-planar natively — jerasure's w packets of
``packetsize`` bytes per super-block are packed bit-planes — so their
planar form is the packet-row matrix (``packet`` flavor) and no
second-level packing is applied.
"""

from __future__ import annotations

import errno

import numpy as np

from ceph_tpu.ec import liberation as libmod
from ceph_tpu.ec import matrices
from ceph_tpu.ec.codec import BitmatrixCodec, MatrixCodec, _DeviceBitEngine
from ceph_tpu.ec.interface import ECError, ErasureCodeProfile

LARGEST_VECTOR_WORDSIZE = 16

TECHNIQUES = (
    "reed_sol_van",
    "reed_sol_r6_op",
    "cauchy_orig",
    "cauchy_good",
    "liberation",
    "blaum_roth",
    "liber8tion",
)


class ErasureCodeJerasure(MatrixCodec):
    DEFAULT_K = "2"
    DEFAULT_M = "1"
    DEFAULT_W = "8"

    def __init__(self, technique: str):
        super().__init__()
        self.technique = technique
        self.per_chunk_alignment = False

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, self.DEFAULT_W)
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            self.chunk_mapping = []
            raise ECError(errno.EINVAL, "bad mapping size")
        self.sanity_check_k(self.k)

    def get_alignment(self) -> int:
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def get_chunk_size(self, object_size: int) -> int:
        # reference ErasureCodeJerasure.cc:74-97
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (object_size + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k


class ReedSolomonVandermonde(ErasureCodeJerasure):
    def __init__(self):
        super().__init__("reed_sol_van")

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        if self.w not in (8, 16, 32):
            profile["w"] = "8"
            self.w = 8
            raise ECError(errno.EINVAL, "w must be in {8, 16, 32}")
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, "false"
        )

    def build_coding_matrix(self) -> np.ndarray:
        if self.w == 8:
            return matrices.reed_sol_vandermonde_coding_matrix(self.k, self.m)
        return matrices.reed_sol_vandermonde_coding_matrix_w(
            self.k, self.m, self.w)


class ReedSolomonRAID6(ErasureCodeJerasure):
    def __init__(self):
        super().__init__("reed_sol_r6_op")

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        profile.pop("m", None)
        self.m = 2
        if self.w not in (8, 16, 32):
            profile["w"] = "8"
            self.w = 8
            raise ECError(errno.EINVAL, "w must be in {8, 16, 32}")

    def build_coding_matrix(self) -> np.ndarray:
        if self.w == 8:
            return matrices.reed_sol_r6_coding_matrix(self.k)
        return matrices.reed_sol_r6_coding_matrix_w(self.k, self.w)


class Cauchy(BitmatrixCodec, ErasureCodeJerasure):
    DEFAULT_PACKETSIZE = "2048"
    variant = "orig"

    def __init__(self):
        ErasureCodeJerasure.__init__(self, f"cauchy_{self.variant}")
        self.packetsize = 2048

    def parse(self, profile: ErasureCodeProfile) -> None:
        ErasureCodeJerasure.parse(self, profile)
        self.packetsize = self.to_int("packetsize", profile, self.DEFAULT_PACKETSIZE)
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, "false"
        )
        if self.w not in (8, 16, 32):
            raise ECError(errno.EINVAL,
                          "tpu cauchy supports w in {8, 16, 32}")
        if self.packetsize <= 0 or self.packetsize % 4:
            raise ECError(errno.EINVAL, "packetsize must be a positive multiple of 4")

    def get_alignment(self) -> int:
        # reference ErasureCodeJerasureCauchy::get_alignment
        if self.per_chunk_alignment:
            alignment = self.w * self.packetsize
            modulo = alignment % LARGEST_VECTOR_WORDSIZE
            if modulo:
                alignment += LARGEST_VECTOR_WORDSIZE - modulo
            return alignment
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
        return alignment

    get_chunk_size = ErasureCodeJerasure.get_chunk_size


class CauchyOrig(Cauchy):
    variant = "orig"

    def build_coding_matrix(self) -> np.ndarray:
        if self.w == 8:
            return matrices.cauchy_original_coding_matrix(self.k, self.m)
        return matrices.cauchy_original_coding_matrix_w(
            self.k, self.m, self.w)


class CauchyGood(Cauchy):
    variant = "good"

    def build_coding_matrix(self) -> np.ndarray:
        if self.w == 8:
            return matrices.cauchy_good_coding_matrix(self.k, self.m)
        return matrices.cauchy_good_coding_matrix_w(self.k, self.m, self.w)


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    return all(n % d for d in range(2, int(n ** 0.5) + 1))


class Liberation(BitmatrixCodec, ErasureCodeJerasure):
    """Native minimal-density bit-matrix RAID-6 (m=2) code with packetsize
    semantics (reference ErasureCodeJerasureLiberation,
    ErasureCodeJerasure.cc:353-441; bit-matrix from ceph_tpu.ec.liberation).
    """

    DEFAULT_PACKETSIZE = "2048"
    technique_name = "liberation"

    def __init__(self):
        ErasureCodeJerasure.__init__(self, self.technique_name)
        self.DEFAULT_K = "2"
        self.DEFAULT_M = "2"
        self.DEFAULT_W = "7"
        self.packetsize = 0
        self.bit_engine: _DeviceBitEngine = None

    def parse(self, profile: ErasureCodeProfile) -> None:
        ErasureCodeJerasure.parse(self, profile)
        profile.pop("m", None)
        self.m = 2
        self.packetsize = self.to_int(
            "packetsize", profile, self.DEFAULT_PACKETSIZE)
        if not self.check_k():
            raise ECError(errno.EINVAL,
                          f"k={self.k} must be <= w={self.w}")
        if not self.check_w():
            raise ECError(errno.EINVAL,
                          f"w={self.w} must be greater than two and be prime")
        if self.packetsize <= 0 or self.packetsize % 4:
            raise ECError(errno.EINVAL,
                          "packetsize must be a positive multiple of 4")

    def check_k(self) -> bool:
        return self.k <= self.w

    def check_w(self) -> bool:
        # reference ErasureCodeJerasureLiberation::check_w (:371-379)
        return self.w > 2 and _is_prime(self.w)

    def get_alignment(self) -> int:
        # reference ErasureCodeJerasureLiberation::get_alignment (:353-359)
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize * \
                LARGEST_VECTOR_WORDSIZE
        return alignment

    get_chunk_size = ErasureCodeJerasure.get_chunk_size

    def build_bitmatrix(self) -> np.ndarray:
        return libmod.liberation_coding_bitmatrix(self.k, self.w)

    def prepare(self) -> None:
        self.bit_engine = _DeviceBitEngine(
            self.k, self.m, self.w, self.build_bitmatrix())

    def _encode_bits(self) -> np.ndarray:
        return self.bit_engine.coding_bits

    def _decode_bits(self, src, out) -> np.ndarray:
        return self.bit_engine.decode_bits(tuple(src), tuple(out))


class BlaumRoth(Liberation):
    technique_name = "blaum_roth"

    def check_w(self) -> bool:
        # reference tolerates w=7 for backward compatibility
        # (ErasureCodeJerasure.cc:446-459)
        if self.w == 7:
            return True
        return self.w > 2 and _is_prime(self.w + 1)

    def build_bitmatrix(self) -> np.ndarray:
        return libmod.blaum_roth_coding_bitmatrix(self.k, self.w)


class Liber8tion(Liberation):
    technique_name = "liber8tion"

    def __init__(self):
        super().__init__()
        self.DEFAULT_W = "8"

    def parse(self, profile: ErasureCodeProfile) -> None:
        # reference Liber8tion::parse pins m=2, w=8 (:470-490)
        profile.pop("w", None)
        super().parse(profile)

    def check_w(self) -> bool:
        return self.w == 8

    def build_bitmatrix(self) -> np.ndarray:
        return libmod.liber8tion_coding_bitmatrix(self.k)


def make_jerasure(profile: ErasureCodeProfile):
    """Technique dispatch (reference ErasureCodePluginJerasure.cc:42-56)."""
    technique = profile.get("technique", "reed_sol_van")
    table = {
        "reed_sol_van": ReedSolomonVandermonde,
        "reed_sol_r6_op": ReedSolomonRAID6,
        "cauchy_orig": CauchyOrig,
        "cauchy_good": CauchyGood,
        "liberation": Liberation,
        "blaum_roth": BlaumRoth,
        "liber8tion": Liber8tion,
    }
    if technique not in TECHNIQUES:
        raise ECError(errno.ENOENT, f"unknown technique {technique}")
    codec = table[technique]()
    codec.init(profile)
    return codec
