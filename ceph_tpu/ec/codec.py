"""Matrix and bit-matrix codecs executing on the TPU MXU.

These are the concrete compute engines behind the jerasure/isa/lrc/shec
plugin families.  Where the reference dispatches to native SIMD libraries
(jerasure_matrix_encode, ISA-L ec_encode_data — reference
ErasureCodeJerasure.cc:156, ErasureCodeIsa.cc:128), we lower the identical
math to a single GF(2) matmul on the MXU (see ceph_tpu.ops.gf8).

Two layouts, matching the two native encode styles:

- MatrixCodec: bytewise GF(2^8) matrix codes (reed_sol_van, reed_sol_r6,
  ISA-L vandermonde/cauchy).  Each output byte position is independent.
- BitmatrixCodec: jerasure's packet-interleaved bit-matrix codes (cauchy_orig,
  cauchy_good; the liberation family slots in here once its matrix builders
  land).  Chunks are w-packet interleaved; encode XORs whole packets selected
  by a (m*w, k*w) GF(2) matrix — natively a GF(2) matmul
  (jerasure_schedule_encode semantics, reference ErasureCodeJerasure.cc:260).
"""

from __future__ import annotations

import errno
import functools
from typing import Dict, Mapping, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ec import matrices
from ceph_tpu.ec.base import ErasureCode
from ceph_tpu.ec.interface import ECError
from ceph_tpu.ec.table_cache import DecodeTableCache
from ceph_tpu.ops import gf8


@functools.lru_cache(maxsize=64)
def _lane_expand(mat_bytes: bytes, shape):
    """Kronecker-expand a 0/1 packet-selection matrix over the 8 byte lanes."""
    m01 = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    return jnp.asarray(np.kron(m01, np.eye(8, dtype=np.uint8)))


@jax.jit
def _encode_cols(bitmat, data):
    """bitmat (8r, 8k) x data (k, N) -> (r, N); the device hot path."""
    return gf8.bitmatrix_matmul(bitmat, data)


@jax.jit
def _encode_batch_jit(bitmat, data):
    """data (B, k, S) -> (B, r, S)."""
    b, k, s = data.shape
    cols = data.transpose(1, 0, 2).reshape(k, b * s)
    out = gf8.bitmatrix_matmul(bitmat, cols)
    r = out.shape[0]
    return out.reshape(r, b, s).transpose(1, 0, 2)


class _DeviceMatrixEngine:
    """Shared encode/decode engine over a (k+m, k) generator matrix."""

    def __init__(self, k: int, m: int, coding: np.ndarray):
        self.k = k
        self.m = m
        self.coding = coding.astype(np.uint8)
        self.generator = matrices.generator_matrix(self.coding)
        self._enc_bitmat = jnp.asarray(gf8.expand_bitmatrix(self.coding))
        self._decode_cache = DecodeTableCache()

    def encode_parity(self, data: np.ndarray) -> np.ndarray:
        """(k, S) -> (m, S) on device."""
        return np.asarray(_encode_cols(self._enc_bitmat, jnp.asarray(data)))

    def encode_parity_batch(self, data) -> jnp.ndarray:
        """(B, k, S) -> (B, m, S), stays on device."""
        return _encode_batch_jit(self._enc_bitmat, jnp.asarray(data))

    def decode_matrix(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...]
    ) -> np.ndarray:
        """Recovery matrix R with chunk[out] = R @ chunk[src].

        Same construction as ISA-L decode (reference ErasureCodeIsa.cc:274-305):
        invert the k x k survivor submatrix of the generator; erased data rows
        come straight from the inverse, erased parity rows compose the coding
        row with the inverse.
        """
        sub = self.generator[list(src_rows)]
        inv = gf8.gf_invert_matrix(sub)
        rows = []
        for e in out_rows:
            if e < self.k:
                rows.append(inv[e])
            else:
                rows.append(gf8.gf_matmul_ref(self.coding[e - self.k][None, :], inv)[0])
        return np.stack(rows).astype(np.uint8)

    def decode_bitmat(self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...]):
        key = (src_rows, out_rows)
        bitmat = self._decode_cache.get(key)
        if bitmat is None:
            rmat = self.decode_matrix(src_rows, out_rows)
            bitmat = jnp.asarray(gf8.expand_bitmatrix(rmat))
            self._decode_cache.put(key, bitmat)
        return bitmat

    def reconstruct(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...], data: np.ndarray
    ) -> np.ndarray:
        """data (k, S) from src_rows -> (len(out_rows), S)."""
        bitmat = self.decode_bitmat(src_rows, out_rows)
        return np.asarray(_encode_cols(bitmat, jnp.asarray(data)))

    def reconstruct_batch(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...], data
    ):
        """(B, k, S) from src_rows -> (B, len(out_rows), S), on device."""
        bitmat = self.decode_bitmat(src_rows, out_rows)
        return _encode_batch_jit(bitmat, jnp.asarray(data))


class MatrixCodec(ErasureCode):
    """Bytewise GF(2^8) matrix code; subclasses supply the coding matrix."""

    def __init__(self):
        super().__init__()
        self.engine: _DeviceMatrixEngine = None  # set by prepare()

    def build_coding_matrix(self) -> np.ndarray:
        raise NotImplementedError

    def prepare(self) -> None:
        self.engine = _DeviceMatrixEngine(self.k, self.m, self.build_coding_matrix())

    # -- single-stripe paths (reference-API compatible) ---------------------

    def encode_chunks(self, chunks: Dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        if data.shape[1] == 0:
            return
        parity = self.engine.encode_parity(data)
        for i in range(self.m):
            chunks[self.k + i][...] = parity[i]

    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise ECError(errno.EIO, "not enough chunks to decode")
        erased = tuple(i for i in range(self.k + self.m) if i not in chunks)
        src = tuple(avail[: self.k])
        data = np.stack([np.asarray(chunks[i], dtype=np.uint8) for i in src])
        out = self.engine.reconstruct(src, erased, data)
        for idx, e in enumerate(erased):
            decoded[e][...] = out[idx]

    # -- batched device paths ----------------------------------------------

    def encode_batch(self, data) -> np.ndarray:
        return self.engine.encode_parity_batch(data)

    def decode_batch(self, erasures: Tuple[int, ...], chunks,
                     want: Tuple[int, ...] = None) -> np.ndarray:
        """chunks: (B, k+m, S) with erased positions ignored (zeros ok).

        ``erasures`` lists EVERY unavailable chunk id (they are excluded
        from the source set); ``want`` selects which of them to rebuild
        (default: all).  Returns (B, len(want), S), device-resident.
        """
        if want is None:
            want = tuple(erasures)
        avail = tuple(i for i in range(self.k + self.m) if i not in erasures)
        src = avail[: self.k]
        data = jnp.asarray(chunks)[:, list(src), :]
        return self.engine.reconstruct_batch(src, tuple(want), data)


class BitmatrixCodec(MatrixCodec):
    """Packet-interleaved bit-matrix code (jerasure cauchy family, w=8).

    Chunk layout follows jerasure_schedule_encode: a chunk is a sequence of
    super-blocks of w*packetsize bytes; packet-row t of a super-block holds
    bits "t" of the w-bit field elements.  Encode selects and XORs packets
    according to the (m*w, k*w) bit-matrix — on the MXU this is the same
    GF(2) matmul with the bit-matrix Kronecker-expanded over byte lanes.
    """

    def __init__(self):
        super().__init__()
        self.packetsize = 2048

    def _layout_rows(self, data: np.ndarray) -> np.ndarray:
        """(c, S) chunks -> (c*w, S/w) packet-row matrix."""
        c, s = data.shape
        w, p = self.w, self.packetsize
        ns = s // (w * p)
        return (
            data.reshape(c, ns, w, p).transpose(0, 2, 1, 3).reshape(c * w, ns * p)
        )

    def _unlayout_rows(self, rows: np.ndarray, s: int) -> np.ndarray:
        c8, n = rows.shape
        w, p = self.w, self.packetsize
        c = c8 // w
        ns = n // p
        return rows.reshape(c, w, ns, p).transpose(0, 2, 1, 3).reshape(c, s)

    def _apply_bitmat(self, m01: np.ndarray, rows: np.ndarray) -> np.ndarray:
        lane = _lane_expand(m01.tobytes(), m01.shape)
        return np.asarray(_encode_cols(lane, jnp.asarray(rows)))

    def encode_chunks(self, chunks: Dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        rows = self._layout_rows(data)
        bitmat = gf8.expand_bitmatrix(self.engine.coding)  # (m*w, k*w) over GF(2)
        prows = self._apply_bitmat(bitmat, rows)
        parity = self._unlayout_rows(prows, data.shape[1])
        for i in range(self.m):
            chunks[self.k + i][...] = parity[i]

    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise ECError(errno.EIO, "not enough chunks to decode")
        erased = tuple(i for i in range(self.k + self.m) if i not in chunks)
        src = tuple(avail[: self.k])
        rmat = self.engine.decode_matrix(src, erased)
        data = np.stack([np.asarray(chunks[i], dtype=np.uint8) for i in src])
        rows = self._layout_rows(data)
        out_rows = self._apply_bitmat(gf8.expand_bitmatrix(rmat), rows)
        out = self._unlayout_rows(out_rows, data.shape[1])
        for idx, e in enumerate(erased):
            decoded[e][...] = out[idx]
