"""Matrix and bit-matrix codecs executing on the TPU MXU.

These are the concrete compute engines behind the jerasure/isa/lrc/shec
plugin families.  Where the reference dispatches to native SIMD libraries
(jerasure_matrix_encode, ISA-L ec_encode_data — reference
ErasureCodeJerasure.cc:156, ErasureCodeIsa.cc:128), we lower the identical
math to a single GF(2) matmul on the MXU (see ceph_tpu.ops.gf8).

Two layouts, matching the two native encode styles:

- MatrixCodec: bytewise GF(2^8) matrix codes (reed_sol_van, reed_sol_r6,
  ISA-L vandermonde/cauchy).  Each output byte position is independent.
- BitmatrixCodec: jerasure's packet-interleaved bit-matrix codes (cauchy_orig,
  cauchy_good; the liberation family slots in here once its matrix builders
  land).  Chunks are w-packet interleaved; encode XORs whole packets selected
  by a (m*w, k*w) GF(2) matrix — natively a GF(2) matmul
  (jerasure_schedule_encode semantics, reference ErasureCodeJerasure.cc:260).
"""

from __future__ import annotations

import errno
import functools
from typing import Dict, Mapping, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ec import matrices
from ceph_tpu.ec.base import ErasureCode
from ceph_tpu.ec.interface import ECError
from ceph_tpu.ec.table_cache import DecodeTableCache
from ceph_tpu.ops import gf8, gfw
from ceph_tpu.utils.perf import KERNELS


def _record_kernel(kind: str, bitmat_shape, nbytes: int) -> None:
    """Device-kernel telemetry: invocation count, payload bytes, and the
    MXU shape-padding waste (a (R, K) GF(2) matmul occupies 128-multiple
    tiles; the unused lanes are throughput the shape leaves on the
    floor — see BENCH_NOTES.md 'where the encode time actually goes')."""
    KERNELS.inc(f"{kind}_calls")
    KERNELS.inc(f"{kind}_bytes", int(nbytes))
    r, k = int(bitmat_shape[0]), int(bitmat_shape[1])
    tiles = (-(-r // 128) * 128) * (-(-k // 128) * 128)
    used = r * k
    if used:
        KERNELS.inc(f"{kind}_mxu_pad_bytes",
                    int(nbytes * (tiles - used) / used))


@functools.lru_cache(maxsize=64)
def _lane_expand(mat_bytes: bytes, shape):
    """Kronecker-expand a 0/1 packet-selection matrix over the 8 byte lanes."""
    m01 = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(shape)
    return jnp.asarray(np.kron(m01, np.eye(8, dtype=np.uint8)))


@jax.jit
def _encode_cols(bitmat, data):
    """bitmat (8r, 8k) x data (k, N) -> (r, N); the device hot path."""
    return gf8.bitmatrix_matmul(bitmat, data)


@jax.jit
def _encode_batch_jit(bitmat, data):
    """data (B, k, S) -> (B, r, S)."""
    b, k, s = data.shape
    cols = data.transpose(1, 0, 2).reshape(k, b * s)
    out = gf8.bitmatrix_matmul(bitmat, cols)
    r = out.shape[0]
    return out.reshape(r, b, s).transpose(1, 0, 2)


@functools.partial(jax.jit, static_argnums=(2,))
def _gather_encode_batch_jit(bitmat, chunks, src):
    """chunks (B, n, S) -> (B, r, S) using only the src rows.

    The row gather is INSIDE the jit so a decode is one device dispatch —
    an eager gather followed by the matmul costs a second round trip
    through the runtime per call, which dominates at small batch shapes."""
    data = chunks[:, list(src), :]
    b, k, s = data.shape
    cols = data.transpose(1, 0, 2).reshape(k, b * s)
    out = gf8.bitmatrix_matmul(bitmat, cols)
    r = out.shape[0]
    return out.reshape(r, b, s).transpose(1, 0, 2)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _gather_encode_batch_w_jit(bitmat, chunks, src, word_bytes: int):
    """Word-generalized variant of _gather_encode_batch_jit."""
    data = chunks[:, list(src), :]
    b, k, s = data.shape
    cols = data.transpose(1, 0, 2).reshape(k, b * s)
    out = gfw.bitmatrix_matmul_w(bitmat, cols, word_bytes)
    r = out.shape[0]
    return out.reshape(r, b, s).transpose(1, 0, 2)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _pkt_batch_apply(lane_mat, data, w: int, p: int, src=None):
    """Packet-interleaved batch apply for bit-matrix codes.

    data (B, c, S) where every chunk is super-blocks of w*p bytes (packet
    row t of super-block s holds bit-plane t); lane_mat is the
    byte-lane-expanded (8rw, 8cw) selection matrix.  One MXU matmul for
    the WHOLE batch (jerasure_schedule_encode semantics over all stripes
    at once, reference ErasureCodeJerasure.cc:260).  ``src`` (static)
    optionally selects source rows inside the jit."""
    if src is not None:
        data = data[:, list(src), :]
    b, c, s = data.shape
    ns = s // (w * p)
    rows = (
        data.reshape(b, c, ns, w, p)
        .transpose(1, 3, 0, 2, 4)
        .reshape(c * w, b * ns * p)
    )
    out = gf8.bitmatrix_matmul(lane_mat, rows)          # (r*w, b*ns*p)
    r = out.shape[0] // w
    return (
        out.reshape(r, w, b, ns, p)
        .transpose(2, 0, 3, 1, 4)
        .reshape(b, r, s)
    )


class _DeviceMatrixEngine:
    """Shared encode/decode engine over a (k+m, k) generator matrix.

    w=8 uses the table-driven gf8 host helpers; w in {16, 32} uses the
    scalar gfw field (matrices are k x m WORDS — still tiny) and the
    word-generalized device matmul.  Either way the data path is ONE MXU
    GF(2) matmul."""

    def __init__(self, k: int, m: int, coding: np.ndarray, w: int = 8):
        self.k = k
        self.m = m
        self.w = w
        self.word_bytes = w // 8
        if w == 8:
            self.coding = coding.astype(np.uint8)
            self._enc_bitmat = jnp.asarray(gf8.expand_bitmatrix(self.coding))
        else:
            self.coding = coding.astype(np.uint64)
            self._enc_bitmat = jnp.asarray(
                gfw.expand_bitmatrix_w(self.coding, w))
        self.generator = matrices.generator_matrix(self.coding)
        self._decode_cache = DecodeTableCache()

    def _apply(self, bitmat, data: np.ndarray) -> np.ndarray:
        _record_kernel("ec_matmul", bitmat.shape, data.size)
        if self.w == 8:
            return np.asarray(_encode_cols(bitmat, jnp.asarray(data)))
        return np.asarray(
            gfw.bitmatrix_matmul_w(bitmat, jnp.asarray(data), self.word_bytes))

    def _apply_batch(self, bitmat, data):
        _record_kernel("ec_matmul", bitmat.shape,
                       int(np.prod(data.shape)))
        if self.w == 8:
            return _encode_batch_jit(bitmat, jnp.asarray(data))
        return gfw.encode_batch_w(bitmat, jnp.asarray(data), self.word_bytes)

    def encode_parity(self, data: np.ndarray) -> np.ndarray:
        """(k, S) -> (m, S) on device."""
        return self._apply(self._enc_bitmat, data)

    def encode_parity_batch(self, data) -> jnp.ndarray:
        """(B, k, S) -> (B, m, S), stays on device."""
        return self._apply_batch(self._enc_bitmat, data)

    def decode_matrix(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...]
    ) -> np.ndarray:
        """Recovery matrix R with chunk[out] = R @ chunk[src].

        Same construction as ISA-L decode (reference ErasureCodeIsa.cc:274-305):
        invert the k x k survivor submatrix of the generator; erased data rows
        come straight from the inverse, erased parity rows compose the coding
        row with the inverse.
        """
        sub = self.generator[list(src_rows)]
        if self.w == 8:
            inv = gf8.gf_invert_matrix(sub)
            rows = []
            for e in out_rows:
                if e < self.k:
                    rows.append(inv[e])
                else:
                    rows.append(gf8.gf_matmul_ref(
                        self.coding[e - self.k][None, :], inv)[0])
            return np.stack(rows).astype(np.uint8)
        gf = gfw.field(self.w)
        inv = gfw.gfw_invert_matrix(sub, self.w)
        rows = []
        for e in out_rows:
            if e < self.k:
                rows.append(inv[e])
            else:
                crow = [int(x) for x in self.coding[e - self.k]]
                row = []
                for c in range(self.k):
                    acc = 0
                    for t in range(self.k):
                        acc ^= gf.mul(crow[t], int(inv[t][c]))
                    row.append(acc)
                rows.append(np.array(row, dtype=np.uint64))
        return np.stack(rows)

    def decode_bitmat(self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...]):
        key = (src_rows, out_rows)
        bitmat = self._decode_cache.get(key)
        if bitmat is None:
            rmat = self.decode_matrix(src_rows, out_rows)
            if self.w == 8:
                bitmat = jnp.asarray(gf8.expand_bitmatrix(rmat))
            else:
                bitmat = jnp.asarray(gfw.expand_bitmatrix_w(rmat, self.w))
            self._decode_cache.put(key, bitmat)
        return bitmat

    def reconstruct(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...], data: np.ndarray
    ) -> np.ndarray:
        """data (k, S) from src_rows -> (len(out_rows), S)."""
        bitmat = self.decode_bitmat(src_rows, out_rows)
        return self._apply(bitmat, data)

    def reconstruct_batch(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...], data
    ):
        """(B, k, S) from src_rows -> (B, len(out_rows), S), on device."""
        bitmat = self.decode_bitmat(src_rows, out_rows)
        return self._apply_batch(bitmat, data)

    def reconstruct_batch_from(
        self, src_rows: Tuple[int, ...], out_rows: Tuple[int, ...], chunks
    ):
        """Like reconstruct_batch but takes the FULL (B, n, S) chunk array
        and gathers src rows inside one jitted dispatch."""
        bitmat = self.decode_bitmat(src_rows, out_rows)
        chunks = jnp.asarray(chunks)
        _record_kernel("ec_matmul", bitmat.shape,
                       int(np.prod(chunks.shape)))
        if self.w == 8:
            return _gather_encode_batch_jit(bitmat, chunks, tuple(src_rows))
        return _gather_encode_batch_w_jit(
            bitmat, chunks, tuple(src_rows), self.word_bytes)


class _DeviceBitEngine:
    """Engine for NATIVE GF(2) bit-matrix codes (liberation family): the
    code is defined directly by an (m*w, k*w) 0/1 matrix with no byte
    matrix behind it.  Decode inverts the k*w x k*w survivor bit-matrix
    over GF(2) — the same solve jerasure performs on its bit-matrices."""

    def __init__(self, k: int, m: int, w: int, coding_bits: np.ndarray):
        self.k = k
        self.m = m
        self.w = w
        self.coding_bits = np.asarray(coding_bits, dtype=np.uint8)
        self.generator_bits = np.vstack(
            [np.eye(k * w, dtype=np.uint8), self.coding_bits])
        self._decode_cache = DecodeTableCache()

    def decode_bits(self, src: Tuple[int, ...],
                    out: Tuple[int, ...]) -> np.ndarray:
        key = (src, out)
        cached = self._decode_cache.get(key)
        if cached is not None:
            return cached
        k, w = self.k, self.w
        g = np.vstack([
            self.generator_bits[s * w:(s + 1) * w] for s in src])  # (kw, kw)
        inv = gfw.gf2_invert_matrix(g)
        rows = []
        for e in out:
            if e < k:
                rows.append(inv[e * w:(e + 1) * w])
            else:
                block = self.coding_bits[(e - k) * w:(e - k + 1) * w]
                rows.append((block.astype(np.int32) @ inv.astype(np.int32))
                            .astype(np.uint8) & 1)
        rmat = np.vstack(rows)
        self._decode_cache.put(key, rmat)
        return rmat


def _planar_rows_matmul(lane_bitmat, rows):
    """Byte-operand GF(2) matmul for packet-planar rows (the 8x expansion
    rides in the lane-expanded matrix): fused Pallas kernel on TPU
    backends, the XLA path elsewhere.  Bit-exact either way."""
    from ceph_tpu.ops import gf8_pallas

    _record_kernel("ec_matmul", lane_bitmat.shape,
                   int(np.prod(rows.shape)))
    if gf8_pallas.available():
        return gf8_pallas.bitmatrix_matmul(lane_bitmat, rows)
    return _encode_cols(lane_bitmat, rows)


class MatrixCodec(ErasureCode):
    """Bytewise GF(2^w) matrix code; subclasses supply the coding matrix."""

    def __init__(self):
        super().__init__()
        self.engine: _DeviceMatrixEngine = None  # set by prepare()

    def build_coding_matrix(self) -> np.ndarray:
        raise NotImplementedError

    def prepare(self) -> None:
        self.engine = _DeviceMatrixEngine(
            self.k, self.m, self.build_coding_matrix(), w=self.w)

    # -- bit-planar device layout (round 6 layout contract) -----------------
    #
    # Stripe batches stay in packed bit-planar form (ec/planar.py) across
    # encode -> parity -> decode -> RMW; each hop is ONE planar GF(2)
    # matmul (gf8.planar_matmul: K-stacked Pallas kernel on TPU), and the
    # byte layout exists only at the host boundary.

    def planar_supported(self, chunk_size: int) -> bool:
        from ceph_tpu.ec.planar import PlanarBatch

        return PlanarBatch.supported(chunk_size, self.w)

    def to_planar(self, batch) -> "PlanarBatch":
        """(B, k-or-n, S) byte batch -> device PlanarBatch (one convert)."""
        from ceph_tpu.ec.planar import PlanarBatch

        return PlanarBatch.from_batch(batch, w=self.w)

    def encode_planar(self, pb) -> "PlanarBatch":
        """PlanarBatch of the k data chunks -> PlanarBatch of m parity
        chunks.  No expansion, no pack: one matmul on packed planes."""
        from ceph_tpu.ops import gf8

        return pb.with_planes(
            gf8.planar_matmul(self.engine._enc_bitmat, pb.planes), self.m)

    def _planar_decode_plan(self, erasures, want):
        """(recovery bit-matrix, source chunk ids) for one erasure
        pattern; MDS codes take the first k available chunks (overridden
        by non-MDS families)."""
        avail = tuple(i for i in range(self.k + self.m)
                      if i not in erasures)
        src = avail[: self.k]
        return self.engine.decode_bitmat(src, tuple(want)), src

    def decode_planar(self, erasures, pb, want=None) -> "PlanarBatch":
        """Planar reconstruction: ``pb`` holds all n chunks (erased rows
        ignored); returns a PlanarBatch of ``want`` (default: erasures)."""
        from ceph_tpu.ec.planar import _select_chunk_rows
        from ceph_tpu.ops import gf8

        if want is None:
            want = tuple(erasures)
        bitmat, src = self._planar_decode_plan(tuple(erasures), tuple(want))
        src_planes = _select_chunk_rows(pb.planes, self.w, tuple(src))
        return pb.with_planes(gf8.planar_matmul(bitmat, src_planes),
                              len(want))

    # -- single-stripe paths (reference-API compatible) ---------------------

    def encode_chunks(self, chunks: Dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        if data.shape[1] == 0:
            return
        parity = self.engine.encode_parity(data)
        for i in range(self.m):
            chunks[self.k + i][...] = parity[i]

    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise ECError(errno.EIO, "not enough chunks to decode")
        erased = tuple(i for i in range(self.k + self.m) if i not in chunks)
        src = tuple(avail[: self.k])
        data = np.stack([np.asarray(chunks[i], dtype=np.uint8) for i in src])
        out = self.engine.reconstruct(src, erased, data)
        for idx, e in enumerate(erased):
            decoded[e][...] = out[idx]

    # -- batched device paths ----------------------------------------------

    def encode_batch(self, data) -> np.ndarray:
        return self.engine.encode_parity_batch(data)

    def stripe_unit(self, default: int) -> int:
        # round to the planar packing quantum (w BYTES: one packed plane
        # byte spans 8 field words) so cluster stripe batches always
        # satisfy the bit-planar layout contract; this is a superset of
        # the old word-size (w/8) alignment
        q = self.w
        return ((default + q - 1) // q) * q

    def decode_batch(self, erasures: Tuple[int, ...], chunks,
                     want: Tuple[int, ...] = None) -> np.ndarray:
        """chunks: (B, k+m, S) with erased positions ignored (zeros ok).

        ``erasures`` lists EVERY unavailable chunk id (they are excluded
        from the source set); ``want`` selects which of them to rebuild
        (default: all).  Returns (B, len(want), S), device-resident.
        """
        if want is None:
            want = tuple(erasures)
        avail = tuple(i for i in range(self.k + self.m) if i not in erasures)
        src = avail[: self.k]
        return self.engine.reconstruct_batch_from(src, tuple(want), chunks)


class BitmatrixCodec(MatrixCodec):
    """Packet-interleaved bit-matrix code (jerasure cauchy + liberation
    families).

    Chunk layout follows jerasure_schedule_encode: a chunk is a sequence of
    super-blocks of w*packetsize bytes; packet-row t of a super-block holds
    bits "t" of the w-bit field elements.  Encode selects and XORs packets
    according to the (m*w, k*w) bit-matrix — on the MXU this is the same
    GF(2) matmul with the bit-matrix Kronecker-expanded over byte lanes.

    Subclasses supply the bit-matrices: the cauchy family derives them from
    a GF(2^8) byte matrix (expand_bitmatrix is a ring homomorphism, so byte
    inversion and bit inversion agree); the liberation family overrides
    ``_encode_bits``/``_decode_bits`` with native GF(2) constructions.
    """

    def __init__(self):
        super().__init__()
        self.packetsize = 2048

    # -- bit-matrix sources (overridden by native bit-matrix codes) ---------

    def _encode_bits(self) -> np.ndarray:
        """(m*w, k*w) GF(2) encode matrix."""
        if self.w == 8:
            return gf8.expand_bitmatrix(self.engine.coding)
        return gfw.expand_bitmatrix_w(self.engine.coding, self.w)

    def _decode_bits(self, src: Tuple[int, ...],
                     out: Tuple[int, ...]) -> np.ndarray:
        """(len(out)*w, k*w) GF(2) recovery matrix over the src chunks."""
        rows = self.engine.decode_matrix(src, out)
        if self.w == 8:
            return gf8.expand_bitmatrix(rows)
        return gfw.expand_bitmatrix_w(rows, self.w)

    # -- packet layout ------------------------------------------------------

    def stripe_unit(self, default: int) -> int:
        quantum = self.w * self.packetsize
        return ((default + quantum - 1) // quantum) * quantum

    def _check_layout(self, s: int) -> None:
        if s % (self.w * self.packetsize):
            raise ECError(
                errno.EINVAL,
                f"chunk size {s} must be a multiple of w*packetsize = "
                f"{self.w * self.packetsize} (choose packetsize/profile "
                "accordingly, reference jerasure blocksize contract)")

    def _layout_rows(self, data: np.ndarray) -> np.ndarray:
        """(c, S) chunks -> (c*w, S/w) packet-row matrix."""
        c, s = data.shape
        w, p = self.w, self.packetsize
        self._check_layout(s)
        ns = s // (w * p)
        return (
            data.reshape(c, ns, w, p).transpose(0, 2, 1, 3).reshape(c * w, ns * p)
        )

    def _unlayout_rows(self, rows: np.ndarray, s: int) -> np.ndarray:
        c8, n = rows.shape
        w, p = self.w, self.packetsize
        c = c8 // w
        ns = n // p
        return rows.reshape(c, w, ns, p).transpose(0, 2, 1, 3).reshape(c, s)

    def _apply_bitmat(self, m01: np.ndarray, rows: np.ndarray) -> np.ndarray:
        lane = _lane_expand(m01.tobytes(), m01.shape)
        _record_kernel("ec_matmul", lane.shape, rows.size)
        return np.asarray(_encode_cols(lane, jnp.asarray(rows)))

    # -- single-stripe paths ------------------------------------------------

    def encode_chunks(self, chunks: Dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[i] for i in range(self.k)])
        rows = self._layout_rows(data)
        prows = self._apply_bitmat(self._encode_bits(), rows)
        parity = self._unlayout_rows(prows, data.shape[1])
        for i in range(self.m):
            chunks[self.k + i][...] = parity[i]

    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise ECError(errno.EIO, "not enough chunks to decode")
        erased = tuple(i for i in range(self.k + self.m) if i not in chunks)
        src = tuple(avail[: self.k])
        data = np.stack([np.asarray(chunks[i], dtype=np.uint8) for i in src])
        rows = self._layout_rows(data)
        out_rows = self._apply_bitmat(self._decode_bits(src, erased), rows)
        out = self._unlayout_rows(out_rows, data.shape[1])
        for idx, e in enumerate(erased):
            decoded[e][...] = out[idx]

    # -- batched device paths (packet-aware, overriding the bytewise
    #    MatrixCodec versions so batch and single-stripe bytes agree) -------

    def encode_batch(self, data) -> np.ndarray:
        data = jnp.asarray(data)
        self._check_layout(data.shape[2])
        m01 = self._encode_bits()
        lane = _lane_expand(m01.tobytes(), m01.shape)
        _record_kernel("ec_matmul", lane.shape,
                       int(np.prod(data.shape)))
        return _pkt_batch_apply(lane, data, self.w, self.packetsize)

    def decode_batch(self, erasures: Tuple[int, ...], chunks,
                     want: Tuple[int, ...] = None) -> np.ndarray:
        if want is None:
            want = tuple(erasures)
        avail = tuple(i for i in range(self.k + self.m) if i not in erasures)
        src = avail[: self.k]
        chunks = jnp.asarray(chunks)
        self._check_layout(chunks.shape[2])
        m01 = self._decode_bits(src, tuple(want))
        lane = _lane_expand(m01.tobytes(), m01.shape)
        _record_kernel("ec_matmul", lane.shape,
                       int(np.prod(chunks.shape)))
        return _pkt_batch_apply(lane, chunks, self.w, self.packetsize, src)

    # -- packet-planar layout (round 6) --------------------------------------
    #
    # Packet-interleaved chunks are ALREADY bit-planar: jerasure's w packets
    # of p bytes per super-block are packed bit-planes of the w-bit symbols.
    # The planar form is therefore the packet-row matrix (c*w, B*ns*p) of
    # raw bytes, and the matmul keeps the byte-lane Kronecker trick — no
    # second-level packing conversion on top.

    def planar_supported(self, chunk_size: int) -> bool:
        from ceph_tpu.ec.planar import PlanarBatch

        return PlanarBatch.supported(chunk_size, self.w, "packet",
                                     self.packetsize)

    def to_planar(self, batch):
        from ceph_tpu.ec.planar import PlanarBatch

        batch = jnp.asarray(batch)
        self._check_layout(int(batch.shape[2]))
        return PlanarBatch.from_batch(batch, w=self.w, layout="packet",
                                      packetsize=self.packetsize)

    def encode_planar(self, pb):
        m01 = self._encode_bits()
        lane = _lane_expand(m01.tobytes(), m01.shape)
        return pb.with_planes(_planar_rows_matmul(lane, pb.planes), self.m)

    def decode_planar(self, erasures, pb, want=None):
        from ceph_tpu.ec.planar import _select_chunk_rows

        if want is None:
            want = tuple(erasures)
        avail = tuple(i for i in range(self.k + self.m) if i not in erasures)
        src = avail[: self.k]
        m01 = self._decode_bits(src, tuple(want))
        lane = _lane_expand(m01.tobytes(), m01.shape)
        src_rows = _select_chunk_rows(pb.planes, self.w, src)
        return pb.with_planes(_planar_rows_matmul(lane, src_rows),
                              len(want))
