"""Bit-planar stripe-batch contract: the internal device layout for EC.

Round 6 (the layout-contract change BENCH_NOTES.md round 5 concluded was
required): stripe batches live on device in PACKED bit-planar form between
the host boundaries of a client op, so encode -> parity -> decode ->
RMW-delta are pure GF(2) matmuls — the per-call 8x {0,1} expansion and
re-pack that dominated the round-5 HBM traffic happens at most once per
direction per batch, and the Pallas kernel (ops/gf8_pallas.planar_matmul)
feeds the MXU a block-stacked >=128-wide K dimension.

Two planar flavors, matching the two codec families:

- ``bitpack`` (MatrixCodec families — jerasure reed_sol*, ISA, LRC, SHEC):
  planes ``(c*w, B*S/w)`` uint8, chunk-major plane rows (row ``j*w + t`` =
  bit-plane t of chunk j), built by ops/gf8.bytes_to_planar /
  ops/gfw.bytes_to_planar_w over the shard-major ``(c, B*S)`` view.

- ``packet`` (BitmatrixCodec families — cauchy/liberation):  those chunks
  are ALREADY bit-interleaved at packet granularity (jerasure's w packets
  of p bytes per super-block are packed bit-planes), so their planar form
  is the packet-row matrix ``(c*w, B*ns*p)`` of raw bytes and the matmul
  uses the byte-lane-expanded matrix — no second-level packing.

Both flavors occupy exactly the byte-layout footprint.  A PlanarBatch
lazily caches its byte-layout view so converting a batch is idempotent
and at most once in each direction.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ceph_tpu.ops import gf8, gfw
from ceph_tpu.ops.profiling import record_planar_convert


# ---------------------------------------------------------------------------
# jitted layout transforms (batch <-> planes), one dispatch each way
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=1)
def _batch_to_planes_bitpack(batch, w: int):
    """(B, c, S) bytes -> (c*w, B*S/w) packed planes (shard-major cols)."""
    b, c, s = batch.shape
    rows = batch.transpose(1, 0, 2).reshape(c, b * s)
    if w == 8:
        return gf8.bytes_to_planar(rows)
    return gfw.bytes_to_planar_w(rows, w)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _planes_to_batch_bitpack(planes, b: int, c: int, s: int, w: int):
    if w == 8:
        rows = gf8.planar_to_bytes(planes)
    else:
        rows = gfw.planar_to_bytes_w(planes, w)
    return rows.reshape(c, b, s).transpose(1, 0, 2)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _batch_to_planes_packet(batch, w: int, p: int):
    """(B, c, S) packet-interleaved chunks -> (c*w, B*ns*p) packet rows."""
    b, c, s = batch.shape
    ns = s // (w * p)
    return (
        batch.reshape(b, c, ns, w, p)
        .transpose(1, 3, 0, 2, 4)
        .reshape(c * w, b * ns * p)
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _planes_to_batch_packet(rows, b: int, c: int, s: int, w: int, p: int):
    ns = s // (w * p)
    return (
        rows.reshape(c, w, b, ns, p)
        .transpose(2, 0, 3, 1, 4)
        .reshape(b, c, s)
    )


@functools.partial(jax.jit, static_argnums=(1, 2))
def _select_chunk_rows(planes, w: int, ids: Tuple[int, ...]):
    """Gather whole chunks (= w-row blocks) out of a plane matrix."""
    cw, npk = planes.shape
    c = cw // w
    sel = jnp.asarray(list(ids), dtype=jnp.int32)
    return planes.reshape(c, w, npk)[sel].reshape(len(ids) * w, npk)


@functools.partial(jax.jit, static_argnums=(2,))
def _concat_chunk_rows(a, b, w: int):
    """Stack two plane matrices along the chunk axis (data ++ parity)."""
    return jnp.concatenate([a, b], axis=0)


class PlanarBatch:
    """Device-resident EC stripe batch in planar layout.

    ``planes``: the plane matrix (see module docstring for the two
    flavors); ``nstripes``/``nchunks``/``chunk_size`` give the byte-layout
    geometry ``(B, c, S)``; ``layout`` is ``"bitpack"`` or ``"packet"``.
    The byte-layout view is computed lazily and cached (``to_batch``), so
    a batch pays at most one conversion in each direction per client op.
    """

    __slots__ = ("planes", "nstripes", "nchunks", "chunk_size", "w",
                 "layout", "packetsize", "_batch")

    def __init__(self, planes, nstripes: int, nchunks: int, chunk_size: int,
                 w: int = 8, layout: str = "bitpack",
                 packetsize: int = 0, batch=None):
        self.planes = planes
        self.nstripes = nstripes
        self.nchunks = nchunks
        self.chunk_size = chunk_size
        self.w = w
        self.layout = layout
        self.packetsize = packetsize
        self._batch = batch

    # -- construction -------------------------------------------------------

    @staticmethod
    def supported(chunk_size: int, w: int, layout: str = "bitpack",
                  packetsize: int = 0) -> bool:
        """Can this geometry round-trip losslessly?  bitpack needs packed
        groups that don't split field words across chunk boundaries."""
        if chunk_size <= 0:
            return False
        if layout == "packet":
            return packetsize > 0 and chunk_size % (w * packetsize) == 0
        return chunk_size % w == 0

    @classmethod
    def from_batch(cls, batch, w: int = 8, layout: str = "bitpack",
                   packetsize: int = 0) -> "PlanarBatch":
        batch = jnp.asarray(batch)
        b, c, s = (int(x) for x in batch.shape)
        if layout == "packet":
            planes = _batch_to_planes_packet(batch, w, packetsize)
        else:
            planes = _batch_to_planes_bitpack(batch, w)
        record_planar_convert("to_planar", b * c * s)
        # deliberately does NOT retain ``batch``: keeping the byte view
        # alive alongside the planes would double the device footprint
        # for the batch's whole lifetime; a later to_batch() re-derives
        # it (still once, then cached) and the round trip is the
        # identity by contract
        return cls(planes, b, c, s, w, layout, packetsize)

    def with_planes(self, planes, nchunks: Optional[int] = None,
                    chunk_ids=None) -> "PlanarBatch":
        """Derived batch (e.g. parity or reconstructed chunks) sharing
        this batch's geometry; ``chunk_ids`` is only for callers' records,
        the planes' chunk axis is positional."""
        del chunk_ids
        if nchunks is None:
            nchunks = int(planes.shape[0]) // self.w
        return PlanarBatch(planes, self.nstripes, nchunks, self.chunk_size,
                           self.w, self.layout, self.packetsize)

    # -- views --------------------------------------------------------------

    def to_batch(self):
        """Byte-layout (B, c, S) view, converted once and cached."""
        if self._batch is None:
            if self.layout == "packet":
                self._batch = _planes_to_batch_packet(
                    self.planes, self.nstripes, self.nchunks,
                    self.chunk_size, self.w, self.packetsize)
            else:
                self._batch = _planes_to_batch_bitpack(
                    self.planes, self.nstripes, self.nchunks,
                    self.chunk_size, self.w)
            record_planar_convert(
                "to_bytes", self.nstripes * self.nchunks * self.chunk_size)
        return self._batch

    def select(self, ids: Tuple[int, ...]) -> "PlanarBatch":
        """Sub-batch of whole chunks (cheap device row gather)."""
        ids = tuple(int(i) for i in ids)
        return PlanarBatch(
            _select_chunk_rows(self.planes, self.w, ids),
            self.nstripes, len(ids), self.chunk_size, self.w,
            self.layout, self.packetsize)

    def concat(self, other: "PlanarBatch") -> "PlanarBatch":
        """data ++ parity along the chunk axis, staying planar."""
        assert other.layout == self.layout and other.w == self.w
        return PlanarBatch(
            _concat_chunk_rows(self.planes, other.planes, self.w),
            self.nstripes, self.nchunks + other.nchunks, self.chunk_size,
            self.w, self.layout, self.packetsize)
