"""Coding-matrix builders for every codec family.

Host-side numpy; these touch k x m bytes, never data.  Constructions mirror
the libraries the reference wraps:

- reed_sol_vandermonde_coding_matrix / reed_sol_r6_coding_matrix: jerasure
  reed_sol.c semantics (called from reference ErasureCodeJerasure.cc:199,245).
- cauchy_original / cauchy_good: jerasure cauchy.c semantics (reference
  ErasureCodeJerasure.cc:301 family).
- isa_rs_matrix / isa_cauchy_matrix: ISA-L gf_gen_rs_matrix /
  gf_gen_cauchy1_matrix semantics (reference ErasureCodeIsa.h:38-40 selects
  kVandermonde / kCauchy).

All are over GF(2^8) (w=8), the shared field of gf-complete and ISA-L.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops import gf8


def reed_sol_extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    """Extended Vandermonde matrix (jerasure reed_sol.c).

    Row 0 is e_0, rows 1..rows-2 are [1, i, i^2, ...], last row is e_{cols-1}.
    """
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    for i in range(1, rows - 1):
        for j in range(cols):
            v[i, j] = gf8.gf_pow(i, j)
    v[rows - 1, cols - 1] = 1
    return v


def _systematize_vandermonde(v: np.ndarray) -> np.ndarray:
    """Elementary column operations making the top cols x cols block identity.

    Same elimination jerasure performs inside
    reed_sol_vandermonde_coding_matrix, so the resulting parity rows match
    its output for any (k, m) where both are defined.
    """
    v = v.copy()
    rows, cols = v.shape
    for i in range(cols):
        if v[i, i] == 0:
            for j in range(i + 1, cols):
                if v[i, j] != 0:
                    v[:, [i, j]] = v[:, [j, i]]
                    break
            else:
                raise ValueError("vandermonde systematization failed")
        if v[i, i] != 1:
            inv = gf8.gf_inv(v[i, i])
            v[:, i] = gf8.gf_mul(v[:, i], inv)
        for j in range(cols):
            if j != i and v[i, j] != 0:
                factor = v[i, j]
                v[:, j] ^= gf8.gf_mul(factor, v[:, i])
    return v


def reed_sol_vandermonde_coding_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) coding matrix: systematized extended Vandermonde, bottom m rows.

    After systematization, jerasure's reed_sol_big_vandermonde_distribution_
    matrix performs two normalizations (reed_sol.c): first scale each parity
    *column* by the inverse of its first-parity-row entry so row 0 of the
    coding block is all ones (making the first parity a plain XOR), then
    scale each parity *row* i >= 1 by the inverse of its column-0 entry so
    column 0 of the coding block is all ones too.  Both operations multiply
    a row/column by a nonzero constant, preserving the MDS property; both
    are required for parity bytes compatible with jerasure.
    """
    v = reed_sol_extended_vandermonde(k + m, k)
    v = _systematize_vandermonde(v)
    assert np.array_equal(v[:k], np.eye(k, dtype=np.uint8))
    coding = v[k:].copy()
    for j in range(k):
        e = int(coding[0, j])
        if e not in (0, 1):
            coding[:, j] = gf8.gf_mul(coding[:, j], gf8.gf_inv(e))
    assert np.all(coding[0] == 1), "first parity row must be all ones"
    for i in range(1, m):
        e = int(coding[i, 0])
        if e not in (0, 1):
            coding[i] = gf8.gf_mul(coding[i], gf8.gf_inv(e))
    assert np.all(coding[:, 0] == 1), "first parity column must be all ones"
    return coding


def reed_sol_r6_coding_matrix(k: int) -> np.ndarray:
    """RAID-6 matrix (jerasure reed_sol_r6_coding_matrix): P = XOR, Q = sum 2^j d_j."""
    mat = np.zeros((2, k), dtype=np.uint8)
    mat[0, :] = 1
    for j in range(k):
        mat[1, j] = gf8.gf_pow(2, j)
    return mat


def cauchy_original_coding_matrix(k: int, m: int) -> np.ndarray:
    """matrix[i][j] = 1 / (i XOR (m + j))  (jerasure cauchy.c)."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    mat = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            mat[i, j] = gf8.gf_inv(i ^ (m + j))
    return mat


def _n_ones(x: int) -> int:
    """Number of ones in the 8x8 bit-matrix of multiply-by-x."""
    return int(gf8.GF_BITMAT[x].sum())


def cauchy_good_coding_matrix(k: int, m: int) -> np.ndarray:
    """Cauchy matrix optimized to minimize bit-matrix ones (jerasure
    cauchy_good_general_coding_matrix): scale each column so row 0 is all
    ones, then scale each later row by the divisor minimizing total ones.
    """
    mat = cauchy_original_coding_matrix(k, m)
    for j in range(k):
        if mat[0, j] != 1:
            inv = gf8.gf_inv(mat[0, j])
            mat[:, j] = gf8.gf_mul(mat[:, j], inv)
    for i in range(1, m):
        best = sum(_n_ones(int(e)) for e in mat[i])
        best_j = -1
        for j in range(k):
            if mat[i, j] != 1:
                inv = gf8.gf_inv(mat[i, j])
                total = sum(
                    _n_ones(int(gf8.gf_mul(e, inv))) for e in mat[i]
                )
                if total < best:
                    best = total
                    best_j = j
        if best_j != -1:
            inv = gf8.gf_inv(mat[i, best_j])
            mat[i] = gf8.gf_mul(mat[i], inv)
    return mat


def cauchy_original_coding_matrix_w(k: int, m: int, w: int) -> np.ndarray:
    """Wide-field cauchy_orig: matrix[i][j] = 1/(i ^ (m+j)) over GF(2^w)
    (jerasure cauchy.c, any w)."""
    from ceph_tpu.ops import gfw

    if k + m > (1 << w):
        raise ValueError(f"k+m must be <= 2^{w}")
    f = gfw.field(w)
    mat = np.zeros((m, k), dtype=np.uint64)
    for i in range(m):
        for j in range(k):
            mat[i, j] = f.inv(i ^ (m + j))
    return mat


def cauchy_good_coding_matrix_w(k: int, m: int, w: int) -> np.ndarray:
    """Wide-field cauchy_good: the SAME ones-minimization as the w=8
    version, counted over the w x w bit-matrices."""
    from ceph_tpu.ops import gfw

    f = gfw.field(w)

    def n_ones(x: int) -> int:
        return int(f.bitmat(int(x)).sum())

    mat = cauchy_original_coding_matrix_w(k, m, w)
    for j in range(k):
        if mat[0, j] != 1:
            inv = f.inv(int(mat[0, j]))
            for i in range(m):
                mat[i, j] = f.mul(int(mat[i, j]), inv)
    for i in range(1, m):
        best = sum(n_ones(int(e)) for e in mat[i])
        best_j = -1
        for j in range(k):
            if mat[i, j] != 1:
                inv = f.inv(int(mat[i, j]))
                total = sum(n_ones(f.mul(int(e), inv)) for e in mat[i])
                if total < best:
                    best = total
                    best_j = j
        if best_j != -1:
            inv = f.inv(int(mat[i, best_j]))
            for j in range(k):
                mat[i, j] = f.mul(int(mat[i, j]), inv)
    return mat


def isa_rs_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) parity rows of ISA-L gf_gen_rs_matrix: row r = [g^0..g^(k-1)],
    g = 2^r.  Row 0 is all ones (the XOR special case the reference keeps,
    ErasureCodeIsa.cc region_xor path)."""
    mat = np.zeros((m, k), dtype=np.uint8)
    gen = 1
    for r in range(m):
        p = 1
        for j in range(k):
            mat[r, j] = p
            p = int(gf8.gf_mul(p, gen))
        gen = int(gf8.gf_mul(gen, 2))
    return mat


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) parity rows of ISA-L gf_gen_cauchy1_matrix: inv(i ^ j),
    i = k..k+m-1."""
    mat = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            mat[i, j] = gf8.gf_inv((k + i) ^ j)
    return mat


def generator_matrix(coding: np.ndarray) -> np.ndarray:
    """Full (k+m, k) generator: identity stacked on the coding rows."""
    m, k = coding.shape
    return np.vstack([np.eye(k, dtype=coding.dtype), coding])


# ---------------------------------------------------------------------------
# Wide-field (w in {16, 32}) builders — same constructions over GF(2^w)
# scalar arithmetic (ceph_tpu.ops.gfw); matrices are k x m WORDS, host-side.
# ---------------------------------------------------------------------------


def reed_sol_vandermonde_coding_matrix_w(k: int, m: int, w: int) -> np.ndarray:
    """(m, k) uint64 coding matrix over GF(2^w): identical algorithm to the
    w=8 builder (extended Vandermonde -> column systematization -> the two
    jerasure normalizations), with gf-complete's default polynomial for w."""
    from ceph_tpu.ops import gfw

    if w == 8:
        return reed_sol_vandermonde_coding_matrix(k, m).astype(np.uint64)
    gf = gfw.field(w)
    rows, cols = k + m, k
    v = [[0] * cols for _ in range(rows)]
    v[0][0] = 1
    for i in range(1, rows - 1):
        for j in range(cols):
            v[i][j] = gf.pow(i, j)
    v[rows - 1][cols - 1] = 1
    # systematize by elementary column operations
    for i in range(cols):
        if v[i][i] == 0:
            for j in range(i + 1, cols):
                if v[i][j] != 0:
                    for r in range(rows):
                        v[r][i], v[r][j] = v[r][j], v[r][i]
                    break
            else:
                raise ValueError("vandermonde systematization failed")
        if v[i][i] != 1:
            inv = gf.inv(v[i][i])
            for r in range(rows):
                v[r][i] = gf.mul(v[r][i], inv)
        for j in range(cols):
            if j != i and v[i][j] != 0:
                f = v[i][j]
                for r in range(rows):
                    v[r][j] ^= gf.mul(f, v[r][i])
    coding = [row[:] for row in v[k:]]
    # normalization 1: first parity row all ones (column scaling)
    for j in range(k):
        e = coding[0][j]
        if e not in (0, 1):
            inv = gf.inv(e)
            for i in range(m):
                coding[i][j] = gf.mul(coding[i][j], inv)
    # normalization 2: first parity column all ones (row scaling, rows 1+)
    for i in range(1, m):
        e = coding[i][0]
        if e not in (0, 1):
            inv = gf.inv(e)
            coding[i] = [gf.mul(x, inv) for x in coding[i]]
    return np.array(coding, dtype=np.uint64)


def reed_sol_r6_coding_matrix_w(k: int, w: int) -> np.ndarray:
    """RAID-6 over GF(2^w): P = XOR, Q = sum 2^j d_j."""
    from ceph_tpu.ops import gfw

    gf = gfw.field(w)
    mat = np.zeros((2, k), dtype=np.uint64)
    mat[0, :] = 1
    for j in range(k):
        mat[1, j] = gf.pow(2, j)
    return mat
