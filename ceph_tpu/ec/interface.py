"""ErasureCodeInterface: the contract every codec implements.

Behavioral mirror of reference src/erasure-code/ErasureCodeInterface.h:170-462.
Chunks are numpy uint8 arrays keyed by chunk id (0..k+m-1, post-mapping);
profiles are str->str dicts exactly like the reference's ErasureCodeProfile.
"""

from __future__ import annotations

import abc
from typing import Dict, Iterable, List, Mapping, Set, Tuple

import numpy as np

ErasureCodeProfile = Dict[str, str]


class ECError(Exception):
    """Raised where the reference returns a negative errno."""

    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(f"errno {errno_}: {msg}")
        self.errno = errno_


class ErasureCodeInterface(abc.ABC):
    """Abstract codec API (reference ErasureCodeInterface.h:170-462)."""

    @abc.abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Initialize from a profile; raises ECError on invalid parameters."""

    @abc.abstractmethod
    def get_profile(self) -> ErasureCodeProfile:
        ...

    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m."""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, object_size: int) -> int:
        """Chunk size for a given object size, honoring alignment rules."""

    @abc.abstractmethod
    def get_chunk_mapping(self) -> List[int]:
        ...

    @abc.abstractmethod
    def minimum_to_decode(
        self, want_to_read: Set[int], available_chunks: Set[int]
    ) -> Set[int]:
        """Minimum chunk set needed to reconstruct want_to_read."""

    def minimum_to_decode_with_cost(
        self, want_to_read: Set[int], available: Mapping[int, int]
    ) -> Set[int]:
        return self.minimum_to_decode(want_to_read, set(available))

    @abc.abstractmethod
    def encode(
        self, want_to_encode: Iterable[int], data: bytes
    ) -> Dict[int, np.ndarray]:
        """Split + pad ``data`` and produce the requested chunks."""

    @abc.abstractmethod
    def encode_chunks(self, chunks: Dict[int, np.ndarray]) -> None:
        """In-place: fill coding chunks from data chunks (all k+m present)."""

    @abc.abstractmethod
    def decode(
        self, want_to_read: Set[int], chunks: Mapping[int, np.ndarray]
    ) -> Dict[int, np.ndarray]:
        """Reconstruct the wanted chunk ids from the available ``chunks``."""

    @abc.abstractmethod
    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        """In-place reconstruction given pre-allocated output chunks."""

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        """Reconstruct and concatenate the data chunks in mapped order."""
        want = {self.chunk_index(i) for i in range(self.get_data_chunk_count())}
        decoded = self.decode(want, chunks)
        out = b"".join(
            decoded[self.chunk_index(i)].tobytes()
            for i in range(self.get_data_chunk_count())
        )
        return out

    def chunk_index(self, i: int) -> int:
        mapping = self.get_chunk_mapping()
        return mapping[i] if len(mapping) > i else i

    # Batched device path (TPU-native extension; not in the reference API).
    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(batch, k, chunk) uint8 -> (batch, m, chunk) parity on device."""
        raise NotImplementedError

    def decode_batch(
        self, erasures: Tuple[int, ...], chunks: np.ndarray
    ) -> np.ndarray:
        """Reconstruct erased chunks for a batch sharing one erasure pattern."""
        raise NotImplementedError
