"""The ISA-L-compatible codec.

Behavioral mirror of reference src/erasure-code/isa/ErasureCodeIsa.{h,cc}:
matrix selection kVandermonde/kCauchy (ErasureCodeIsa.h:38-40), chunk size =
ceil(object/k) rounded to 32 (ErasureCodeIsa.cc:65-78), decode via survivor
submatrix inversion (:274-305), decode-table caching keyed by the erasure
signature (ErasureCodeIsaTableCache.h:48).  The m=1 XOR special case falls
out naturally: the first vandermonde parity row is all ones, and a
multiply-by-1 bit-matrix block is the identity, so the MXU matmul *is* the
region XOR.

Round 6: as a MatrixCodec this plugin carries the bit-planar layout
contract (ec/planar.py) — cluster stripe batches stay packed bit-planar
across encode/decode/RMW (``to_planar``/``encode_planar``/
``decode_planar``), which is what takes the k8m4 headline encode from the
HBM-bound 8x-expansion path to the K-stacked fused kernel.  The 32-byte
ISA address alignment is already a multiple of the planar packing quantum
(w = 8 bytes), so every legal ISA chunk geometry rides the contract.
"""

from __future__ import annotations

import errno

import numpy as np

from ceph_tpu.ec import matrices
from ceph_tpu.ec.codec import MatrixCodec
from ceph_tpu.ec.interface import ECError, ErasureCodeProfile

EC_ISA_ADDRESS_ALIGNMENT = 32


class ErasureCodeIsaDefault(MatrixCodec):
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def __init__(self, matrixtype: str = "reed_sol_van"):
        super().__init__()
        self.technique = matrixtype

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.technique = self.to_string("technique", profile, "reed_sol_van")
        self.sanity_check_k(self.k)
        if self.technique not in ("reed_sol_van", "cauchy"):
            raise ECError(errno.EINVAL, f"technique {self.technique} not supported")
        if self.k + self.m > 256:
            raise ECError(errno.EINVAL, "k+m must be <= 256")

    def get_alignment(self) -> int:
        return EC_ISA_ADDRESS_ALIGNMENT

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    def build_coding_matrix(self) -> np.ndarray:
        if self.technique == "cauchy":
            return matrices.isa_cauchy_matrix(self.k, self.m)
        return matrices.isa_rs_matrix(self.k, self.m)


def make_isa(profile: ErasureCodeProfile):
    codec = ErasureCodeIsaDefault()
    codec.init(profile)
    return codec
