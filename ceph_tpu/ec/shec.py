"""SHEC: shingled erasure codes (k, m, c).

Behavioral mirror of reference src/erasure-code/shec/ErasureCodeShec.{h,cc}
and ErasureCodePluginShec.cc: a Vandermonde RS matrix with a shingle pattern
of zeros (shec_reedsolomon_coding_matrix, ErasureCodeShec.cc:456), the
(m1, c1, m2, c2) split chosen by the recovery-efficiency metric
(shec_calc_recovery_efficiency1, :415), per-erasure-pattern decode via a
minimal-subset search over parity combinations + GF Gaussian elimination
(shec_make_decoding_matrix, :526), and a decode-table cache keyed by the
(want, avails) pattern (ErasureCodeShecTableCache).

Tolerates up to ``c`` erasures while reading fewer chunks than a full-k MDS
decode — the "shingle" rows overlap so each data chunk is covered by a
cheap local-ish parity.  Encode is the standard bytewise GF(2^8) matrix
multiply, so the TPU MXU bit-matrix path serves it unchanged; only
decode-matrix *construction* differs from MDS codes and stays on the host
(k x k bytes).
"""

from __future__ import annotations

import errno
from typing import Dict, List, Mapping, Set, Tuple

import numpy as np

from ceph_tpu.ec import matrices
from ceph_tpu.ec.codec import MatrixCodec
from ceph_tpu.ec.interface import ECError, ErasureCodeProfile
from ceph_tpu.ops import gf8, gfw

MULTIPLE = 0
SINGLE = 1

LARGEST_VECTOR_WORDSIZE = 16


def gfw_invert(mat: np.ndarray, w: int) -> np.ndarray:
    """gfw inversion with the gf8 SingularMatrixError contract."""
    try:
        return gfw.gfw_invert_matrix(mat, w)
    except ValueError as e:
        raise gf8.SingularMatrixError(str(e))


def _calc_recovery_efficiency1(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """Reference shec_calc_recovery_efficiency1 (ErasureCodeShec.cc:415)."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [100000000] * k
    r_e1 = 0.0
    for rr in range(m1):
        start = ((rr * k) // m1) % k
        end = (((rr + c1) * k) // m1) % k
        cc = start
        first = True
        while first or cc != end:
            first = False
            r_eff_k[cc] = min(r_eff_k[cc],
                              ((rr + c1) * k) // m1 - (rr * k) // m1)
            cc = (cc + 1) % k
        r_e1 += ((rr + c1) * k) // m1 - (rr * k) // m1
    for rr in range(m2):
        start = ((rr * k) // m2) % k
        end = (((rr + c2) * k) // m2) % k
        cc = start
        first = True
        while first or cc != end:
            first = False
            r_eff_k[cc] = min(r_eff_k[cc],
                              ((rr + c2) * k) // m2 - (rr * k) // m2)
            cc = (cc + 1) % k
        r_e1 += ((rr + c2) * k) // m2 - (rr * k) // m2
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_coding_matrix(k: int, m: int, c: int, technique: int,
                       w: int = 8) -> np.ndarray:
    """Shingled (m, k) coding matrix (reference
    shec_reedsolomon_coding_matrix, ErasureCodeShec.cc:456): a Vandermonde
    RS matrix over GF(2^w) with shingle-patterned zeros."""
    if technique == MULTIPLE:
        c1_best, m1_best = -1, -1
        min_r_e1 = 100.0
        for c1 in range(c // 2 + 1):
            for m1 in range(m + 1):
                c2 = c - c1
                m2 = m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
                    continue
                if (m1 != 0 and c1 == 0) or (m2 != 0 and c2 == 0):
                    continue
                r_e1 = _calc_recovery_efficiency1(k, m1, m2, c1, c2)
                if min_r_e1 - r_e1 > np.finfo(float).eps and r_e1 < min_r_e1:
                    min_r_e1 = r_e1
                    c1_best, m1_best = c1, m1
        m1, c1 = m1_best, c1_best
        m2, c2 = m - m1_best, c - c1_best
    else:
        m1, c1 = 0, 0
        m2, c2 = m, c

    if w == 8:
        mat = matrices.reed_sol_vandermonde_coding_matrix(k, m).astype(
            np.uint8)
    else:
        mat = matrices.reed_sol_vandermonde_coding_matrix_w(k, m, w)
    for rr in range(m1):
        end = ((rr * k) // m1) % k
        start = (((rr + c1) * k) // m1) % k
        cc = start
        while cc != end:
            mat[rr, cc] = 0
            cc = (cc + 1) % k
    for rr in range(m2):
        end = ((rr * k) // m2) % k
        start = (((rr + c2) * k) // m2) % k
        cc = start
        while cc != end:
            mat[rr + m1, cc] = 0
            cc = (cc + 1) % k
    return mat


class ErasureCodeShec(MatrixCodec):
    DEFAULT_K = 4
    DEFAULT_M = 3
    DEFAULT_C = 2

    def __init__(self, technique: int = MULTIPLE):
        super().__init__()
        self.technique = technique
        self.c = 0
        # decode-plan cache keyed by (want, avails) bit patterns
        # (ErasureCodeShecTableCache semantics)
        self._plan_cache: Dict[Tuple, Tuple] = {}
        # batched recovery matrices per (erasures, want) pattern
        self._batch_cache: Dict[Tuple, Tuple] = {}

    # -- profile ------------------------------------------------------------

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        has = [name in profile and profile[name] for name in ("k", "m", "c")]
        if not any(has):
            self.k, self.m, self.c = self.DEFAULT_K, self.DEFAULT_M, self.DEFAULT_C
        elif not all(has):
            raise ECError(errno.EINVAL, "(k, m, c) must all be chosen")
        else:
            try:
                self.k = int(profile["k"])
                self.m = int(profile["m"])
                self.c = int(profile["c"])
            except ValueError as e:
                raise ECError(errno.EINVAL, f"bad k/m/c: {e}")
        k, m, c = self.k, self.m, self.c
        if k <= 0 or m <= 0 or c <= 0:
            raise ECError(errno.EINVAL, "k, m, c must be positive")
        if m < c:
            raise ECError(errno.EINVAL, f"c={c} must be <= m={m}")
        if k > 12:
            raise ECError(errno.EINVAL, f"k={k} must be <= 12")
        if k + m > 20:
            raise ECError(errno.EINVAL, f"k+m={k+m} must be <= 20")
        if k < m:
            raise ECError(errno.EINVAL, f"m={m} must be <= k={k}")
        w = profile.get("w")
        self.w = 8
        if w:
            try:
                wv = int(w)
            except ValueError:
                wv = 8
            if wv not in (8, 16, 32):
                wv = 8  # reference falls back to the default, no error
            self.w = wv

    def get_alignment(self) -> int:
        # reference ErasureCodeShecReedSolomonVandermonde::get_alignment:
        # k * w * sizeof(int)
        return self.k * self.w * 4

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def build_coding_matrix(self) -> np.ndarray:
        return shec_coding_matrix(self.k, self.m, self.c, self.technique,
                                  self.w)

    # -- field-width helpers (gf8 fast path, gfw for w in {16, 32}) ---------

    def _invert(self, mat: np.ndarray) -> np.ndarray:
        if self.w == 8:
            return gf8.gf_invert_matrix(mat.astype(np.uint8))
        return gfw_invert(mat, self.w)

    def _mul(self, a: int, b_row: np.ndarray) -> np.ndarray:
        if self.w == 8:
            return gf8.gf_mul(a, b_row)
        gf = gfw.field(self.w)
        return np.array([gf.mul(a, int(x)) for x in b_row],
                        dtype=np.uint64)

    def _matmul_host(self, rows: np.ndarray, data: np.ndarray) -> np.ndarray:
        """(r, c) words x (c, S) bytes -> (r, S) bytes on host."""
        if self.w == 8:
            return np.asarray(gf8.gf_matmul_ref(rows, data))
        bitmat = gfw.expand_bitmatrix_w(rows, self.w)
        import jax.numpy as jnp

        return np.asarray(gfw.bitmatrix_matmul_w(
            jnp.asarray(bitmat), jnp.asarray(data), self.w // 8))

    # -- decode-plan search (reference shec_make_decoding_matrix, :526) -----

    def _make_decoding_plan(self, want: List[int], avails: List[int]):
        """Returns (srcs, cols, inv, minimum):
        srcs — chunk ids whose values feed the solve (rows of the system),
        cols — data chunk ids solved for (columns),
        inv  — GF inverse of the system matrix (None when nothing to solve),
        minimum — minimal chunk-id set to read.
        Raises ECError(EIO) when the pattern is unrecoverable."""
        k, m = self.k, self.m
        matrix = self.engine.coding
        want = list(want)
        # to re-encode a wanted erased parity, all data in its support is wanted
        for i in range(m):
            if want[k + i] and not avails[k + i]:
                for j in range(k):
                    if matrix[i, j] > 0:
                        want[j] = 1

        key = (tuple(want), tuple(avails))
        cached = self._plan_cache.get(key)
        if cached is not None:
            return cached

        mindup = k + 1
        minp = k + 1
        best_srcs: List[int] = []
        best_cols: List[int] = []
        best_inv = None
        for pp in range(1 << m):
            p = [i for i in range(m) if pp & (1 << i)]
            ek = len(p)
            if ek > minp:
                continue
            if not all(avails[k + i] for i in p):
                continue
            tmprow = [0] * (k + m)
            tmpcolumn = [0] * k
            for i in range(k):
                if want[i] and not avails[i]:
                    tmpcolumn[i] = 1
            for i in p:
                tmprow[k + i] = 1
                for j in range(k):
                    element = int(matrix[i, j])
                    if element != 0:
                        tmpcolumn[j] = 1
                        if avails[j] == 1:
                            tmprow[j] = 1
            dup_row = sum(tmprow)
            dup_column = sum(tmpcolumn)
            if dup_row != dup_column:
                continue
            dup = dup_row
            if dup == 0:
                mindup = 0
                best_srcs, best_cols, best_inv = [], [], None
                break
            if dup < mindup:
                srcs = [i for i in range(k + m) if tmprow[i]]
                cols = [j for j in range(k) if tmpcolumn[j]]
                tmpmat = np.zeros((dup, dup),
                                  dtype=np.uint8 if self.w == 8
                                  else np.uint64)
                for r, i in enumerate(srcs):
                    for cidx, j in enumerate(cols):
                        if i < k:
                            tmpmat[r, cidx] = 1 if i == j else 0
                        else:
                            tmpmat[r, cidx] = matrix[i - k, j]
                try:
                    inv = self._invert(tmpmat)
                except gf8.SingularMatrixError:
                    continue  # singular: determinant is zero, reject
                mindup = dup
                best_srcs, best_cols, best_inv = srcs, cols, inv
                minp = ek

        if mindup == k + 1:
            raise ECError(errno.EIO, "shec: can't find recover matrix")

        minimum = set(best_srcs)
        for i in range(k):
            if want[i] and avails[i]:
                minimum.add(i)
        for i in range(m):
            if want[k + i] and avails[k + i] and (k + i) not in minimum:
                for j in range(k):
                    if matrix[i, j] > 0 and not want[j]:
                        minimum.add(k + i)
                        break

        plan = (best_srcs, best_cols, best_inv, minimum)
        self._plan_cache[key] = plan
        return plan

    # -- interface ----------------------------------------------------------

    def minimum_to_decode(
        self, want_to_read: Set[int], available_chunks: Set[int]
    ) -> Set[int]:
        n = self.k + self.m
        for s in (want_to_read, available_chunks):
            for i in s:
                if i < 0 or i >= n:
                    raise ECError(errno.EINVAL, f"bad chunk id {i}")
        want = [1 if i in want_to_read else 0 for i in range(n)]
        avails = [1 if i in available_chunks else 0 for i in range(n)]
        _, _, _, minimum = self._make_decoding_plan(want, avails)
        return set(minimum)

    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        """Reference shec_matrix_decode (ErasureCodeShec.cc:756): solve the
        minimal system for erased wanted data chunks, then re-encode erased
        wanted parities from the (now complete) data row."""
        k, m = self.k, self.m
        n = k + m
        avails = [1 if i in chunks else 0 for i in range(n)]
        want = [1 if (i in want_to_read and i not in chunks) else 0
                for i in range(n)]
        if not any(want):
            return
        srcs, cols, inv, _ = self._make_decoding_plan(want, avails)
        if inv is not None and srcs:
            src_data = np.stack([
                np.asarray(decoded[i], dtype=np.uint8) for i in srcs
            ])
            # reconstruct only the erased columns; available ones are
            # already in `decoded`
            out_rows = [ci for ci, j in enumerate(cols) if not avails[j]]
            if out_rows:
                rmat = inv[out_rows]
                out = self._matmul_host(rmat, src_data) \
                    if src_data.shape[1] < 4096 or self.w != 8 \
                    else self._device_matmul(rmat, src_data)
                for idx, ci in enumerate(out_rows):
                    decoded[cols[ci]][...] = out[idx]
        # re-encode wanted erased parity chunks from complete data
        parity_want = [i for i in range(m) if want[k + i]]
        if parity_want:
            data = np.stack([
                np.asarray(decoded[i], dtype=np.uint8) for i in range(k)
            ])
            rows = self.engine.coding[parity_want]
            out = self._matmul_host(rows, data) \
                if data.shape[1] < 4096 or self.w != 8 \
                else self._device_matmul(rows, data)
            for idx, i in enumerate(parity_want):
                decoded[k + i][...] = out[idx]

    def _device_matmul(self, rmat: np.ndarray, data: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        from ceph_tpu.ec.codec import _encode_cols

        bitmat = jnp.asarray(gf8.expand_bitmatrix(rmat))
        return np.asarray(_encode_cols(bitmat, jnp.asarray(data)))

    def decode_batch(self, erasures: Tuple[int, ...], chunks,
                     want: Tuple[int, ...] = None) -> np.ndarray:
        """Batched single-pattern reconstruction on device: build the plan
        once, apply ONE recovery matrix to the whole stripe batch.
        ``erasures`` = every unavailable chunk id; ``want`` = the subset to
        rebuild (default all of them).

        Erased parity rows are handled by composing the coding row with the
        data-recovery expressions (same composition the reference performs
        chunk-at-a-time in shec_matrix_decode, ErasureCodeShec.cc:526-756):
        every data chunk j is either an available source (identity row) or a
        solved combination of the plan's sources (its inverse row), so
        parity i = coding[i] @ [data exprs] is itself one row over sources.
        """
        import jax.numpy as jnp

        from ceph_tpu.ec.codec import (_gather_encode_batch_jit,
                                       _gather_encode_batch_w_jit)

        def _apply(bitmat, src_list):
            if self.w == 8:
                return _gather_encode_batch_jit(
                    bitmat, jnp.asarray(chunks), tuple(src_list))
            return _gather_encode_batch_w_jit(
                bitmat, jnp.asarray(chunks), tuple(src_list), self.w // 8)

        if want is None:
            want = tuple(erasures)
        bitmat, src_list = self._batch_plan(tuple(erasures), tuple(want))
        return _apply(bitmat, src_list)

    def _planar_decode_plan(self, erasures, want):
        """Planar decode rides the same non-MDS plan construction (the
        MatrixCodec default of 'first k available' can be singular for
        SHEC's punctured coding matrix)."""
        return self._batch_plan(erasures, want)

    def _batch_plan(self, erasures: Tuple[int, ...],
                    want: Tuple[int, ...]):
        """(recovery bit-matrix, source ids) for one erasure pattern,
        cached like the reference decode tables."""
        cache_key = (erasures, want)
        cached = self._batch_cache.get(cache_key)
        if cached is not None:
            return cached
        import jax.numpy as jnp

        n = self.k + self.m
        avails = [0 if i in erasures else 1 for i in range(n)]
        want_vec = [1 if i in want else 0 for i in range(n)]
        srcs, cols, inv, _ = self._make_decoding_plan(want_vec, avails)
        src_list = list(srcs)
        pos = {s: i for i, s in enumerate(src_list)}
        # available data chunks in an erased parity's support feed the
        # composition directly; extend the source list with them
        for e in want:
            if e >= self.k:
                for j in range(self.k):
                    if self.engine.coding[e - self.k, j] and avails[j] \
                            and j not in pos:
                        pos[j] = len(src_list)
                        src_list.append(j)
        S = len(src_list)

        word_dtype = np.uint8 if self.w == 8 else np.uint64

        def data_expr(j: int) -> np.ndarray:
            """Row expressing data chunk j over src_list."""
            row = np.zeros(S, dtype=word_dtype)
            if avails[j]:
                row[pos[j]] = 1
            else:
                ci = cols.index(j)
                for r_i, s in enumerate(srcs):
                    row[pos[s]] = inv[ci][r_i]
            return row

        rows = []
        for e in want:
            if e < self.k:
                rows.append(data_expr(e))
            else:
                crow = self.engine.coding[e - self.k]
                acc = np.zeros(S, dtype=word_dtype)
                for j in range(self.k):
                    cj = int(crow[j])
                    if cj:
                        acc ^= self._mul(cj, data_expr(j)).astype(word_dtype)
                rows.append(acc)
        rmat = np.stack(rows).astype(word_dtype)
        if self.w == 8:
            bitmat = jnp.asarray(gf8.expand_bitmatrix(rmat))
        else:
            bitmat = jnp.asarray(gfw.expand_bitmatrix_w(rmat, self.w))
        self._batch_cache[cache_key] = (bitmat, tuple(src_list))
        return bitmat, tuple(src_list)


def make_shec(profile: ErasureCodeProfile):
    technique_name = profile.get("technique") or "multiple"
    profile["technique"] = technique_name
    if technique_name == "multiple":
        technique = MULTIPLE
    elif technique_name == "single":
        technique = SINGLE
    else:
        raise ECError(errno.ENOENT,
                      f"technique={technique_name} is not a valid coding technique")
    codec = ErasureCodeShec(technique)
    codec.init(profile)
    return codec
