"""LRC: layered locally-repairable codes.

Behavioral mirror of reference src/erasure-code/lrc/ErasureCodeLrc.{h,cc}:
a stack of layers, each a chunk-subset delegation to another EC plugin
(struct Layer, ErasureCodeLrc.h:51-61), profile either explicit
mapping+layers JSON or generated from (k, m, l) (parse_kml,
ErasureCodeLrc.cc:295), locality-aware minimum_to_decode
(ErasureCodeLrc.cc:572) so a single erasure reads only its local group,
and multi-step CRUSH rule generation (rule_steps, ErasureCodeLrc.h:66-75,
create_rule ErasureCodeLrc.cc).

The compute stays on the TPU: every layer delegates to a MatrixCodec whose
encode/decode is the MXU bit-matrix matmul — LRC itself only routes chunk
subsets, exactly like the reference routes bufferlists between plugins.
"""

from __future__ import annotations

import errno
import json
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Set

import numpy as np

from ceph_tpu.ec.base import ErasureCode
from ceph_tpu.ec.interface import ECError, ErasureCodeInterface, ErasureCodeProfile

DEFAULT_KML = "-1"


@dataclass
class Layer:
    """One LRC layer (reference ErasureCodeLrc.h:51-61)."""

    chunks_map: str
    profile: ErasureCodeProfile = field(default_factory=dict)
    erasure_code: ErasureCodeInterface = None
    data: List[int] = field(default_factory=list)
    coding: List[int] = field(default_factory=list)
    chunks: List[int] = field(default_factory=list)
    chunks_as_set: Set[int] = field(default_factory=set)


@dataclass
class Step:
    """One generated CRUSH rule step (reference ErasureCodeLrc.h:66-75)."""

    op: str
    type: str
    n: int


class ErasureCodeLrc(ErasureCode):
    def __init__(self):
        super().__init__()
        self.layers: List[Layer] = []
        self.chunk_count = 0
        self.data_chunk_count = 0
        self.rule_steps: List[Step] = [Step("chooseleaf", "host", 0)]
        # jitted batch entry points: the layer routing must be ONE device
        # dispatch — eager per-layer gathers/scatters cost a runtime round
        # trip each, which dominates end-to-end throughput
        self._enc_jit = None
        self._enc_planar_bitmat = None
        self._dec_jit: Dict = {}

    # -- profile parsing ----------------------------------------------------

    def _parse_kml(self, profile: ErasureCodeProfile) -> None:
        """Generate mapping/layers/rule-steps from (k, m, l)
        (reference parse_kml, ErasureCodeLrc.cc:295)."""
        k = self.to_int("k", profile, DEFAULT_KML)
        m = self.to_int("m", profile, DEFAULT_KML)
        l = self.to_int("l", profile, DEFAULT_KML)
        if k == -1 and m == -1 and l == -1:
            return
        if k == -1 or m == -1 or l == -1:
            raise ECError(errno.EINVAL,
                          "all of k, m, l must be set or none of them")
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile and profile[generated]:
                raise ECError(
                    errno.EINVAL,
                    f"the {generated} parameter cannot be set when k, m, l are set")
        if (k + m) % l:
            raise ECError(errno.EINVAL, "k + m must be a multiple of l")
        local_group_count = (k + m) // l
        if k % local_group_count:
            raise ECError(errno.EINVAL, "k must be a multiple of (k + m) / l")
        if m % local_group_count:
            raise ECError(errno.EINVAL, "m must be a multiple of (k + m) / l")

        mapping = ""
        for _ in range(local_group_count):
            mapping += "D" * (k // local_group_count) + \
                "_" * (m // local_group_count) + "_"
        profile["mapping"] = mapping

        layers = [ ]
        # global layer
        desc = ""
        for _ in range(local_group_count):
            desc += "D" * (k // local_group_count) + \
                "c" * (m // local_group_count) + "_"
        layers.append([desc, ""])
        # local layers
        for i in range(local_group_count):
            desc = ""
            for j in range(local_group_count):
                if i == j:
                    desc += "D" * l + "c"
                else:
                    desc += "_" * (l + 1)
            layers.append([desc, ""])
        profile["layers"] = json.dumps(layers)

        rule_locality = profile.get("crush-locality", "")
        rule_failure_domain = profile.get("crush-failure-domain", "host")
        if rule_locality:
            self.rule_steps = [
                Step("choose", rule_locality, local_group_count),
                Step("chooseleaf", rule_failure_domain, l + 1),
            ]
        elif rule_failure_domain:
            self.rule_steps = [Step("chooseleaf", rule_failure_domain, 0)]

    def _parse_rule(self, profile: ErasureCodeProfile) -> None:
        """crush-steps JSON override (reference parse_rule)."""
        if not profile.get("crush-steps"):
            return
        try:
            description = json.loads(profile["crush-steps"])
        except json.JSONDecodeError as e:
            raise ECError(errno.EINVAL, f"failed to parse crush-steps: {e}")
        if not isinstance(description, list):
            raise ECError(errno.EINVAL, "crush-steps must be a JSON array")
        self.rule_steps = []
        for entry in description:
            if not isinstance(entry, list):
                raise ECError(errno.EINVAL,
                              "each crush-steps element must be a JSON array")
            op, type_, n = "", "", 0
            for pos, v in enumerate(entry):
                if pos in (0, 1) and not isinstance(v, str):
                    raise ECError(errno.EINVAL,
                                  f"crush-steps element {pos} must be a string")
                if pos == 2 and not isinstance(v, int):
                    raise ECError(errno.EINVAL,
                                  "crush-steps element 2 must be an int")
                if pos == 0:
                    op = v
                elif pos == 1:
                    type_ = v
                elif pos == 2:
                    n = v
            self.rule_steps.append(Step(op, type_, n))

    def _layers_parse(self, profile: ErasureCodeProfile) -> None:
        """layers JSON -> Layer list (reference layers_parse,
        ErasureCodeLrc.cc:145)."""
        if not profile.get("layers"):
            raise ECError(errno.EINVAL, "could not find 'layers' in profile")
        try:
            description = json.loads(profile["layers"])
        except json.JSONDecodeError as e:
            raise ECError(errno.EINVAL, f"failed to parse layers: {e}")
        if not isinstance(description, list):
            raise ECError(errno.EINVAL, "layers must be a JSON array")
        self.layers = []
        for position, entry in enumerate(description):
            if not isinstance(entry, list):
                raise ECError(
                    errno.EINVAL,
                    f"layers element at position {position} must be a JSON array")
            if not entry or not isinstance(entry[0], str):
                raise ECError(
                    errno.EINVAL,
                    f"the first element of layers entry {position} must be a string")
            layer = Layer(chunks_map=entry[0])
            if len(entry) > 1:
                config = entry[1]
                if isinstance(config, str):
                    if config:
                        try:
                            layer.profile = {
                                str(a): str(b)
                                for a, b in json.loads(config).items()
                            }
                        except (json.JSONDecodeError, AttributeError) as e:
                            raise ECError(errno.EINVAL,
                                          f"bad layer config {config!r}: {e}")
                elif isinstance(config, dict):
                    layer.profile = {str(a): str(b) for a, b in config.items()}
                else:
                    raise ECError(
                        errno.EINVAL,
                        f"the second element of layers entry {position} "
                        "must be a string or object")
            # trailing elements ignored, like the reference
            self.layers.append(layer)

    def _layers_init(self) -> None:
        """Resolve chunk positions + instantiate per-layer codecs
        (reference layers_init, ErasureCodeLrc.cc:215)."""
        from ceph_tpu.ec.registry import ErasureCodePluginRegistry

        registry = ErasureCodePluginRegistry.instance()
        for layer in self.layers:
            layer.data = [i for i, c in enumerate(layer.chunks_map) if c == "D"]
            layer.coding = [i for i, c in enumerate(layer.chunks_map) if c == "c"]
            layer.chunks = layer.data + layer.coding
            layer.chunks_as_set = set(layer.chunks)
            layer.profile.setdefault("k", str(len(layer.data)))
            layer.profile.setdefault("m", str(len(layer.coding)))
            layer.profile.setdefault("plugin", "jerasure")
            layer.profile.setdefault("technique", "reed_sol_van")
            layer.erasure_code = registry.factory(
                layer.profile["plugin"], layer.profile)

    def _layers_sanity_checks(self) -> None:
        if len(self.layers) < 1:
            raise ECError(errno.EINVAL, "layers must have at least one entry")
        for position, layer in enumerate(self.layers):
            if len(layer.chunks_map) != self.chunk_count:
                raise ECError(
                    errno.EINVAL,
                    f"layer {position} chunks_map {layer.chunks_map!r} must be "
                    f"{self.chunk_count} characters long")

    def init(self, profile: ErasureCodeProfile) -> None:
        # ordering mirrors reference ErasureCodeLrc::init (:496-553)
        self._parse_kml(profile)
        self.rule_root = self.to_string("crush-root", profile, "default")
        self.rule_failure_domain = self.to_string(
            "crush-failure-domain", profile, "host")
        self.rule_device_class = self.to_string("crush-device-class", profile, "")
        self._parse_rule(profile)
        self._layers_parse(profile)
        self._layers_init()
        if not profile.get("mapping"):
            raise ECError(errno.EINVAL, "the 'mapping' profile is missing")
        mapping = profile["mapping"]
        self.data_chunk_count = mapping.count("D")
        self.chunk_count = len(mapping)
        self._layers_sanity_checks()
        self.to_mapping(profile)
        # kml-generated parameters are internal; do not expose them
        # (reference :545-550)
        if profile.get("l") and profile["l"] != DEFAULT_KML:
            profile.pop("mapping", None)
            profile.pop("layers", None)
        self._profile = profile

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.chunk_count

    def get_data_chunk_count(self) -> int:
        return self.data_chunk_count

    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- minimum_to_decode (the locality win) -------------------------------

    def minimum_to_decode(
        self, want_to_read: Set[int], available_chunks: Set[int]
    ) -> Set[int]:
        """Reference ErasureCodeLrc::minimum_to_decode (:572): recover
        erasures with as few chunks as possible, preferring the lowest
        (most local) layers; on a single local erasure the read set is the
        local group, not k chunks."""
        erasures_total = set()
        erasures_not_recovered = set()
        erasures_want = set()
        for i in range(self.get_chunk_count()):
            if i not in available_chunks:
                erasures_total.add(i)
                erasures_not_recovered.add(i)
                if i in want_to_read:
                    erasures_want.add(i)

        # Case 1: nothing wanted is missing
        if not erasures_want:
            return set(want_to_read)

        # Case 2: recover wanted erasures bottom-up (local layers last in
        # the list, reverse iteration visits them first)
        minimum: Set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                minimum |= layer_want
                continue
            erasures = layer.chunks_as_set & erasures_not_recovered
            if len(erasures) > layer.erasure_code.get_coding_chunk_count():
                # too many erasures for this layer: hope an upper layer helps
                continue
            layer_minimum = layer.chunks_as_set - erasures_not_recovered
            for j in erasures:
                erasures_not_recovered.discard(j)
                erasures_want.discard(j)
            minimum |= layer_minimum
        if not erasures_want:
            minimum |= want_to_read
            minimum -= erasures_total
            return minimum

        # Case 3: recover everything recoverable, layer by layer, and read
        # all available chunks
        erasures_total = {
            i for i in range(self.get_chunk_count()) if i not in available_chunks
        }
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available_chunks)

        raise ECError(errno.EIO,
                      f"not enough chunks in {sorted(available_chunks)} "
                      f"to read {sorted(want_to_read)}")

    # -- encode / decode ----------------------------------------------------

    def encode_chunks(self, chunks: Dict[int, np.ndarray]) -> None:
        """Apply every layer in order: the global layer fills the global
        parities, then each local layer its local parity (reference
        encode_chunks, ErasureCodeLrc.cc:744 with want = all chunks)."""
        for layer in self.layers:
            layer_chunks = {
                j: chunks[c] for j, c in enumerate(layer.chunks)
            }
            layer.erasure_code.encode_chunks(layer_chunks)

    def decode_chunks(
        self,
        want_to_read: Set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: Dict[int, np.ndarray],
    ) -> None:
        """Reference decode_chunks (ErasureCodeLrc.cc:782): walk layers
        bottom-up; each successful layer decode improves ``decoded`` and
        shrinks the erasure set for the layers above."""
        erasures = {
            i for i in range(self.get_chunk_count()) if i not in chunks
        }
        want_to_read_erasures = erasures & want_to_read
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if len(layer_erasures) > layer.erasure_code.get_coding_chunk_count():
                continue  # too many erasures for this layer
            if not layer_erasures:
                continue  # all of this layer's chunks already available
            layer_want: Set[int] = set()
            layer_chunks: Dict[int, np.ndarray] = {}
            layer_decoded: Dict[int, np.ndarray] = {}
            for j, c in enumerate(layer.chunks):
                # pick from `decoded` (not `chunks`) to reuse chunks
                # recovered by previous layers
                if c not in erasures:
                    layer_chunks[j] = decoded[c]
                if c in want_to_read:
                    layer_want.add(j)
                layer_decoded[j] = decoded[c]
            layer.erasure_code.decode_chunks(
                layer_want, layer_chunks, layer_decoded)
            for j, c in enumerate(layer.chunks):
                decoded[c][...] = layer_decoded[j]
                erasures.discard(c)
            want_to_read_erasures = erasures & want_to_read
            if not want_to_read_erasures:
                break
        if want_to_read_erasures:
            raise ECError(errno.EIO,
                          f"unable to read {sorted(want_to_read_erasures)}")

    # -- batched device paths -----------------------------------------------
    #
    # The cluster stripe layer (ceph_tpu.ec.stripe) talks in LOGICAL chunk
    # ids: data chunks 0..k-1 then coding chunks k..n-1, the same order
    # chunk_index() maps to positions.  Layers think in POSITIONS (indices
    # into the mapping string), so the batch paths convert at the boundary.

    def _positions(self):
        data_pos = self.chunk_mapping[: self.data_chunk_count]
        coding_pos = self.chunk_mapping[self.data_chunk_count:]
        return data_pos, coding_pos

    def _flat_coding_matrix(self) -> "np.ndarray":
        """Compose the layer walk into ONE (m_total, k) GF(2^8) matrix
        over the logical data chunks (round 5).

        Every LRC parity — global or local — is a linear function of the
        data (local layers that read global parities compose through
        them), so the whole layered encode collapses to a single MXU
        matmul.  The honest benchmark showed the per-layer walk paying
        tiny-K matmuls plus scatter materializations for 8.9 GB/s; the
        flattened matrix runs at the plain-RS rate.  encode_chunks keeps
        the literal layer walk (it IS the reference semantics the goldens
        pin); this matrix is algebraically identical by construction."""
        import numpy as np

        from ceph_tpu.ops import gf8

        k = self.data_chunk_count
        data_pos, coding_pos = self._positions()
        expr = {c: np.zeros(k, dtype=np.uint8) for c in
                range(self.chunk_count)}
        for i, c in enumerate(data_pos):
            expr[c][i] = 1
        for layer in self.layers:
            lm = layer.erasure_code.engine.coding  # (lm, lk) bytes
            for r, cout in enumerate(layer.coding):
                acc = np.zeros(k, dtype=np.uint8)
                for j, cin in enumerate(layer.data):
                    coef = int(lm[r, j])
                    if coef:
                        acc ^= gf8.gf_mul(coef, expr[cin])
                expr[cout] = acc
        return np.stack([expr[c] for c in coding_pos])

    def encode_batch(self, data):
        """(B, k, S) logical data -> (B, m, S) coding chunks,
        device-resident, as ONE flattened-generator MXU matmul (see
        _flat_coding_matrix).

        CRITICAL: the encode bit-matrix stays HOST numpy and is passed
        as a jit ARGUMENT — a jit closure over a device-resident array
        permanently degrades every subsequent dispatch in the process on
        the axon platform (~150x).
        """
        import jax

        from ceph_tpu.ops import gf8

        if self._enc_jit is None:
            flat_bitmat = gf8.expand_bitmatrix(self._flat_coding_matrix())

            def impl(data, bitmat):
                import jax.numpy as jnp

                data = jnp.asarray(data, dtype=jnp.uint8)
                b, k, s = data.shape
                cols = data.transpose(1, 0, 2).reshape(k, b * s)
                out = gf8.bitmatrix_matmul(bitmat, cols)
                return out.reshape(out.shape[0], b, s).transpose(1, 0, 2)

            self._enc_jit = (jax.jit(impl), flat_bitmat)
        fn, bitmat = self._enc_jit
        return fn(data, bitmat)

    def decode_batch(self, erasures, chunks, want=None):
        """Batched single-pattern reconstruction, walking layers bottom-up
        exactly like decode_chunks.  ``chunks``: (B, n, S) in logical order
        with zeros at erased ids; ``erasures`` = every unavailable logical
        id; ``want`` = subset to return (default all).  Returns
        (B, len(want), S).  Jitted per erasure pattern: the whole walk is
        one device dispatch, recovery plans cached like the reference's
        decode-table caches."""
        import jax

        if want is None:
            want = tuple(erasures)
        key = (tuple(erasures), tuple(want))
        cached = self._dec_jit.get(key)
        if cached is None:
            cached = self._dec_jit[key] = self._build_flat_decode(key)
        fn, bitmat, src_ids = cached
        return fn(bitmat, jax.numpy.asarray(chunks), src_ids)

    def _build_flat_decode(self, key):
        """Compose the bottom-up layer walk for one erasure pattern into
        ONE recovery matrix over the AVAILABLE logical chunks (round 5;
        same flattening as encode — the walk is linear, so the per-step
        tiny-K matmuls + scatters collapse to a single gather+matmul).
        Host-side per pattern, cached like the reference decode tables."""
        import numpy as np

        from ceph_tpu.ec.codec import _gather_encode_batch_jit
        from ceph_tpu.ops import gf8

        erasures, want = key
        steps, out_pos = self._decode_plan(erasures, want)
        logical_to_pos = list(self.chunk_mapping)
        avail_logical = tuple(e for e in range(self.chunk_count)
                              if e not in erasures)
        basis = {logical_to_pos[e]: i
                 for i, e in enumerate(avail_logical)}
        expr: dict = {}
        for p, i in basis.items():
            row = np.zeros(len(avail_logical), dtype=np.uint8)
            row[i] = 1
            expr[p] = row
        for layer, local_erasures, layer_erased in steps:
            src = self._layer_src(layer, local_erasures)
            rmat = layer.erasure_code.engine.decode_matrix(
                src, local_erasures)              # (out, src) bytes
            for r, out_local in enumerate(local_erasures):
                acc = np.zeros(len(avail_logical), dtype=np.uint8)
                for j, s_local in enumerate(src):
                    coef = int(rmat[r, j])
                    if coef:
                        acc ^= gf8.gf_mul(coef,
                                          expr[layer.chunks[s_local]])
                expr[layer.chunks[out_local]] = acc
        flat = np.stack([expr[p] for p in out_pos])
        # round 6 (locality): drop all-zero columns so the device gather
        # reads ONLY the chunks the composed recovery actually uses — a
        # single local erasure pulls its l+1-group, not all n-1 survivors
        # (the reference's minimum_to_decode read set, ErasureCodeLrc.cc:572,
        # applied to the batched matmul).  Coefficients are untouched, so
        # the result stays bit-identical; only the source set shrinks.
        used = np.flatnonzero(flat.any(axis=0))
        if used.size == 0:
            used = np.arange(min(1, len(avail_logical)))
        flat = np.ascontiguousarray(flat[:, used])
        src_ids = tuple(avail_logical[int(i)] for i in used)
        bitmat = gf8.expand_bitmatrix(flat)
        return _gather_encode_batch_jit, bitmat, src_ids

    # -- bit-planar device layout (round 6) ---------------------------------
    #
    # LRC's layer walk is flattened to single matrices (encode: the
    # composed generator; decode: the composed pruned recovery), so the
    # planar path is the same one-matmul story as the plain matrix codes:
    # packed planes in, packed planes out, conversion only at the host
    # boundary.  LRC layers are w=8 matrix codes, so w is always 8 here.

    def planar_supported(self, chunk_size: int) -> bool:
        from ceph_tpu.ec.planar import PlanarBatch

        return PlanarBatch.supported(chunk_size, 8)

    def to_planar(self, batch):
        from ceph_tpu.ec.planar import PlanarBatch

        return PlanarBatch.from_batch(batch, w=8)

    def encode_planar(self, pb):
        from ceph_tpu.ops import gf8

        if self._enc_planar_bitmat is None:
            self._enc_planar_bitmat = gf8.expand_bitmatrix(
                self._flat_coding_matrix())
        planes = gf8.planar_matmul(self._enc_planar_bitmat, pb.planes)
        return pb.with_planes(planes, self.chunk_count -
                              self.data_chunk_count)

    def decode_planar(self, erasures, pb, want=None):
        from ceph_tpu.ec.planar import _select_chunk_rows
        from ceph_tpu.ops import gf8

        if want is None:
            want = tuple(erasures)
        key = (tuple(erasures), tuple(want))
        cached = self._dec_jit.get(key)
        if cached is None:
            cached = self._dec_jit[key] = self._build_flat_decode(key)
        _, bitmat, src_ids = cached
        src_planes = _select_chunk_rows(pb.planes, 8, src_ids)
        return pb.with_planes(gf8.planar_matmul(bitmat, src_planes),
                              len(want))

    @staticmethod
    def _layer_src(layer, local_erasures):
        ln = len(layer.chunks)
        lk = layer.erasure_code.get_data_chunk_count()
        avail = tuple(i for i in range(ln) if i not in local_erasures)
        return avail[:lk]

    def _decode_plan(self, erasures, want):
        """Host-side routing decisions for one erasure pattern: which
        layers run, with which local erasures."""
        logical_to_pos = list(self.chunk_mapping)
        erased_pos = {logical_to_pos[e] for e in erasures}
        want_pos = {logical_to_pos[e] for e in want}
        steps = []
        for layer in reversed(self.layers):
            layer_erased = [c for c in layer.chunks if c in erased_pos]
            if not layer_erased:
                continue
            if len(layer_erased) > layer.erasure_code.get_coding_chunk_count():
                continue
            local_ids = {c: j for j, c in enumerate(layer.chunks)}
            steps.append(
                (layer, tuple(local_ids[c] for c in layer_erased),
                 tuple(layer_erased)))
            erased_pos -= set(layer_erased)
            if not erased_pos & want_pos:
                break
        if erased_pos & want_pos:
            raise ECError(
                errno.EIO,
                f"unable to reconstruct positions {sorted(erased_pos & want_pos)}")
        out_pos = tuple(logical_to_pos[e] for e in want)
        return steps, out_pos

    # -- CRUSH rule generation ----------------------------------------------

    def create_rule(self, name: str, cmap) -> int:
        """Generate the multi-step indep rule (reference create_rule):
        SET_CHOOSELEAF_TRIES 5, SET_CHOOSE_TRIES 100, TAKE root, then one
        CHOOSE/CHOOSELEAF_INDEP per rule_step, then EMIT."""
        from ceph_tpu.crush import types as ct

        root = None
        for item_id, item_name in cmap.item_names.items():
            if item_name == self.rule_root:
                root = item_id
                break
        if root is None:
            raise ECError(errno.ENOENT,
                          f"root item {self.rule_root} does not exist")
        type_ids = {v: k for k, v in cmap.type_names.items()}
        steps = [
            (ct.RULE_SET_CHOOSELEAF_TRIES, 5, 0),
            (ct.RULE_SET_CHOOSE_TRIES, 100, 0),
            (ct.RULE_TAKE, root, 0),
        ]
        for s in self.rule_steps:
            op = (ct.RULE_CHOOSELEAF_INDEP if s.op == "chooseleaf"
                  else ct.RULE_CHOOSE_INDEP)
            if s.type not in type_ids:
                raise ECError(errno.EINVAL, f"unknown crush type {s.type}")
            steps.append((op, s.n, type_ids[s.type]))
        steps.append((ct.RULE_EMIT, 0, 0))
        return cmap.add_rule(
            ct.Rule(steps=steps, type=3, min_size=3,
                    max_size=self.get_chunk_count()))


def make_lrc(profile: ErasureCodeProfile):
    codec = ErasureCodeLrc()
    codec.init(profile)
    return codec
