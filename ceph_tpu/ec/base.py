"""ErasureCode base class: shared padding / mapping / decode plumbing.

Behavioral mirror of reference src/erasure-code/ErasureCode.{h,cc}: SIMD_ALIGN
chunk padding (ErasureCode.cc:30), encode_prepare split+pad (:139-174), the
generic encode (:176-192) and decode fallback (:200-233), greedy
minimum_to_decode (:91-108), profile coercion helpers (:280-328), and the
"mapping" profile key (:to_mapping).
"""

from __future__ import annotations

import errno
from typing import Dict, Iterable, List, Mapping, Set

import numpy as np

from ceph_tpu.ec.interface import ECError, ErasureCodeInterface, ErasureCodeProfile

SIMD_ALIGN = 32


class ErasureCode(ErasureCodeInterface):
    def __init__(self):
        self.k = 0
        self.m = 0
        self.w = 8
        self.chunk_mapping: List[int] = []
        self._profile: ErasureCodeProfile = {}
        self.rule_root = "default"
        self.rule_failure_domain = "host"
        self.rule_device_class = ""

    # -- profile plumbing ---------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = self.to_string("crush-root", profile, "default")
        self.rule_failure_domain = self.to_string(
            "crush-failure-domain", profile, "host"
        )
        self.rule_device_class = self.to_string("crush-device-class", profile, "")
        self.parse(profile)
        self._profile = profile
        self.prepare()

    def parse(self, profile: ErasureCodeProfile) -> None:
        self.to_mapping(profile)

    def prepare(self) -> None:
        ...

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    @staticmethod
    def to_int(name: str, profile: ErasureCodeProfile, default: str) -> int:
        if not profile.get(name):
            profile[name] = default
        try:
            return int(profile[name])
        except ValueError:
            raise ECError(errno.EINVAL, f"could not convert {name}={profile[name]}")

    @staticmethod
    def to_bool(name: str, profile: ErasureCodeProfile, default: str) -> bool:
        if not profile.get(name):
            profile[name] = default
        return profile[name] in ("yes", "true")

    @staticmethod
    def to_string(name: str, profile: ErasureCodeProfile, default: str) -> str:
        if not profile.get(name):
            profile[name] = default
        return profile[name]

    def to_mapping(self, profile: ErasureCodeProfile) -> None:
        if "mapping" in profile:
            mapping = profile["mapping"]
            data_pos = [i for i, c in enumerate(mapping) if c == "D"]
            coding_pos = [i for i, c in enumerate(mapping) if c != "D"]
            self.chunk_mapping = data_pos + coding_pos

    @staticmethod
    def sanity_check_k(k: int) -> None:
        if k < 2:
            raise ECError(errno.EINVAL, f"k={k} must be >= 2")

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_mapping(self) -> List[int]:
        return self.chunk_mapping

    def stripe_unit(self, default: int) -> int:
        """Smallest cluster stripe unit >= ``default`` this codec's batch
        layout accepts (packet-interleaved codecs need multiples of
        w*packetsize; wide fields need word multiples).  Used at pool
        create so profile defaults always compose."""
        return default

    # -- minimum_to_decode (greedy base semantics) --------------------------

    def minimum_to_decode(
        self, want_to_read: Set[int], available_chunks: Set[int]
    ) -> Set[int]:
        if want_to_read <= available_chunks:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available_chunks) < k:
            raise ECError(errno.EIO, "not enough chunks to decode")
        return set(sorted(available_chunks)[:k])

    # -- encode / decode ----------------------------------------------------

    def encode_prepare(self, raw: bytes) -> Dict[int, np.ndarray]:
        k = self.get_data_chunk_count()
        m = self.get_chunk_count() - k
        blocksize = self.get_chunk_size(len(raw))
        if blocksize == 0:
            # zero-length object: k+m empty chunks (the reference never
            # encodes empty objects; this keeps the API total)
            return {
                self.chunk_index(i): np.zeros(0, dtype=np.uint8)
                for i in range(k + m)
            }
        padded_chunks = k - len(raw) // blocksize
        encoded: Dict[int, np.ndarray] = {}
        raw_arr = np.frombuffer(raw, dtype=np.uint8)
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = raw_arr[
                i * blocksize : (i + 1) * blocksize
            ].copy()
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = np.zeros(blocksize, dtype=np.uint8)
            buf[:remainder] = raw_arr[(k - padded_chunks) * blocksize :]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return encoded

    def encode(
        self, want_to_encode: Iterable[int], data: bytes
    ) -> Dict[int, np.ndarray]:
        want = set(want_to_encode)
        encoded = self.encode_prepare(data)
        self.encode_chunks(encoded)
        return {i: c for i, c in encoded.items() if i in want}

    def decode(
        self, want_to_read: Set[int], chunks: Mapping[int, np.ndarray]
    ) -> Dict[int, np.ndarray]:
        have = set(chunks)
        if want_to_read <= have:
            return {i: np.asarray(chunks[i]) for i in want_to_read}
        k = self.get_data_chunk_count()
        m = self.get_chunk_count() - k
        blocksize = len(next(iter(chunks.values())))
        decoded: Dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = np.asarray(chunks[i], dtype=np.uint8)
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(want_to_read, chunks, decoded)
        return {i: decoded[i] for i in want_to_read}
