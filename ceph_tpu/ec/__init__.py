"""Erasure-code framework: interface, plugin registry, codec families.

Mirrors the capability surface of the reference's src/erasure-code/: the
``ErasureCodeInterface`` contract (ErasureCodeInterface.h:170-462), the shared
``ErasureCode`` base-class semantics (padding, chunk mapping, greedy
minimum_to_decode), a plugin registry, and the jerasure / isa / lrc / shec
codec families — with all bulk GF(2^8) math executed as batched TPU matmuls
(see ceph_tpu.ops.gf8).
"""

from ceph_tpu.ec.interface import ErasureCodeInterface, ECError  # noqa: F401
from ceph_tpu.ec.registry import ErasureCodePluginRegistry, factory  # noqa: F401
