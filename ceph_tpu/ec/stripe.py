"""EC stripe tessellation: logical<->chunk offset math + batched codecs.

Behavioral mirror of ECUtil::stripe_info_t (reference src/osd/ECUtil.h:31-84):
an EC object is a sequence of stripes, each stripe_width = k * stripe_unit
logical bytes wide, cut into k data chunks of stripe_unit bytes; shard s of
the object is the concatenation of that shard's chunk from every stripe.

TPU-first design: the stripe axis is the batch axis.  Encoding an object is
ONE device dispatch over (nstripes, k, unit); reading or recovering a range
is one dispatch over the touched stripes.  This is the "long sequence"
tessellation SURVEY §5 maps onto the MXU — where the reference loops
per-stripe through jerasure_matrix_encode, we hand XLA the whole batch.

Batch shapes are bucketed to powers of two so repeated object sizes reuse
compiled executables instead of triggering per-size recompiles.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np


class StripeInfo:
    """stripe_info_t analog: all offset arithmetic for a (k, stripe_unit)
    layout (reference ECUtil.h:31-84)."""

    def __init__(self, k: int, stripe_unit: int):
        if stripe_unit <= 0 or k <= 0:
            raise ValueError("k and stripe_unit must be positive")
        self.k = k
        self.chunk_size = stripe_unit
        self.stripe_width = k * stripe_unit

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1) // self.stripe_width) \
            * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset - rem + self.stripe_width if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, offset: int, length: int) -> Tuple[int, int]:
        """(stripe-aligned offset, stripe-aligned length) covering the range
        (reference offset_len_to_stripe_bounds)."""
        off = self.logical_to_prev_stripe_offset(offset)
        ln = self.logical_to_next_stripe_offset((offset - off) + length)
        return off, ln

    def object_stripes(self, logical_size: int) -> int:
        return (logical_size + self.stripe_width - 1) // self.stripe_width \
            if logical_size else 0

    def shard_size(self, logical_size: int) -> int:
        return self.object_stripes(logical_size) * self.chunk_size


def _bucket(n: int) -> int:
    """Round a stripe count up to a power of two: bounded compile count."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def _planar_ok(codec, unit: int) -> bool:
    """Does this codec carry the round-6 bit-planar layout contract for
    this stripe unit?  (Mesh adapters and odd geometries fall back to the
    byte batch path — same math, just without the layout residency.)"""
    sup = getattr(codec, "planar_supported", None)
    return bool(sup and sup(unit))


def encode_stripes(codec, sinfo: StripeInfo, data: bytes) -> np.ndarray:
    """Encode a stripe-aligned-or-padded byte range in one device dispatch.

    Returns (k+m, nstripes * unit) uint8: shard rows, chunk-per-stripe
    concatenated.  ``data`` is zero-padded to the next stripe boundary.
    The stripe batch rides the bit-planar device layout (ec/planar.py):
    ONE conversion in, one parity conversion out at the host boundary.
    """
    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    nstripes = sinfo.object_stripes(len(data))
    if nstripes == 0:
        return np.zeros((n, 0), dtype=np.uint8)
    padded = nstripes * sinfo.stripe_width
    buf = np.zeros(padded, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    batch = buf.reshape(nstripes, k, unit)
    bb = _bucket(nstripes)
    if bb != nstripes:
        batch = np.concatenate(
            [batch, np.zeros((bb - nstripes, k, unit), dtype=np.uint8)])
    # padding-waste telemetry: stripe-boundary zero fill + the power-of-2
    # batch bucket rows are bytes the device encodes but nobody stores
    from ceph_tpu.utils.perf import KERNELS

    KERNELS.inc("ec_stripe_pad_bytes",
                (padded - len(data)) + (bb - nstripes) * k * unit)
    if _planar_ok(codec, unit):
        pb = codec.to_planar(batch)
        parity = np.asarray(codec.encode_planar(pb).to_batch())[:nstripes]
    else:
        parity = np.asarray(codec.encode_batch(batch))[:nstripes]
    full = np.concatenate([batch[:nstripes], parity], axis=1)  # (ns, n, unit)
    return full.transpose(1, 0, 2).reshape(n, nstripes * unit)


def _host_engine_ok(codec) -> bool:
    """Should the coalesced encode use the vectorized host GF engine?

    On CPU jax backends XLA's emulation of the packed GF(2) bit-matmul
    (built for the MXU) runs ~100x below memory bandwidth, so the
    coalesced write path computes parity with table-driven numpy GF
    arithmetic instead — bit-exact by construction (same field, same
    coding matrix; the cross-engine equality is a tier-1 test).  Device
    backends keep the planar fused dispatch (BENCH_NOTES round 11)."""
    import jax

    if jax.default_backend() != "cpu":
        return False
    eng = getattr(codec, "engine", None)
    return eng is not None and getattr(eng, "w", 0) == 8 and \
        getattr(eng, "coding", None) is not None


def _gf_apply_host(mat: np.ndarray, batch: np.ndarray) -> np.ndarray:
    """(B, k, S) x (m, k) GF(2^8) matrix -> (B, m, S) via table-driven
    numpy: coefficient-1 terms are pure XOR (the whole of RS m=1),
    others one 256-entry LUT gather per term.  Shared by the coalesced
    host ENCODE (mat = the coding matrix) and the round-16 host DECODE
    (mat = the inverted-survivor recovery matrix) — same field, same
    tables, so either direction is bit-exact with the device path by
    construction."""
    from ceph_tpu.ops.gf8 import GF_MUL
    from ceph_tpu.utils.perf import KERNELS

    m, k = mat.shape
    b, _k, s = batch.shape
    KERNELS.inc("ec_host_matmul_calls")
    KERNELS.inc("ec_host_matmul_bytes", b * k * s)
    out = np.empty((b, m, s), dtype=np.uint8)
    for j in range(m):
        acc = None
        for i in range(k):
            c = int(mat[j, i])
            if c == 0:
                continue
            term = batch[:, i, :] if c == 1 else GF_MUL[c][batch[:, i, :]]
            if acc is None:
                acc = term.copy() if c == 1 else term
            else:
                np.bitwise_xor(acc, term, out=acc)
        out[:, j, :] = acc if acc is not None else 0
    return out


def _encode_parity_host(coding: np.ndarray, batch: np.ndarray) -> np.ndarray:
    """(B, k, S) -> (B, m, S) parity on the host GF engine."""
    return _gf_apply_host(coding, batch)


def encode_stripes_multi(codec, sinfo: StripeInfo, datas,
                         want_crcs=None):
    """Coalesced encode: N ops' stripe ranges in ONE device round trip.

    The tick-level batch of the round-11 data plane: every op's stripe
    batch concatenates along the batch axis, the combined batch pays one
    planar conversion + one fused encode dispatch, and shard rows of
    full-shard writes checksum in one crc32c batch.  Bit-exact with
    per-op ``encode_stripes`` by construction — the code is stripe-local
    (parity of stripe j never depends on other batch rows), so batch
    composition cannot change any op's shards.

    Returns ``[(shards, crcs), ...]`` aligned with ``datas``: ``shards``
    is the per-op (k+m, nstripes*unit) uint8 matrix ``encode_stripes``
    would return; ``crcs`` is the per-shard-row ``ceph_crc32c(~0, row)``
    list for ops whose ``want_crcs`` flag is set (full-shard rewrites),
    else None.
    """
    from ceph_tpu.ops.crc32c import crc32c_rows
    from ceph_tpu.utils.perf import KERNELS

    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    if want_crcs is None:
        want_crcs = [False] * len(datas)
    counts = [sinfo.object_stripes(len(d)) for d in datas]
    total = sum(counts)
    out = [None] * len(datas)
    if total == 0:
        for i in range(len(datas)):
            shards = np.zeros((n, 0), dtype=np.uint8)
            out[i] = (shards,
                      crc32c_rows(shards) if want_crcs[i] else None)
        return out
    KERNELS.inc("ec_coalesced_ticks")
    KERNELS.inc("ec_coalesced_ops", len(datas))
    batch = np.zeros((total, k, unit), dtype=np.uint8)
    pad = 0
    ofs = 0
    for d, ns in zip(datas, counts):
        if ns == 0:
            continue
        flat = batch[ofs:ofs + ns].reshape(ns * k * unit)
        flat[: len(d)] = np.frombuffer(d, dtype=np.uint8)
        pad += ns * sinfo.stripe_width - len(d)
        ofs += ns
    if _host_engine_ok(codec):
        # CPU backend: no layout conversion, no bucket padding — the
        # host GF engine is shape-agnostic and bandwidth-bound
        KERNELS.inc("ec_stripe_pad_bytes", pad)
        parity = _encode_parity_host(codec.engine.coding, batch)
    else:
        bb = _bucket(total)
        if bb != total:
            batch = np.concatenate(
                [batch, np.zeros((bb - total, k, unit), dtype=np.uint8)])
        KERNELS.inc("ec_stripe_pad_bytes",
                    pad + (bb - total) * k * unit)
        if _planar_ok(codec, unit):
            pb = codec.to_planar(batch)
            parity = np.asarray(
                codec.encode_planar(pb).to_batch())[:total]
        else:
            parity = np.asarray(codec.encode_batch(batch))[:total]
    # split parity back per op and assemble each op's shard rows
    crc_rows = []           # (out-index, shard row matrix) for one batch
    ofs = 0
    for i, ns in enumerate(counts):
        full = np.concatenate(
            [batch[ofs:ofs + ns], parity[ofs:ofs + ns]], axis=1)
        shards = full.transpose(1, 0, 2).reshape(n, ns * unit)
        ofs += ns
        out[i] = (shards, None)
        if want_crcs[i]:
            crc_rows.append((i, shards))
    # one crc32c batch per shard length group (a tick's ops usually
    # share object size; mixed sizes split into one dispatch per size)
    by_len = {}
    for i, shards in crc_rows:
        by_len.setdefault(shards.shape[1], []).append((i, shards))
    for _length, group in by_len.items():
        stacked = np.concatenate([s for _i, s in group], axis=0)
        crcs = crc32c_rows(stacked)
        for gi, (i, shards) in enumerate(group):
            out[i] = (out[i][0], crcs[gi * n:(gi + 1) * n])
    return out


def decode_stripes(
    codec,
    sinfo: StripeInfo,
    shards: Mapping[int, np.ndarray],
    logical_size: int,
) -> bytes:
    """Rebuild the logical bytes from >= k shard rows in one dispatch.

    ``shards`` maps shard id -> (nstripes * unit) bytes.  Missing data
    shards are reconstructed batched (one erasure pattern for the whole
    object, reference ECBackend reply aggregation + ECUtil::decode).
    """
    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    nstripes = sinfo.object_stripes(logical_size)
    if nstripes == 0:
        return b""
    shard_len = nstripes * unit
    have = sorted(shards)
    data_rows: Dict[int, np.ndarray] = {}
    for s in have:
        arr = np.asarray(shards[s], dtype=np.uint8)
        if arr.shape[0] != shard_len:
            raise ValueError(
                f"shard {s}: {arr.shape[0]} bytes, want {shard_len}")
        if s < k:
            data_rows[s] = arr
    missing = [s for s in range(k) if s not in data_rows]
    if missing:
        if len(have) < k:
            raise ValueError(f"only {len(have)} of {k} shards")
        full = np.zeros((nstripes, n, unit), dtype=np.uint8)
        for s in have:
            full[:, s, :] = np.asarray(
                shards[s], dtype=np.uint8).reshape(nstripes, unit)
        # erasures = every absent shard (absent parity must never be used
        # as a decode source); want = only the missing DATA shards, since
        # this function returns logical bytes — absent parity (possibly
        # simply not requested) is not reconstructed, and non-MDS codecs
        # (shec) don't search for a needlessly hard recovery plan.
        erasures = tuple(s for s in range(n) if s not in shards)
        want = tuple(s for s in range(k) if s not in shards)
        bb = _bucket(nstripes)
        if bb != nstripes:
            full = np.concatenate(
                [full, np.zeros((bb - nstripes, n, unit), dtype=np.uint8)])
        if _planar_ok(codec, unit):
            pb = codec.to_planar(full)
            recovered = np.asarray(
                codec.decode_planar(erasures, pb, want=want)
                .to_batch())[:nstripes]
        else:
            recovered = np.asarray(
                codec.decode_batch(erasures, full, want=want))[:nstripes]
        for idx, e in enumerate(want):
            data_rows[e] = recovered[:, idx, :].reshape(shard_len)
    stacked = np.stack([data_rows[s].reshape(nstripes, unit)
                        for s in range(k)], axis=1)
    return stacked.reshape(nstripes * sinfo.stripe_width)[
        :logical_size].tobytes()


def reencode_stripes(
    codec,
    sinfo: StripeInfo,
    shards: Mapping[int, np.ndarray],
    logical_size: int,
) -> np.ndarray:
    """Recovery fast path: rebuild ALL shard rows from >= k shard rows
    WITHOUT leaving the planar domain between decode and re-encode.

    The batch is converted to bit-planar once, missing data chunks are
    reconstructed planar, parity is re-derived planar, and the result is
    converted back once — so a recovery op transposes the stripe batch
    exactly once in each direction (the ECBackend::run_recovery_op analog
    used to round-trip through logical bytes, paying the layout
    conversion twice more).  Returns (k+m, nstripes * unit) uint8.
    """
    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    nstripes = sinfo.object_stripes(logical_size)
    if nstripes == 0:
        return np.zeros((n, 0), dtype=np.uint8)
    if len(shards) < k:
        raise ValueError(f"only {len(shards)} of {k} shards")
    if not _planar_ok(codec, unit):
        data = decode_stripes(codec, sinfo, shards, logical_size)
        return encode_stripes(codec, sinfo, data)
    shard_len = nstripes * unit
    full = np.zeros((nstripes, n, unit), dtype=np.uint8)
    for s in shards:
        arr = np.asarray(shards[s], dtype=np.uint8)
        if arr.shape[0] != shard_len:
            raise ValueError(
                f"shard {s}: {arr.shape[0]} bytes, want {shard_len}")
        full[:, s, :] = arr.reshape(nstripes, unit)
    bb = _bucket(nstripes)
    if bb != nstripes:
        full = np.concatenate(
            [full, np.zeros((bb - nstripes, n, unit), dtype=np.uint8)])
    pb = codec.to_planar(full)
    missing_data = tuple(s for s in range(k) if s not in shards)
    if missing_data:
        erasures = tuple(s for s in range(n) if s not in shards)
        dec = codec.decode_planar(erasures, pb, want=missing_data)
        combined = pb.concat(dec)
        order = tuple(n + missing_data.index(j) if j in missing_data else j
                      for j in range(k))
        data_pb = combined.select(order)
    else:
        data_pb = pb.select(tuple(range(k)))
    parity_pb = codec.encode_planar(data_pb)
    out = np.asarray(data_pb.concat(parity_pb).to_batch())[:nstripes]
    return out.transpose(1, 0, 2).reshape(n, shard_len)


def _assemble_logical(data_rows: Dict[int, np.ndarray], k: int,
                      nstripes: int, unit: int,
                      logical_size: int) -> bytes:
    """Interleave k data shard rows back into logical bytes."""
    stacked = np.stack([data_rows[s].reshape(nstripes, unit)
                        for s in range(k)], axis=1)
    return stacked.reshape(nstripes * k * unit)[:logical_size].tobytes()


def assemble_data_stripes(sinfo: StripeInfo, shards: Mapping[int, object],
                          logical_size: int) -> bytes:
    """The no-erasure decode: every data shard present, so the logical
    bytes are a pure host interleave (zero device work) — the fast path
    ``decode_stripes``/``decode_stripes_multi`` take internally, exposed
    for the read coalescer's non-degraded short circuit."""
    k = sinfo.k
    unit = sinfo.chunk_size
    nstripes = sinfo.object_stripes(logical_size)
    if nstripes == 0:
        return b""
    shard_len = nstripes * unit
    rows: Dict[int, np.ndarray] = {}
    for s in range(k):
        arr = np.asarray(shards[s], dtype=np.uint8)
        if arr.shape[0] != shard_len:
            raise ValueError(
                f"shard {s}: {arr.shape[0]} bytes, want {shard_len}")
        rows[s] = arr
    return _assemble_logical(rows, k, nstripes, unit, logical_size)


def _host_decode_matrix(codec, src: Tuple[int, ...],
                        want: Tuple[int, ...]) -> Optional[np.ndarray]:
    """GF(2^8) recovery matrix for the host engine (chunk[want] =
    R @ chunk[src]), or None when this codec/pattern cannot be solved
    by plain survivor-submatrix inversion (non-MDS plans like SHEC fall
    back to the codec's own decode machinery)."""
    eng = getattr(codec, "engine", None)
    if eng is None or not hasattr(eng, "decode_matrix"):
        return None
    try:
        return np.asarray(eng.decode_matrix(tuple(src), tuple(want)),
                          dtype=np.uint8)
    except Exception:
        return None


def decode_stripes_multi(codec, sinfo: StripeInfo, reqs):
    """Coalesced decode: N read gathers' shard maps in ONE device round
    trip per distinct erasure pattern — the round-16 decode twin of
    ``encode_stripes_multi`` (ROADMAP item 1).

    ``reqs`` is a sequence of ``(shards, logical_size)`` pairs shaped
    exactly like ``decode_stripes`` arguments; returns the list of
    logical byte strings, aligned with ``reqs``.  Ops with every data
    shard present never touch the device (pure host interleave); ops
    missing data shards group by their (erasures, want) pattern and
    each group pays one layout conversion + one fused decode dispatch
    for its whole concatenated stripe batch.  Engine per backend like
    the write side: CPU jax backends reconstruct through the inverted
    survivor submatrix on the table-driven host GF engine (bit-exact —
    same field, same generator), device backends keep the planar fused
    decode.  Bit-exact with per-op ``decode_stripes`` by construction:
    the code is stripe-local, so batch composition cannot change any
    op's bytes (the tier-1 read-exactness gate compares them).
    """
    from ceph_tpu.utils.perf import KERNELS

    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    out: List = [None] * len(reqs)
    groups: Dict[Tuple, List] = {}
    for i, (shards, logical_size) in enumerate(reqs):
        nstripes = sinfo.object_stripes(logical_size)
        if nstripes == 0:
            out[i] = b""
            continue
        shard_len = nstripes * unit
        arrs: Dict[int, np.ndarray] = {}
        data_rows: Dict[int, np.ndarray] = {}
        for s in sorted(shards):
            arr = np.asarray(shards[s], dtype=np.uint8)
            if arr.shape[0] != shard_len:
                raise ValueError(
                    f"shard {s}: {arr.shape[0]} bytes, want {shard_len}")
            arrs[s] = arr
            if s < k:
                data_rows[s] = arr
        missing = tuple(s for s in range(k) if s not in data_rows)
        if not missing:
            out[i] = _assemble_logical(data_rows, k, nstripes, unit,
                                       logical_size)
            continue
        if len(arrs) < k:
            raise ValueError(f"only {len(arrs)} of {k} shards")
        erasures = tuple(s for s in range(n) if s not in arrs)
        groups.setdefault((erasures, missing), []).append(
            (i, arrs, data_rows, nstripes, logical_size))
    if not groups:
        return out
    KERNELS.inc("ec_coalesced_read_ticks")
    KERNELS.inc("ec_coalesced_reads",
                sum(len(g) for g in groups.values()))
    host = _host_engine_ok(codec)
    for (erasures, want), items in groups.items():
        total = sum(ns for _i, _a, _d, ns, _ls in items)
        full = np.zeros((total, n, unit), dtype=np.uint8)
        ofs = 0
        for _i, arrs, _d, ns, _ls in items:
            for s, arr in arrs.items():
                full[ofs:ofs + ns, s, :] = arr.reshape(ns, unit)
            ofs += ns
        recovered = None
        if host:
            src = tuple(s for s in range(n) if s not in erasures)[:k]
            rmat = _host_decode_matrix(codec, src, want)
            if rmat is not None:
                recovered = _gf_apply_host(rmat, full[:, list(src), :])
        if recovered is None:
            bb = _bucket(total)
            batch = full if bb == total else np.concatenate(
                [full, np.zeros((bb - total, n, unit), dtype=np.uint8)])
            if _planar_ok(codec, unit):
                pb = codec.to_planar(batch)
                recovered = np.asarray(
                    codec.decode_planar(erasures, pb, want=want)
                    .to_batch())[:total]
            else:
                recovered = np.asarray(
                    codec.decode_batch(erasures, batch,
                                       want=want))[:total]
        ofs = 0
        for i, _arrs, data_rows, ns, logical_size in items:
            for idx, e in enumerate(want):
                data_rows[e] = recovered[ofs:ofs + ns, idx, :] \
                    .reshape(ns * unit)
            ofs += ns
            out[i] = _assemble_logical(data_rows, k, ns, unit,
                                       logical_size)
    return out


def reencode_stripes_multi(codec, sinfo: StripeInfo, reqs):
    """Coalesced recovery rebuild: N objects' full shard-row matrices in
    one device round trip per distinct missing-data pattern — the multi
    twin of ``reencode_stripes``, sharing its contract (returns the
    per-op (k+m, nstripes*unit) uint8 matrices, aligned with ``reqs``).

    CPU backends reconstruct missing data rows through the inverted
    survivor submatrix and re-derive parity with the coding matrix —
    both table-driven host GF passes, no layout conversion at all.
    Device backends ride the planar grouped round trip (one to_planar,
    one decode + one encode dispatch per pattern group); codecs without
    the planar contract fall back to coalesced decode + coalesced
    encode, which still batches the whole tick.
    """
    from ceph_tpu.utils.perf import KERNELS

    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    out: List = [None] * len(reqs)
    groups: Dict[Tuple, List] = {}
    for i, (shards, logical_size) in enumerate(reqs):
        nstripes = sinfo.object_stripes(logical_size)
        if nstripes == 0:
            out[i] = np.zeros((n, 0), dtype=np.uint8)
            continue
        if len(shards) < k:
            raise ValueError(f"only {len(shards)} of {k} shards")
        shard_len = nstripes * unit
        arrs: Dict[int, np.ndarray] = {}
        for s in sorted(shards):
            arr = np.asarray(shards[s], dtype=np.uint8)
            if arr.shape[0] != shard_len:
                raise ValueError(
                    f"shard {s}: {arr.shape[0]} bytes, want {shard_len}")
            arrs[s] = arr
        erasures = tuple(s for s in range(n) if s not in arrs)
        missing = tuple(s for s in range(k) if s not in arrs)
        groups.setdefault((erasures, missing), []).append(
            (i, arrs, nstripes, logical_size))
    if not groups:
        return out
    KERNELS.inc("ec_coalesced_reencode_ticks")
    KERNELS.inc("ec_coalesced_reencodes",
                sum(len(g) for g in groups.values()))
    host = _host_engine_ok(codec)
    planar = _planar_ok(codec, unit)
    for (erasures, want), items in groups.items():
        total = sum(ns for _i, _a, ns, _ls in items)
        # ONE assembly of the group's (total, n, unit) batch, shared by
        # the host and planar branches (the decode twin's shape)
        full = np.zeros((total, n, unit), dtype=np.uint8)
        ofs = 0
        for _i, arrs, ns, _ls in items:
            for s, arr in arrs.items():
                full[ofs:ofs + ns, s, :] = arr.reshape(ns, unit)
            ofs += ns
        rows = None                     # (total, n, unit) result batch
        if host:
            rmat = None
            if want:
                src = tuple(s for s in range(n)
                            if s not in erasures)[:k]
                rmat = _host_decode_matrix(codec, src, want)
            if not want or rmat is not None:
                if want:
                    rec = _gf_apply_host(rmat, full[:, list(src), :])
                    for idx, e in enumerate(want):
                        full[:, e, :] = rec[:, idx, :]
                data = full[:, :k, :]
                full[:, k:, :] = _gf_apply_host(codec.engine.coding,
                                                data)
                rows = full
        if rows is None and planar:
            bb = _bucket(total)
            if bb != total:
                full = np.concatenate(
                    [full, np.zeros((bb - total, n, unit),
                                    dtype=np.uint8)])
            pb = codec.to_planar(full)
            if want:
                dec = codec.decode_planar(erasures, pb, want=want)
                combined = pb.concat(dec)
                order = tuple(n + want.index(j) if j in want else j
                              for j in range(k))
                data_pb = combined.select(order)
            else:
                data_pb = pb.select(tuple(range(k)))
            parity_pb = codec.encode_planar(data_pb)
            rows = np.asarray(
                data_pb.concat(parity_pb).to_batch())[:total]
        if rows is None:
            # no planar contract and no host matrix: coalesced decode
            # to logical bytes + coalesced encode back to shard rows —
            # still one batched trip per direction for the whole group
            idxs = [i for i, _a, _ns, _ls in items]
            datas = decode_stripes_multi(
                codec, sinfo,
                [(arrs, ls) for _i, arrs, _ns, ls in items])
            encoded = encode_stripes_multi(codec, sinfo, datas)
            for i, (shards_i, _crcs) in zip(idxs, encoded):
                out[i] = shards_i
            continue
        ofs = 0
        for i, _arrs, ns, _ls in items:
            out[i] = rows[ofs:ofs + ns].transpose(1, 0, 2) \
                .reshape(n, ns * unit)
            ofs += ns
    return out


# ---------------------------------------------------------------------------
# Planar AT-REST entry points (round 19): shards enter and leave as packed
# bit-planes (ec/planar_store.py layout) — the steady-state write, read,
# RMW, recovery and scrub paths run below with ZERO byte<->plane layout
# conversions outside the sanctioned ingest (client bytes at encode) and
# egress (logical bytes at read assemble) seams.
# ---------------------------------------------------------------------------


def planar_at_rest_ok(codec, unit: int) -> bool:
    """Can this (codec, stripe_unit) pool store EC shards as packed
    bit-planes at rest?

    Requires the bitpack layout contract: a MatrixCodec-family engine
    (w == 8, byte coding matrix, survivor-submatrix decode) and a
    stripe unit that is a multiple of the 8-byte packing quantum.
    Packet-interleaved codecs (the BitmatrixCodec family — their planar
    form is the packet-row matrix, a different serialization) and
    exotic plans (LRC/SHEC locality groups, mesh adapters) keep
    byte-at-rest; the config gate falls back per pool, not per cluster.
    """
    eng = getattr(codec, "engine", None)
    if eng is None or getattr(eng, "w", 0) != 8:
        return False
    if getattr(eng, "coding", None) is None:
        return False
    if not hasattr(eng, "decode_matrix"):
        return False
    if getattr(codec, "packetsize", None) is not None:
        return False
    if unit <= 0 or unit % 8:
        return False
    sup = getattr(codec, "planar_supported", None)
    return bool(sup and sup(unit))


def _planes_rows_for(codec, src: Tuple[int, ...],
                     want: Tuple[int, ...],
                     src_planes: np.ndarray) -> Optional[np.ndarray]:
    """Reconstruct ``want`` chunks' plane rows from ``src`` chunks'
    plane rows, engine per backend: host XOR over the expanded recovery
    bit-matrix on CPU, the fused planar matmul elsewhere.  None when the
    pattern has no survivor-submatrix solution (caller falls back to the
    byte machinery)."""
    from ceph_tpu.ec import planar_store as pstore
    from ceph_tpu.ops import gf8

    rmat = _host_decode_matrix(codec, src, want)
    if rmat is None:
        return None
    if _host_engine_ok(codec):
        return pstore.planar_matmul_host(gf8.expand_bitmatrix(rmat),
                                         src_planes)
    import jax.numpy as jnp

    bitmat = codec.engine.decode_bitmat(tuple(src), tuple(want))
    return np.asarray(gf8.planar_matmul(bitmat, jnp.asarray(src_planes)))


def _parity_planes_for(codec, data_planes: np.ndarray) -> np.ndarray:
    """(k*8, cols) data plane rows -> (m*8, cols) parity plane rows."""
    from ceph_tpu.ec import planar_store as pstore
    from ceph_tpu.ops import gf8

    if _host_engine_ok(codec):
        return pstore.planar_matmul_host(
            gf8.expand_bitmatrix(codec.engine.coding), data_planes)
    import jax.numpy as jnp

    return np.asarray(gf8.planar_matmul(codec.engine._enc_bitmat,
                                        jnp.asarray(data_planes)))


def _select_shard_planes(full_planes: np.ndarray,
                         shards: Tuple[int, ...]) -> np.ndarray:
    """Row-select whole shards (8 plane rows each) from a chunk-major
    plane matrix — a pure gather, no layout change."""
    idx = np.concatenate([np.arange(s * 8, s * 8 + 8) for s in shards])
    return full_planes[idx]


def encode_planes_multi(codec, sinfo: StripeInfo, datas, want_crcs=None):
    """Coalesced encode emitting AT-REST PLANES: the planar-at-rest twin
    of ``encode_stripes_multi``.

    Returns ``[(planes, crcs), ...]`` aligned with ``datas``: ``planes``
    is the per-op (n, 8, shard_len/8) uint8 array — ``planes[s]`` is
    shard s's at-rest plane matrix, serialized by ``tobytes()`` — and
    ``crcs`` (when the op's flag is set) are per-shard
    ``ceph_crc32c(~0, byte_view)`` values computed through the planar
    row view, bit-identical to the byte anchor.  Client bytes pack into
    planes exactly ONCE (the sanctioned ingest conversion, booked on
    the ``ec_planar_ingest`` counters); parity is derived in the plane
    domain and shard bytes are never materialized.
    """
    from ceph_tpu.ec import planar_store as pstore
    from ceph_tpu.ops.crc32c import crc32c_planar_rows
    from ceph_tpu.ops.profiling import record_planar_at_rest
    from ceph_tpu.utils.perf import KERNELS

    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    if want_crcs is None:
        want_crcs = [False] * len(datas)
    counts = [sinfo.object_stripes(len(d)) for d in datas]
    total = sum(counts)
    out: List = [None] * len(datas)
    if total == 0:
        for i in range(len(datas)):
            planes = np.zeros((n, 8, 0), dtype=np.uint8)
            out[i] = (planes,
                      crc32c_planar_rows(planes.reshape(n * 8, 0))
                      if want_crcs[i] else None)
        return out
    KERNELS.inc("ec_coalesced_ticks")
    KERNELS.inc("ec_coalesced_ops", len(datas))
    batch = np.zeros((total, k, unit), dtype=np.uint8)
    pad = 0
    ofs = 0
    for d, ns in zip(datas, counts):
        if ns == 0:
            continue
        flat = batch[ofs:ofs + ns].reshape(ns * k * unit)
        flat[: len(d)] = np.frombuffer(d, dtype=np.uint8)
        pad += ns * sinfo.stripe_width - len(d)
        ofs += ns
    if _host_engine_ok(codec):
        KERNELS.inc("ec_stripe_pad_bytes", pad)
        # THE sanctioned ingest: client bytes -> planes, once per tick
        record_planar_at_rest("ingest", total * k * unit)
        rows = np.ascontiguousarray(
            batch.transpose(1, 0, 2).reshape(k, total * unit))
        data_planes = pstore.rows_to_planes(rows)
        all_planes = np.vstack(
            [data_planes, _parity_planes_for(codec, data_planes)])
    else:
        bb = _bucket(total)
        if bb != total:
            batch = np.concatenate(
                [batch, np.zeros((bb - total, k, unit), dtype=np.uint8)])
        KERNELS.inc("ec_stripe_pad_bytes", pad + (bb - total) * k * unit)
        record_planar_at_rest("ingest", total * k * unit)
        pb = codec.to_planar(batch)
        parity_pb = codec.encode_planar(pb)
        all_planes = np.vstack([np.asarray(pb.planes),
                                np.asarray(parity_pb.planes)])
    # per-op at-rest planes slice straight out of the coalesced plane
    # matrix: op columns are contiguous (unit % 8 == 0), shard s is
    # plane rows s*8..s*8+8 — no conversion, no transpose of payload
    crc_groups: Dict[int, List] = {}
    c0 = 0
    for i, ns in enumerate(counts):
        cw = ns * unit // 8
        op_planes = np.ascontiguousarray(
            all_planes[:, c0:c0 + cw]).reshape(n, 8, cw)
        c0 += cw
        out[i] = (op_planes, None)
        if want_crcs[i]:
            crc_groups.setdefault(cw, []).append((i, op_planes))
    # one planar crc dispatch per shard length group (planar row view:
    # bit-identical to the byte anchor's crc32c_rows)
    for _cw, group in crc_groups.items():
        stacked = np.concatenate(
            [p.reshape(n * 8, -1) for _i, p in group], axis=0)
        crcs = crc32c_planar_rows(stacked)
        for gi, (i, p) in enumerate(group):
            out[i] = (out[i][0], crcs[gi * n:(gi + 1) * n])
    return out


def _normalize_planes(shards, cols: int) -> Dict[int, np.ndarray]:
    """Shard map values -> (8, cols) plane matrices (serialized blobs
    reshape in place; already-shaped arrays pass through)."""
    from ceph_tpu.ec import planar_store as pstore

    out: Dict[int, np.ndarray] = {}
    for s, v in shards.items():
        arr = pstore.blob_to_planes(v) if isinstance(v, (bytes, bytearray,
                                                         memoryview)) \
            else np.ascontiguousarray(v, dtype=np.uint8).reshape(8, -1)
        if arr.shape[1] != cols:
            raise ValueError(
                f"shard {s}: {arr.shape[1]} plane cols, want {cols}")
        out[s] = arr
    return out


def _assemble_from_planes(data_planes: Dict[int, np.ndarray], k: int,
                          nstripes: int, unit: int,
                          logical_size: int) -> bytes:
    """Planar shards -> logical client bytes: THE sanctioned egress."""
    from ceph_tpu.ec import planar_store as pstore
    from ceph_tpu.ops.profiling import record_planar_at_rest

    stacked = np.vstack([data_planes[s] for s in range(k)])
    record_planar_at_rest("egress", int(stacked.size))
    rows = pstore.planes_to_rows(stacked)          # (k, shard_len)
    return _assemble_logical({s: rows[s] for s in range(k)},
                             k, nstripes, unit, logical_size)


def decode_planes_multi(codec, sinfo: StripeInfo, reqs):
    """Coalesced decode from AT-REST PLANES to logical bytes: the
    planar-at-rest twin of ``decode_stripes_multi``.

    ``reqs`` is a sequence of ``(shard_planes, logical_size)`` pairs;
    ``shard_planes`` maps shard id -> (8, shard_len/8) plane matrix (or
    its serialized blob).  Reconstruction of missing data shards runs in
    the plane domain (grouped by erasure pattern, engine per backend);
    the ONLY conversion is the final planes -> logical-bytes assemble,
    booked as the sanctioned egress.  Patterns without a
    survivor-submatrix solution fall back to the byte machinery through
    a relayout conversion (legal, counted, never on the steady state).
    """
    from ceph_tpu.ec import planar_store as pstore
    from ceph_tpu.utils.perf import KERNELS

    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    out: List = [None] * len(reqs)
    groups: Dict[Tuple, List] = {}
    for i, (shards, logical_size) in enumerate(reqs):
        nstripes = sinfo.object_stripes(logical_size)
        if nstripes == 0:
            out[i] = b""
            continue
        cols = nstripes * unit // 8
        arrs = _normalize_planes(shards, cols)
        missing = tuple(s for s in range(k) if s not in arrs)
        if not missing:
            out[i] = _assemble_from_planes(arrs, k, nstripes, unit,
                                           logical_size)
            continue
        if len(arrs) < k:
            raise ValueError(f"only {len(arrs)} of {k} shards")
        erasures = tuple(s for s in range(n) if s not in arrs)
        groups.setdefault((erasures, missing), []).append(
            (i, arrs, nstripes, logical_size))
    if not groups:
        return out
    KERNELS.inc("ec_coalesced_read_ticks")
    KERNELS.inc("ec_coalesced_reads", sum(len(g) for g in groups.values()))
    for (erasures, want), items in groups.items():
        src = tuple(s for s in range(n) if s not in erasures)[:k]
        total_cols = sum(ns for _i, _a, ns, _ls in items) * unit // 8
        src_planes = np.zeros((k * 8, total_cols), dtype=np.uint8)
        c0 = 0
        for _i, arrs, ns, _ls in items:
            cw = ns * unit // 8
            for j, s in enumerate(src):
                src_planes[j * 8:j * 8 + 8, c0:c0 + cw] = arrs[s]
            c0 += cw
        rec = _planes_rows_for(codec, src, want, src_planes)
        if rec is None:
            # unsolvable pattern for the plane engine: relayout to the
            # byte machinery (counted; never the steady state)
            for i, arrs, ns, logical_size in items:
                byte_shards = {
                    s: np.frombuffer(
                        pstore.planes_to_shard(a, seam="relayout"),
                        dtype=np.uint8)
                    for s, a in arrs.items()}
                out[i] = decode_stripes_multi(
                    codec, sinfo, [(byte_shards, logical_size)])[0]
            continue
        c0 = 0
        for i, arrs, ns, logical_size in items:
            cw = ns * unit // 8
            data_planes = {s: arrs[s] for s in range(k) if s in arrs}
            for idx, e in enumerate(want):
                data_planes[e] = rec[idx * 8:idx * 8 + 8, c0:c0 + cw]
            c0 += cw
            out[i] = _assemble_from_planes(data_planes, k, ns, unit,
                                           logical_size)
    return out


def reencode_planes_multi(codec, sinfo: StripeInfo, reqs):
    """Coalesced recovery rebuild in the plane domain: AT-REST planes
    in, AT-REST planes out — ZERO layout conversions (the recovery path
    neither ingests client bytes nor egresses logical bytes).

    ``reqs`` mirrors ``decode_planes_multi``; returns the per-op
    (n, 8, shard_len/8) uint8 arrays, aligned with ``reqs``.  Missing
    chunks' plane rows rebuild through the recovery bit-matrix, parity
    re-derives from the data plane rows, and surviving shards pass
    through untouched.
    """
    from ceph_tpu.ec import planar_store as pstore
    from ceph_tpu.utils.perf import KERNELS

    k = sinfo.k
    unit = sinfo.chunk_size
    n = codec.get_chunk_count()
    out: List = [None] * len(reqs)
    groups: Dict[Tuple, List] = {}
    for i, (shards, logical_size) in enumerate(reqs):
        nstripes = sinfo.object_stripes(logical_size)
        if nstripes == 0:
            out[i] = np.zeros((n, 8, 0), dtype=np.uint8)
            continue
        if len(shards) < k:
            raise ValueError(f"only {len(shards)} of {k} shards")
        cols = nstripes * unit // 8
        arrs = _normalize_planes(shards, cols)
        erasures = tuple(s for s in range(n) if s not in arrs)
        missing = tuple(s for s in range(k) if s not in arrs)
        groups.setdefault((erasures, missing), []).append(
            (i, arrs, nstripes, logical_size))
    if not groups:
        return out
    KERNELS.inc("ec_coalesced_reencode_ticks")
    KERNELS.inc("ec_coalesced_reencodes",
                sum(len(g) for g in groups.values()))
    for (erasures, want), items in groups.items():
        src = tuple(s for s in range(n) if s not in erasures)[:k]
        total_cols = sum(ns for _i, _a, ns, _ls in items) * unit // 8
        full = np.zeros((n * 8, total_cols), dtype=np.uint8)
        c0 = 0
        for _i, arrs, ns, _ls in items:
            cw = ns * unit // 8
            for s, a in arrs.items():
                full[s * 8:s * 8 + 8, c0:c0 + cw] = a
            c0 += cw
        rec = None
        if want:
            rec = _planes_rows_for(codec, src,
                                   want, _select_shard_planes(full, src))
            if rec is None:
                # relayout fallback through the byte reencode
                for i, arrs, ns, logical_size in items:
                    byte_shards = {
                        s: np.frombuffer(
                            pstore.planes_to_shard(a, seam="relayout"),
                            dtype=np.uint8)
                        for s, a in arrs.items()}
                    rows = reencode_stripes_multi(
                        codec, sinfo, [(byte_shards, logical_size)])[0]
                    out[i] = pstore.rows_to_planes(rows).reshape(
                        n, 8, rows.shape[1] // 8)
                    pstore.record_planar_at_rest(
                        "relayout", int(rows.size))
                continue
            for idx, e in enumerate(want):
                full[e * 8:e * 8 + 8] = rec[idx * 8:idx * 8 + 8]
        full[k * 8:] = _parity_planes_for(codec, full[: k * 8])
        c0 = 0
        for i, _arrs, ns, _ls in items:
            cw = ns * unit // 8
            out[i] = np.ascontiguousarray(
                full[:, c0:c0 + cw]).reshape(n, 8, cw)
            c0 += cw
    return out


def merge_range(old: bytes, old_size: int, offset: int, data: bytes) -> bytes:
    """Overlay ``data`` at ``offset`` onto ``old`` (zero-extending holes);
    returns the new logical object bytes."""
    new_size = max(old_size, offset + len(data))
    buf = np.zeros(new_size, dtype=np.uint8)
    if old:
        buf[: len(old)] = np.frombuffer(old, dtype=np.uint8)
    buf[offset: offset + len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf.tobytes()
