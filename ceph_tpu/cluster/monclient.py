"""MonClient targeting: monmap normalization + hunting failover.

The single implementation of the reference MonClient's session-hunting
behavior (src/mon/MonClient.cc _reopen_session: try the next monitor when
the current one stops answering), shared by the OSD daemon and the
client-side Objecter so their failover semantics cannot drift: on every
hunt the new monitor immediately receives a map subscription, keeping the
caller in its subscriber set.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ceph_tpu.cluster import messages as M

Addr = Tuple[str, int]


class MonTargeter:
    def __init__(self, messenger, mon_addr,
                 subscribe_since: Optional[Callable[[], int]] = None):
        """``mon_addr``: one (host, port) or a list of them (the monmap).
        ``subscribe_since``: epoch callback used to re-subscribe on the
        newly-hunted monitor (None disables re-subscription)."""
        self.messenger = messenger
        if mon_addr and isinstance(mon_addr[0], (list, tuple)):
            self.addrs: List[Addr] = [tuple(a) for a in mon_addr]
        else:
            self.addrs = [tuple(mon_addr)]
        self._i = 0
        self.subscribe_since = subscribe_since

    @property
    def current(self) -> Addr:
        return self.addrs[self._i]

    def hunt(self) -> None:
        self._i = (self._i + 1) % len(self.addrs)

    async def send(self, msg, raise_on_fail: bool = False) -> bool:
        """Send to the current monitor, hunting across the monmap on
        connection failure."""
        last: Optional[Exception] = None
        # RuntimeError included: asyncio raises it for writes on a
        # closing transport and the messenger re-raises it
        errs = (ConnectionError, OSError, RuntimeError)
        for _ in range(len(self.addrs)):
            try:
                await self.messenger.send_message(msg, self.current)
                return True
            except errs as e:
                last = e
                self.hunt()
                if len(self.addrs) > 1 and \
                        self.subscribe_since is not None:
                    try:
                        await self.messenger.send_message(
                            M.MMonSubscribe(
                                what="osdmap",
                                addr=self.messenger.my_addr,
                                since=self.subscribe_since()),
                            self.current)
                    except errs:
                        continue
        if raise_on_fail:
            raise last or ConnectionError("no monitor reachable")
        return False
