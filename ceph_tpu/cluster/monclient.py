"""MonClient targeting: monmap normalization + hunting failover.

The single implementation of the reference MonClient's session-hunting
behavior (src/mon/MonClient.cc _reopen_session: try the next monitor when
the current one stops answering), shared by the OSD daemon and the
client-side Objecter so their failover semantics cannot drift: on every
hunt the new monitor immediately receives a map subscription, keeping the
caller in its subscriber set.

Hunting backs off (reference mon_client_hunt_interval_backoff): each
failed target costs a capped-exponential jittered delay before the next
is tried, instead of the old immediate hammering — under a partition a
daemon's monclient no longer busy-spins the whole monmap.  The jitter
rng is injectable (chaos scenarios seed it) and the backoff resets on
any successful send.
"""

from __future__ import annotations

import asyncio
from typing import Callable, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.utils.backoff import ExpBackoff

Addr = Tuple[str, int]


class MonTargeter:
    def __init__(self, messenger, mon_addr,
                 subscribe_since: Optional[Callable[[], int]] = None,
                 rng=None):
        """``mon_addr``: one (host, port) or a list of them (the monmap).
        ``subscribe_since``: epoch callback used to re-subscribe on the
        newly-hunted monitor (None disables re-subscription).  ``rng``:
        seeded jitter source for the hunt backoff (None = fresh
        entropy)."""
        self.messenger = messenger
        if mon_addr and isinstance(mon_addr[0], (list, tuple)):
            self.addrs: List[Addr] = [tuple(a) for a in mon_addr]
        else:
            self.addrs = [tuple(mon_addr)]
        self._i = 0
        self.subscribe_since = subscribe_since
        self.backoff = ExpBackoff(base=0.05, cap=1.0, rng=rng)

    @property
    def current(self) -> Addr:
        return self.addrs[self._i]

    def hunt(self) -> None:
        self._i = (self._i + 1) % len(self.addrs)

    async def send(self, msg, raise_on_fail: bool = False) -> bool:
        """Send to the current monitor, hunting across the monmap on
        connection failure."""
        last: Optional[Exception] = None
        # RuntimeError included: asyncio raises it for writes on a
        # closing transport and the messenger re-raises it
        errs = (ConnectionError, OSError, RuntimeError)
        for attempt in range(len(self.addrs)):
            try:
                await self.messenger.send_message(msg, self.current)
                self.backoff.reset()
                return True
            except errs as e:
                last = e
                self.hunt()
                if attempt == len(self.addrs) - 1:
                    break  # out of targets: fail now, not a sleep later
                # backoff BEFORE trying the next target: a dead monmap
                # must not be hammered at loop speed
                await asyncio.sleep(self.backoff.next())
                if len(self.addrs) > 1 and \
                        self.subscribe_since is not None:
                    try:
                        await self.messenger.send_message(
                            M.MMonSubscribe(
                                what="osdmap",
                                addr=self.messenger.my_addr,
                                since=self.subscribe_since()),
                            self.current)
                    except errs:
                        continue
        if raise_on_fail:
            raise last or ConnectionError("no monitor reachable")
        return False
