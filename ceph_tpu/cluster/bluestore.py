"""BlueStore-analog: block-device layout, extent allocator, kv-backed
onode metadata, checksum verified on EVERY read.

Behavioral mirror of the reference's flagship store
(src/os/bluestore/BlueStore.cc): object DATA lives in 4 KiB blocks on a
raw block "device" (one flat file here) placed by a bitmap allocator
(BitmapAllocator analog); per-object metadata — extent map, per-block
crc32c, xattrs, omap, version — is an ONODE in a write-ahead-logged kv
(the RocksDB/BlueFS analog: append-only WAL + checkpoint, kept tiny and
replayed at mount); every read recomputes block checksums against the
onode (_verify_csum, BlueStore.cc:9012,3703-3709 — silent media
corruption surfaces as EIO, never as returned garbage).

Write path is COW: new bytes land in FRESHLY allocated blocks; old
blocks free once the onode points at the new ones, so a torn write can
never corrupt committed data.  Transactions ride the kv WAL whole
(i.e. small writes are journaled — the shape of BlueStore's DEFERRED
write path; the reference skips the journal for large non-deferred
writes, a documented simplification here), and replay re-runs them
against fresh allocations idempotently.

Unlike FileStore's pickle-the-world checkpoint (r3 verdict weakness
#7), checkpointing is O(onode metadata): object DATA never rewrites on
checkpoint — the block device holds it exactly once.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster.store import ObjectStore, Transaction
from ceph_tpu.ec import planar_store
from ceph_tpu.ops import crc32c as crcmod

BLOCK = 4096
SUPER_BLOCKS = 16                    # reserved: superblock region
_FRAME = struct.Struct("<I")


@dataclass
class Onode:
    """Per-object metadata (bluestore_onode_t analog)."""

    size: int = 0
    blocks: List[int] = field(default_factory=list)   # logical idx -> blkno
    csums: List[int] = field(default_factory=list)    # per-block crc32c
    xattrs: Dict[str, bytes] = field(default_factory=dict)
    omap: Dict[str, bytes] = field(default_factory=dict)
    version: int = 0
    # at-rest data layout (round 19): None = bytes; planar8 means the
    # blocks hold the shard's packed bit-plane matrix row-major.  Read
    # with getattr(o, "layout", None) — kv checkpoints written before
    # this field existed unpickle without it.
    layout: Optional[str] = None


class BitmapAllocator:
    """Free-block bitmap (reference BitmapAllocator): first-fit block
    allocation; contiguity is incidental (extents are per-block)."""

    def __init__(self, n_blocks: int):
        self.free = bytearray(b"\x01" * n_blocks)
        self.hint = 0
        self.n_free = n_blocks

    def alloc(self, n: int) -> List[int]:
        if n > self.n_free:
            raise OSError(28, "ENOSPC: block device full")
        out: List[int] = []
        i = self.hint
        total = len(self.free)
        scanned = 0
        while len(out) < n and scanned <= total:
            if self.free[i]:
                self.free[i] = 0
                out.append(i)
            i = (i + 1) % total
            scanned += 1
        if len(out) < n:           # bitmap said free but scan missed: bug
            for b in out:
                self.free[b] = 1
            raise OSError(28, "ENOSPC: allocator inconsistency")
        self.hint = i
        self.n_free -= n
        return out

    def release(self, blocks: List[int]) -> None:
        for b in blocks:
            if not self.free[b]:
                self.free[b] = 1
                self.n_free += 1


class BlueStore(ObjectStore):
    def __init__(self, path: str, size: int = 256 << 20,
                 checkpoint_every: int = 512, fsync: bool = False):
        self.path = path
        self.device_size = size
        # the superblock region is reserved: allocatable blocks must all
        # land INSIDE the declared device size
        self.n_blocks = max(0, size // BLOCK - SUPER_BLOCKS)
        self.fsync = fsync
        self.checkpoint_every = checkpoint_every
        self._onodes: Dict[str, Dict[str, Onode]] = {}   # coll -> oid -> onode
        self._lock = threading.RLock()
        self._dev = None
        self._wal = None
        self._since_ckpt = 0
        self._mounted = False
        self.alloc = BitmapAllocator(self.n_blocks)

    # -- paths -------------------------------------------------------------

    @property
    def _block_path(self):
        return os.path.join(self.path, "block")

    @property
    def _kv_path(self):
        return os.path.join(self.path, "kv.ckpt")

    @property
    def _wal_path(self):
        return os.path.join(self.path, "kv.wal")

    # -- mount/umount ------------------------------------------------------

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        if not os.path.exists(self._block_path):
            with open(self._block_path, "wb") as f:
                f.truncate(self.device_size)
        # r+b, NOT append mode: append mode ignores seek() on write and
        # every block would land at EOF
        self._dev = open(self._block_path, "r+b")
        if os.path.exists(self._kv_path):
            with open(self._kv_path, "rb") as f:
                self._onodes = pickle.load(f)
        # freelist BEFORE replay: replayed writes allocate fresh blocks,
        # and an all-free bitmap would hand them blocks the checkpointed
        # onodes already own — clobbering committed data
        self._rebuild_allocator()
        # WAL replay: metadata txns since the last kv checkpoint
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    (n,) = _FRAME.unpack(hdr)
                    blob = f.read(n)
                    if len(blob) < n:
                        break  # torn tail: discard
                    txn = Transaction.decode(blob)
                    with self._lock:
                        for op in txn.ops:
                            self._apply(op, replay=True)
        self._wal = open(self._wal_path, "ab")
        self._mounted = True

    def _rebuild_allocator(self) -> None:
        """Free map = everything not referenced by an onode (the mount-
        time freelist rebuild, reference fsck/allocation recovery)."""
        self.alloc = BitmapAllocator(self.n_blocks)
        used: List[int] = []
        for coll in self._onodes.values():
            for o in coll.values():
                used.extend(b for b in o.blocks if b >= 0)
        for b in used:
            if self.alloc.free[b]:
                self.alloc.free[b] = 0
                self.alloc.n_free -= 1

    def umount(self) -> None:
        if self._mounted:
            self.checkpoint()
            self._wal.close()
            self._wal = None
            self._dev.close()
            self._dev = None
            self._mounted = False

    def crash(self, torn_tail: bool = False, lose_frames: int = 0) -> None:
        """Power-cut stop (chaos disk injector): close WITHOUT the
        clean kv checkpoint, drop RAM onode state, optionally damage the
        kv WAL tail (torn frame / lost frames).  mount() then replays
        checkpoint + surviving WAL over the block device like a machine
        that lost power mid-write."""
        from ceph_tpu.cluster.filestore import _damage_journal

        if not self._mounted:
            return
        self._wal.close()
        self._wal = None
        self._dev.close()
        self._dev = None
        self._mounted = False
        self._onodes = {}
        self._since_ckpt = 0
        _damage_journal(self._wal_path, torn_tail, lose_frames)

    def debug_bitrot(self, coll: str, oid: str, bit: int) -> None:
        """Flip one bit of the object's stored data ON THE DEVICE,
        leaving the onode csums untouched: the next read of that block
        raises EIO (the csum-verify path) — silent media corruption
        exactly as BlueStore meets it."""
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            if o is None or o.size == 0:
                raise FileNotFoundError(f"{coll}/{oid}")
            bit %= o.size * 8
            idx = (bit // 8) // BLOCK
            blkno = o.blocks[idx]
            if blkno < 0:
                raise ValueError(f"{coll}/{oid} block {idx} is a hole")
            off = (SUPER_BLOCKS + blkno) * BLOCK + (bit // 8) % BLOCK
            self._dev.seek(off)
            cur = self._dev.read(1)
            self._dev.seek(off)
            self._dev.write(bytes([cur[0] ^ (1 << (bit % 8))]))
            self._dev.flush()

    def checkpoint(self) -> None:
        """Atomic ONODE-kv snapshot + WAL truncate: O(metadata), never
        O(data) — the block device is untouched."""
        tmp = self._kv_path + ".tmp"
        with self._lock:
            if self._wal is None:
                return
            with open(tmp, "wb") as f:
                pickle.dump(self._onodes, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._kv_path)
            self._wal.close()
            self._wal = open(self._wal_path, "wb")
            self._since_ckpt = 0

    # -- block IO ----------------------------------------------------------

    def _write_block(self, blkno: int, data: bytes) -> int:
        assert len(data) <= BLOCK
        if len(data) < BLOCK:
            data = data + b"\0" * (BLOCK - len(data))
        off = (SUPER_BLOCKS + blkno) * BLOCK
        self._dev.seek(off)
        self._dev.write(data)
        return crcmod.crc32c(0xFFFFFFFF, data)

    def _read_block(self, coll: str, oid: str, o: Onode, idx: int) -> bytes:
        blkno = o.blocks[idx]
        if blkno < 0:
            return b"\0" * BLOCK      # hole
        self._dev.seek((SUPER_BLOCKS + blkno) * BLOCK)
        data = self._dev.read(BLOCK)
        # csum verify on EVERY read (BlueStore.cc:9012): silent media
        # corruption becomes EIO, never returned bytes
        if crcmod.crc32c(0xFFFFFFFF, data) != o.csums[idx]:
            raise IOError(
                f"csum mismatch {coll}/{oid} block {idx} (blk {blkno})")
        return data

    # -- transaction application -------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        if not self._mounted:
            raise RuntimeError("BlueStore not mounted")
        if self.chaos is not None:
            # injected ENOSPC: refuse the whole txn up front, exactly
            # like the real up-front capacity check below
            self.chaos.on_write(txn)
        with self._lock:
            # up-front capacity check: a mid-transaction ENOSPC would
            # leave half-applied onode state with no rollback, which the
            # next checkpoint would bless as committed truth
            need = self._txn_block_cost(txn)
            if need > self.alloc.n_free:
                raise OSError(28, f"ENOSPC: txn needs {need} blocks, "
                                  f"{self.alloc.n_free} free")
            # apply (COW into fresh blocks) then WAL-commit the txn;
            # crash replay re-applies idempotently over fresh blocks
            for op in txn.ops:
                self._apply(op)
            blob = txn.encode()
            self._wal.write(_FRAME.pack(len(blob)) + blob)
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
            self._dev.flush()
        # store-commit boundary on the current op's timeline: the txn is
        # WAL-durable here (no-op outside a tracked dispatch)
        from ceph_tpu.cluster.optracker import mark_current

        mark_current("store:commit")
        self._since_ckpt += 1
        if self._since_ckpt >= self.checkpoint_every:
            self.checkpoint()
        if self.chaos is not None:
            self.chaos.maybe_rot(self, txn)

    def _txn_block_cost(self, txn: Transaction) -> int:
        """Worst-case fresh-block demand of a transaction (write ops COW
        every touched block; clones copy the whole source)."""
        need = 0
        for op in txn.ops:
            if op[0] == "write":
                _, _, _, offset, data = op
                if data:
                    need += (offset + len(data) - 1) // BLOCK \
                        - offset // BLOCK + 1
            elif op[0] == "write_planar":
                # whole-matrix COW rewrite: blocks of the FINAL size
                # (old blocks free only after the onode repoints)
                _, _, _, _, _, total_cols = op
                need += (8 * total_cols + BLOCK - 1) // BLOCK
            elif op[0] == "truncate":
                need += 1                       # partial-tail rewrite
            elif op[0] == "clone":
                src = self._onodes.get(op[1], {}).get(op[2])
                if src is not None:
                    need += sum(1 for b in src.blocks if b >= 0)
        return need

    def _coll(self, coll: str) -> Dict[str, Onode]:
        return self._onodes.setdefault(coll, {})

    def _onode(self, coll: str, oid: str) -> Onode:
        return self._coll(coll).setdefault(oid, Onode())

    def _free_onode(self, o: Onode) -> None:
        self.alloc.release([b for b in o.blocks if b >= 0])

    def _apply(self, op: Tuple, replay: bool = False) -> None:
        kind = op[0]
        if kind == "create_collection":
            self._onodes.setdefault(op[1], {})
        elif kind == "remove_collection":
            for o in self._onodes.pop(op[1], {}).values():
                self._free_onode(o)
        elif kind == "touch":
            self._onode(op[1], op[2])
        elif kind == "write":
            _, coll, oid, offset, data = op
            o = self._coll(coll).get(oid)
            if o is not None and \
                    getattr(o, "layout", None) == planar_store.LAYOUT_PLANAR:
                # byte write onto a planar object: it leaves planar-at-
                # rest.  A partial overlay must land on LOGICAL bytes,
                # so materialize once (counted relayout) first.
                end = offset + len(data)
                if not (offset == 0 and o.size <= end) and o.size:
                    raw = self._read_all_replay_ok(coll, oid, o, replay)
                    logical = planar_store.planes_to_shard(
                        planar_store.blob_to_planes(raw), seam="relayout")
                    self._do_truncate(coll, oid, 0, replay)
                    self._do_write(coll, oid, 0, logical, replay)
                o.layout = None
            self._do_write(coll, oid, offset, data, replay)
        elif kind == "write_planar":
            _, coll, oid, plane_off, data, total_cols = op
            self._do_write_planar(coll, oid, plane_off, data, total_cols,
                                  replay)
        elif kind == "truncate":
            _, coll, oid, size = op
            o = self._coll(coll).get(oid)
            if o is not None and o.size != size and o.size and \
                    getattr(o, "layout", None) == planar_store.LAYOUT_PLANAR:
                # byte truncate of a planar object cuts PLANE ROWS, not
                # logical bytes — leave planar first (counted relayout)
                raw = self._read_all_replay_ok(coll, oid, o, replay)
                logical = planar_store.planes_to_shard(
                    planar_store.blob_to_planes(raw), seam="relayout")
                self._do_truncate(coll, oid, 0, replay)
                self._do_write(coll, oid, 0, logical, replay)
                o.layout = None
            self._do_truncate(coll, oid, size, replay)
        elif kind == "remove":
            o = self._coll(op[1]).pop(op[2], None)
            if o is not None:
                self._free_onode(o)
        elif kind == "clone":
            _, coll, src, dst = op
            self._do_clone(coll, src, dst, replay)
        elif kind == "rb_capture":
            _, coll, oid, rb_oid, key = op
            o = self._coll(coll).get(oid)
            try:
                data = self._read_all(coll, oid, o) if o is not None \
                    else b""
            except IOError:
                if not replay:
                    raise
                # replay over blocks a later pre-crash txn reused: the
                # record is unrecoverable, but a dead rollback record
                # must not make the store unmountable
                data = b""
                o = None
            rec = {
                "oid": oid, "existed": o is not None, "chunk_off": 0,
                "old_range": data,
                "old_total": o.size if o else 0,
                "old_attrs": ({k: o.xattrs.get(k)
                               for k in ("shard", "size", "hinfo_crc")}
                              if o else {}),
                "old_version": o.version if o else 0,
                # at-rest layout travels with the rollback record so a
                # rewind restores planar objects AS planar
                "layout": getattr(o, "layout", None) if o else None,
            }
            self._onode(coll, rb_oid).omap[key] = pickle.dumps(rec)
        elif kind == "setattr":
            _, coll, oid, name, value = op
            self._onode(coll, oid).xattrs[name] = value
        elif kind == "rmattr":
            _, coll, oid, name = op
            o = self._coll(coll).get(oid)
            if o is not None:
                o.xattrs.pop(name, None)
        elif kind == "omap_set":
            _, coll, oid, kv = op
            self._onode(coll, oid).omap.update(kv)
        elif kind == "omap_rmkeys":
            _, coll, oid, keys = op
            o = self._coll(coll).get(oid)
            if o is not None:
                for k in keys:
                    o.omap.pop(k, None)
        elif kind == "set_version":
            _, coll, oid, version = op
            self._onode(coll, oid).version = version
        else:
            raise ValueError(f"unknown transaction op {kind}")

    def _do_write(self, coll, oid, offset, data, replay) -> None:
        """COW block write: touched blocks get FRESH allocations; the old
        blocks free once the onode points at the new ones."""
        o = self._onode(coll, oid)
        if not data:
            return
        end = offset + len(data)
        n_blocks = (max(o.size, end) + BLOCK - 1) // BLOCK
        while len(o.blocks) < n_blocks:
            o.blocks.append(-1)          # holes
            o.csums.append(0)
        for idx in range(offset // BLOCK, (end - 1) // BLOCK + 1):
            bstart = idx * BLOCK
            lo = max(offset, bstart) - bstart      # in-block range
            hi = min(end, bstart + BLOCK) - bstart
            if lo > 0 or hi < BLOCK:
                try:
                    cur = self._read_block(coll, oid, o, idx) \
                        if o.blocks[idx] >= 0 else b"\0" * BLOCK
                except IOError:
                    if not replay:
                        raise
                    cur = b"\0" * BLOCK   # replay over reused blocks
                block = bytearray(cur)
            else:
                block = bytearray(BLOCK)
            block[lo:hi] = data[(bstart + lo) - offset:
                                (bstart + hi) - offset]
            (new_blk,) = self.alloc.alloc(1)
            crc = self._write_block(new_blk, bytes(block))
            if o.blocks[idx] >= 0:
                self.alloc.release([o.blocks[idx]])
            o.blocks[idx] = new_blk
            o.csums[idx] = crc
        o.size = max(o.size, end)

    def _read_all_replay_ok(self, coll, oid, o, replay) -> bytes:
        """_read_all, but WAL replay over blocks a later pre-crash txn
        reused yields zeros instead of failing the mount."""
        try:
            return self._read_all(coll, oid, o)
        except IOError:
            if not replay:
                raise
            return b"\0" * o.size

    def _do_write_planar(self, coll, oid, plane_off, data, total_cols,
                         replay) -> None:
        """Planar-at-rest shard write: splice the (8, wc) plane-column
        window into the object's plane matrix and rewrite it whole —
        COW into fresh blocks like every other write.  A full rewrite
        (the common EC case: whole-shard window, plane_off 0) never
        reads the old blocks; only a windowed splice (RMW delta) does.
        Documented simplification vs per-block surgery: shard objects
        are a handful of blocks, and the COW rewrite keeps csums and
        crash replay identical to the byte path."""
        o = self._onode(coll, oid)
        window = planar_store.blob_to_planes(data)
        full_rewrite = plane_off == 0 and window.shape[1] >= total_cols
        cur = None
        if o.size and not full_rewrite:
            raw = self._read_all_replay_ok(coll, oid, o, replay)
            if len(raw) % 8:
                raw += b"\0" * (8 - len(raw) % 8)
            if getattr(o, "layout", None) == planar_store.LAYOUT_PLANAR:
                cur = planar_store.blob_to_planes(raw)
            else:
                # planar write landing on a byte-at-rest object: the
                # config gate flipped mid-life — convert once, counted
                cur = planar_store.shard_to_planes(raw, seam="relayout")
        merged = planar_store.splice_columns(
            cur, plane_off, window, total_cols)
        self._do_truncate(coll, oid, 0, replay)
        self._do_write(coll, oid, 0, planar_store.planes_to_blob(merged),
                       replay)
        o.size = 8 * total_cols
        o.layout = planar_store.LAYOUT_PLANAR

    def _do_truncate(self, coll, oid, size, replay) -> None:
        o = self._onode(coll, oid)
        n_blocks = (size + BLOCK - 1) // BLOCK
        if size < o.size:
            dead = [b for b in o.blocks[n_blocks:] if b >= 0]
            self.alloc.release(dead)
            del o.blocks[n_blocks:]
            del o.csums[n_blocks:]
            # zero the tail of the last partial block (COW)
            if size % BLOCK and o.blocks and o.blocks[-1] >= 0:
                try:
                    cur = bytearray(self._read_block(
                        coll, oid, o, len(o.blocks) - 1))
                except IOError:
                    if not replay:
                        raise
                    cur = bytearray(BLOCK)
                cur[size % BLOCK:] = b"\0" * (BLOCK - size % BLOCK)
                (nb,) = self.alloc.alloc(1)
                crc = self._write_block(nb, bytes(cur))
                self.alloc.release([o.blocks[-1]])
                o.blocks[-1] = nb
                o.csums[-1] = crc
        else:
            while len(o.blocks) < n_blocks:
                o.blocks.append(-1)
                o.csums.append(0)
        o.size = size

    def _do_clone(self, coll, src, dst, replay) -> None:
        s = self._coll(coll).get(src)
        if s is None:
            return
        old = self._coll(coll).pop(dst, None)
        if old is not None:
            self._free_onode(old)
        d = Onode(size=s.size, xattrs=dict(s.xattrs), omap=dict(s.omap),
                  version=s.version, layout=getattr(s, "layout", None))
        # physical copy block-by-block (no refcounted blobs — documented
        # simplification of the reference's shared-blob clone)
        for idx, blk in enumerate(s.blocks):
            if blk < 0:
                d.blocks.append(-1)
                d.csums.append(0)
                continue
            try:
                data = self._read_block(coll, src, s, idx)
            except IOError:
                if not replay:
                    raise
                data = b"\0" * BLOCK
            (nb,) = self.alloc.alloc(1)
            d.blocks.append(nb)
            d.csums.append(self._write_block(nb, data))
        self._coll(coll)[dst] = d

    # -- reads (ObjectStore contract, csum-verified) -----------------------

    def _read_all(self, coll: str, oid: str, o: Onode) -> bytes:
        out = bytearray()
        for idx in range(len(o.blocks)):
            out += self._read_block(coll, oid, o, idx)
        return bytes(out[: o.size])

    def read(self, coll: str, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        if self.chaos is not None:
            self.chaos.on_read(coll, oid)
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            if o is None:
                raise FileNotFoundError(f"{coll}/{oid}")
            if getattr(o, "layout", None) == planar_store.LAYOUT_PLANAR \
                    and o.size:
                # byte view of a planar object OUTSIDE the sanctioned
                # seams (egress of last resort): logical byte 8i+u needs
                # column i of ALL 8 plane rows, so the whole object is
                # read and csum-verified; books the ``unseamed``
                # counter the steady-state contract pins to zero.
                data = planar_store.planes_to_shard(  # graftlint: ignore[planar-conversion-hygiene]
                    planar_store.blob_to_planes(self._read_all(
                        coll, oid, o)), seam="unseamed")
                if length is None:
                    return data[offset:]
                return data[offset : offset + length]
            end = o.size if length is None else min(o.size,
                                                    offset + length)
            if offset >= end:
                return b""
            # touch (and csum-verify) ONLY the blocks in range — a 4 KiB
            # read of a 4 MiB object must not verify all 1024 blocks
            first, last = offset // BLOCK, (end - 1) // BLOCK
            out = bytearray()
            for idx in range(first, last + 1):
                out += self._read_block(coll, oid, o, idx)
            lo = offset - first * BLOCK
            return bytes(out[lo: lo + (end - offset)])

    def read_planar(self, coll: str, oid: str) -> bytes:
        """The at-rest plane blob as stored — ZERO layout conversion
        (csum-verified block reads).  Callers gate on object_layout; a
        byte-at-rest object raises."""
        if self.chaos is not None:
            self.chaos.on_read(coll, oid)
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            if o is None:
                raise FileNotFoundError(f"{coll}/{oid}")
            if getattr(o, "layout", None) != planar_store.LAYOUT_PLANAR:
                raise ValueError(f"{coll}/{oid} is not planar-at-rest")
            return self._read_all(coll, oid, o)

    def object_layout(self, coll: str, oid: str) -> Optional[str]:
        """At-rest layout tag (None = bytes / missing object)."""
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            return None if o is None else getattr(o, "layout", None)

    def stat(self, coll: str, oid: str) -> Optional[int]:
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            return None if o is None else o.size

    def get_version(self, coll: str, oid: str) -> int:
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            return 0 if o is None else o.version

    def getattr(self, coll: str, oid: str, name: str) -> Optional[bytes]:
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            return None if o is None else o.xattrs.get(name)

    def get_xattrs(self, coll: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            return {} if o is None else dict(o.xattrs)

    def omap_get(self, coll: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._onodes.get(coll, {}).get(oid)
            return {} if o is None else dict(o.omap)

    def list_objects(self, coll: str) -> List[str]:
        with self._lock:
            return sorted(self._onodes.get(coll, {}))

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(self._onodes)

    def statfs(self) -> Tuple[int, int]:
        """O(1) from the allocator (BlueStore::statfs)."""
        with self._lock:
            used = (self.n_blocks - self.alloc.n_free) * BLOCK
            return (self.device_size, used)
