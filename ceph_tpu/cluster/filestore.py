"""FileStore: a durable, journaled ObjectStore.

Behavioral analog of the reference's journaling object store (FileStore:
write-ahead journal + apply, src/os/filestore; same Transaction contract as
BlueStore's txn path, src/os/ObjectStore.h:1470-1498 and
src/os/bluestore/BlueStore.cc:9012): every Transaction is framed and
appended to a write-ahead journal BEFORE being applied to the in-memory
state, and a periodic checkpoint (atomic tmp+rename snapshot) bounds
journal replay.  mount() restores checkpoint + replays the journal tail,
so an OSD restart resumes with all data, xattrs, omaps, versions, and the
persisted PG logs intact — the restart-resume path the reference drives
from OSD::init (read_superblock/load_pgs, src/osd/OSD.cc:2556,2572).

Design choice (TPU-framework, not a disk engine): state is RAM-resident
(MemStore semantics) with durability from the journal — the dev-cluster
and tests exercise the exact ObjectStore contract while the hot I/O path
stays allocation-free.  A block-device store (BlueStore analog) can slot
under the same contract later.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Optional

from ceph_tpu.cluster.store import MemStore, Transaction

_FRAME = struct.Struct("<I")


def _damage_journal(path: str, torn_tail: bool, lose_frames: int) -> None:
    """Crash-model journal damage: truncate away the last ``lose_frames``
    committed frames, then (optionally) re-append HALF of the next frame
    so the tail is torn mid-write.  Chaos counters tick per mutation."""
    if not os.path.exists(path) or (not torn_tail and not lose_frames):
        return
    from ceph_tpu.chaos.counters import CHAOS

    offsets = []   # frame start offsets
    with open(path, "rb") as f:
        off = 0
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                break
            (n,) = _FRAME.unpack(hdr)
            blob = f.read(n)
            if len(blob) < n:
                break   # already-torn tail: leave as-is
            offsets.append((off, 4 + n))
            off += 4 + n
    victims = offsets[max(0, len(offsets) - lose_frames):] \
        if lose_frames else []
    keep_end = victims[0][0] if victims else (
        offsets[-1][0] if torn_tail and offsets else None)
    if keep_end is None:
        return
    torn_src = None
    if torn_tail:
        # the frame being torn: the first lost frame (its write "was in
        # flight" at the cut) or the last surviving one
        torn_src = victims[0] if victims else offsets[-1]
    with open(path, "rb+") as f:
        torn_bytes = b""
        if torn_src is not None:
            f.seek(torn_src[0])
            whole = f.read(torn_src[1])
            torn_bytes = whole[: max(5, torn_src[1] // 2)]
        f.truncate(keep_end)
        if torn_bytes:
            f.seek(keep_end)
            f.write(torn_bytes)
            CHAOS.inc("disk_torn_journals")
    if victims:
        CHAOS.inc("disk_lost_frames", len(victims))


class FileStore(MemStore):
    def __init__(self, path: str, checkpoint_every: int = 2048,
                 fsync: bool = False, device_bytes: int = 1 << 30):
        super().__init__(device_bytes)
        self.path = path
        self.checkpoint_every = checkpoint_every
        self.fsync = fsync
        self._journal = None
        self._since_checkpoint = 0
        self._mounted = False
        self._ckpt_inflight = False

    # -- lifecycle ----------------------------------------------------------

    @property
    def _ckpt_path(self) -> str:
        return os.path.join(self.path, "checkpoint.bin")

    @property
    def _journal_path(self) -> str:
        return os.path.join(self.path, "journal.bin")

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        if os.path.exists(self._ckpt_path):
            with open(self._ckpt_path, "rb") as f:
                self._colls = pickle.load(f)
            # the checkpoint restores the object map wholesale: rebuild
            # the incremental used-bytes counter before journal replay
            # (replayed ops then adjust it like live transactions)
            self._recount_used()
        if os.path.exists(self._journal_path):
            with open(self._journal_path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    (n,) = _FRAME.unpack(hdr)
                    blob = f.read(n)
                    if len(blob) < n:
                        break  # torn tail write: discard (atomic replay)
                    txn = Transaction.decode(blob)
                    with self._lock:
                        for op in txn.ops:
                            self._apply(op)
        self._journal = open(self._journal_path, "ab")
        self._mounted = True

    def umount(self) -> None:
        if self._mounted:
            self.checkpoint()
            self._journal.close()
            self._journal = None
            self._mounted = False

    def crash(self, torn_tail: bool = False, lose_frames: int = 0) -> None:
        """Power-cut stop (chaos disk injector): close WITHOUT the
        clean-shutdown checkpoint, drop all RAM state, and optionally
        mutate the on-disk journal tail — ``lose_frames`` discards the
        last N committed frames (lost writes), ``torn_tail`` truncates
        the (remaining) last frame mid-bytes so mount() meets a torn
        write and must discard it atomically.  The next mount() resumes
        from checkpoint + surviving journal exactly like a machine that
        lost power."""
        if not self._mounted:
            return
        self._journal.close()
        self._journal = None
        self._mounted = False
        self._colls = {}
        self._used = 0
        self._since_checkpoint = 0
        _damage_journal(self._journal_path, torn_tail, lose_frames)

    def checkpoint(self) -> None:
        """Atomic snapshot + journal truncate (bounded replay)."""
        tmp = self._ckpt_path + ".tmp"
        with self._lock:
            if self._journal is None:
                return  # raced umount; final checkpoint already ran
            with open(tmp, "wb") as f:
                pickle.dump(self._colls, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._ckpt_path)
            self._journal.close()
            self._journal = open(self._journal_path, "wb")
            self._since_checkpoint = 0

    # -- transactions -------------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        if not self._mounted:
            raise RuntimeError("FileStore not mounted")
        if self.chaos is not None:
            # refuse BEFORE the journal write: an injected ENOSPC must
            # not leave a journaled-but-unapplied frame
            self.chaos.on_write(txn)
        # the round-16 capacity backstop, likewise pre-journal: a
        # refused txn must never persist a frame replay would re-apply
        self._check_capacity(txn)
        blob = txn.encode()
        with self._lock:
            self._journal.write(_FRAME.pack(len(blob)) + blob)
            self._journal.flush()
            if self.fsync:
                os.fsync(self._journal.fileno())
        self._commit(txn)
        if self.chaos is not None:
            # rot hits the live (RAM) state only — like media decay on
            # the applied copy; the journal frame stays pristine
            self.chaos.maybe_rot(self, txn)
        # store-commit boundary on the current op's timeline: the txn is
        # journal-durable and applied (no-op outside a tracked dispatch)
        from ceph_tpu.cluster.optracker import mark_current

        mark_current("store:commit")
        self._since_checkpoint += 1
        if self._since_checkpoint >= self.checkpoint_every and \
                not self._ckpt_inflight:
            # checkpoint OFF the caller's thread: a synchronous whole-store
            # pickle would stall the OSD event loop (heartbeats/beacons)
            # for the duration; the journal keeps durability meanwhile
            self._ckpt_inflight = True
            self._since_checkpoint = 0
            import asyncio

            def _bg():
                try:
                    self.checkpoint()
                finally:
                    self._ckpt_inflight = False

            try:
                asyncio.get_running_loop().run_in_executor(None, _bg)
            except RuntimeError:
                _bg()
