"""FileStore: a durable, journaled ObjectStore.

Behavioral analog of the reference's journaling object store (FileStore:
write-ahead journal + apply, src/os/filestore; same Transaction contract as
BlueStore's txn path, src/os/ObjectStore.h:1470-1498 and
src/os/bluestore/BlueStore.cc:9012): every Transaction is framed and
appended to a write-ahead journal BEFORE being applied to the in-memory
state, and a periodic checkpoint (atomic tmp+rename snapshot) bounds
journal replay.  mount() restores checkpoint + replays the journal tail,
so an OSD restart resumes with all data, xattrs, omaps, versions, and the
persisted PG logs intact — the restart-resume path the reference drives
from OSD::init (read_superblock/load_pgs, src/osd/OSD.cc:2556,2572).

Design choice (TPU-framework, not a disk engine): state is RAM-resident
(MemStore semantics) with durability from the journal — the dev-cluster
and tests exercise the exact ObjectStore contract while the hot I/O path
stays allocation-free.  A block-device store (BlueStore analog) can slot
under the same contract later.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Optional

from ceph_tpu.cluster.store import MemStore, Transaction

_FRAME = struct.Struct("<I")


class FileStore(MemStore):
    def __init__(self, path: str, checkpoint_every: int = 2048,
                 fsync: bool = False):
        super().__init__()
        self.path = path
        self.checkpoint_every = checkpoint_every
        self.fsync = fsync
        self._journal = None
        self._since_checkpoint = 0
        self._mounted = False
        self._ckpt_inflight = False

    # -- lifecycle ----------------------------------------------------------

    @property
    def _ckpt_path(self) -> str:
        return os.path.join(self.path, "checkpoint.bin")

    @property
    def _journal_path(self) -> str:
        return os.path.join(self.path, "journal.bin")

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        if os.path.exists(self._ckpt_path):
            with open(self._ckpt_path, "rb") as f:
                self._colls = pickle.load(f)
        if os.path.exists(self._journal_path):
            with open(self._journal_path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    (n,) = _FRAME.unpack(hdr)
                    blob = f.read(n)
                    if len(blob) < n:
                        break  # torn tail write: discard (atomic replay)
                    txn = Transaction.decode(blob)
                    with self._lock:
                        for op in txn.ops:
                            self._apply(op)
        self._journal = open(self._journal_path, "ab")
        self._mounted = True

    def umount(self) -> None:
        if self._mounted:
            self.checkpoint()
            self._journal.close()
            self._journal = None
            self._mounted = False

    def checkpoint(self) -> None:
        """Atomic snapshot + journal truncate (bounded replay)."""
        tmp = self._ckpt_path + ".tmp"
        with self._lock:
            if self._journal is None:
                return  # raced umount; final checkpoint already ran
            with open(tmp, "wb") as f:
                pickle.dump(self._colls, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._ckpt_path)
            self._journal.close()
            self._journal = open(self._journal_path, "wb")
            self._since_checkpoint = 0

    # -- transactions -------------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        if not self._mounted:
            raise RuntimeError("FileStore not mounted")
        blob = txn.encode()
        with self._lock:
            self._journal.write(_FRAME.pack(len(blob)) + blob)
            self._journal.flush()
            if self.fsync:
                os.fsync(self._journal.fileno())
        super().queue_transaction(txn)
        self._since_checkpoint += 1
        if self._since_checkpoint >= self.checkpoint_every and \
                not self._ckpt_inflight:
            # checkpoint OFF the caller's thread: a synchronous whole-store
            # pickle would stall the OSD event loop (heartbeats/beacons)
            # for the duration; the journal keeps durability meanwhile
            self._ckpt_inflight = True
            self._since_checkpoint = 0
            import asyncio

            def _bg():
                try:
                    self.checkpoint()
                finally:
                    self._ckpt_inflight = False

            try:
                asyncio.get_running_loop().run_in_executor(None, _bg)
            except RuntimeError:
                _bg()
