"""dmClock: reservation/weight/limit QoS scheduling.

Behavioral analog of the reference's dmClock op scheduling
(src/dmclock/ vendored library + mClockOpClassQueue / mClockClientQueue,
src/osd/mClockOpClassQueue.h): each client class gets a QoS spec
(reservation = guaranteed ops/s, weight = proportional share of spare
capacity, limit = ops/s cap); every request is stamped with reservation/
proportion/limit tags derived from the previous tag (the dmClock paper's
tag arithmetic), and dequeue serves reservation-eligible requests by
R-tag first, then spare capacity by P-tag, never past the L-tag.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class QoSSpec:
    """Client-class service parameters (dmclock ClientInfo)."""

    reservation: float = 0.0   # guaranteed ops/s (0 = none)
    weight: float = 1.0        # share of spare capacity
    limit: float = 0.0         # ops/s cap (0 = unlimited)


@dataclass
class _Tags:
    r: float
    p: float
    l: float


class _ClientRec:
    def __init__(self, spec: QoSSpec):
        self.spec = spec
        self.prev: Optional[_Tags] = None
        self.queue: List[Tuple[int, object]] = []


class DmClockQueue:
    """Single-queue dmClock scheduler (the per-shard queue the reference
    plugs into ShardedOpWQ)."""

    def __init__(self, now=time.monotonic):
        self._clients: Dict[str, _ClientRec] = {}
        self._now = now
        self._seq = itertools.count()
        # conformance counters (dmclock PullReq phase telemetry): how
        # many dequeues were reservation-driven vs spare-capacity, and
        # how many queued requests were evicted to admit higher classes
        # under throttle pressure — exported via the OSD perf path
        self.stats: Dict[str, int] = {
            "served_reservation": 0, "served_spare": 0, "evicted": 0}

    def ensure_client(self, client: str, default: QoSSpec) -> None:
        """Install ``default`` only on first sight of the client."""
        if client not in self._clients:
            self._clients[client] = _ClientRec(default)

    def set_client(self, client: str, spec: QoSSpec) -> None:
        """Install/update a client's QoS spec; queued requests and tag
        history survive a spec change (injectargs-style live update)."""
        rec = self._clients.get(client)
        if rec is None:
            self._clients[client] = _ClientRec(spec)
        else:
            rec.spec = spec

    def enqueue(self, client: str, item) -> None:
        rec = self._clients.setdefault(client, _ClientRec(QoSSpec()))
        now = self._now()
        s = rec.spec
        prev = rec.prev
        # dmClock tag arithmetic: advance from the previous tag at the
        # class's configured rate, but never fall behind real time
        if prev is None:
            tags = _Tags(r=now, p=now, l=now)
        else:
            tags = _Tags(
                r=max(now, prev.r + (1.0 / s.reservation
                                     if s.reservation else 0.0)),
                p=max(now, prev.p + 1.0 / max(s.weight, 1e-9)),
                l=max(now, prev.l + (1.0 / s.limit if s.limit else 0.0)),
            )
        rec.prev = tags
        rec.queue.append((next(self._seq), item, tags))

    def _head(self, rec: _ClientRec):
        return rec.queue[0] if rec.queue else None

    def dequeue(self) -> Optional[object]:
        """One scheduling decision (dmclock PullPriorityQueue::pull):
        1. any reservation-eligible request (R-tag <= now) — smallest R;
        2. else the smallest P-tag whose limit allows service (L <= now);
        3. else nothing is currently eligible."""
        now = self._now()
        best_r = None
        best_p = None
        for name, rec in self._clients.items():
            head = self._head(rec)
            if head is None:
                continue
            _, _, tags = head
            if rec.spec.reservation and tags.r <= now:
                if best_r is None or tags.r < best_r[0]:
                    best_r = (tags.r, name)
            if tags.l <= now:
                if best_p is None or tags.p < best_p[0]:
                    best_p = (tags.p, name)
        pick = best_r or best_p
        if pick is None:
            return None
        self.stats["served_reservation" if pick is best_r
                   else "served_spare"] += 1
        rec = self._clients[pick[1]]
        _, item, _ = rec.queue.pop(0)
        return item

    def _evict_pick(self, match) -> Optional[str]:
        """The eviction victim's client: largest HEAD P-tag among
        matching clients with queued work — the class currently least
        entitled to service (head tag = its next scheduling position;
        the tail tag would just bias toward the longest backlog)."""
        best = None
        for name, rec in self._clients.items():
            if not rec.queue or not match(name):
                continue
            tag = rec.queue[0][2]
            if best is None or tag.p > best[0]:
                best = (tag.p, name)
        return best[1] if best is not None else None

    def peek_evict(self, match) -> Optional[object]:
        """The item ``evict(match)`` WOULD shed, without shedding it —
        the caller checks whether the eviction actually buys admission
        before dropping background work for nothing."""
        name = self._evict_pick(match)
        if name is None:
            return None
        return self._clients[name].queue[-1][1]

    def evict(self, match) -> Optional[object]:
        """Shed one queued request of a client whose name satisfies
        ``match`` — the youngest request of the client with the LARGEST
        head P-tag (the least-entitled class, its least-urgent work).
        The QoS-enforced shedding seam: under admission pressure the
        caller evicts background classes to admit reserved clients.
        Returns the evicted item, or None when nothing matches."""
        name = self._evict_pick(match)
        if name is None:
            return None
        rec = self._clients[name]
        _, item, _ = rec.queue.pop()
        self.stats["evicted"] += 1
        return item

    def evicted_total(self) -> int:
        return self.stats["evicted"]

    def purge(self, predicate) -> List[object]:
        """Remove and return every queued item satisfying ``predicate``
        (dead-work shedding: an op whose deadline passed must not wait
        for its L-tag to mature — it is dropped, not paced).  Tag
        history is untouched, so the class's pacing is unaffected."""
        out: List[object] = []
        for rec in self._clients.values():
            keep = []
            for entry in rec.queue:
                if predicate(entry[1]):
                    out.append(entry[1])
                else:
                    keep.append(entry)
            rec.queue[:] = keep
        return out

    def dump(self) -> Dict:
        """Conformance + queue-depth snapshot (the `dump_dmclock` admin
        payload): per-client spec, depth, and the global counters."""
        return {
            "stats": dict(self.stats),
            "clients": {
                name: {"reservation": rec.spec.reservation,
                       "weight": rec.spec.weight,
                       "limit": rec.spec.limit,
                       "queued": len(rec.queue)}
                for name, rec in self._clients.items()},
        }

    def next_eligible_in(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the earliest queued head becomes limit-eligible
        (None when the queue is empty; 0 when something is ready)."""
        if now is None:
            now = self._now()
        best = None
        for rec in self._clients.values():
            head = self._head(rec)
            if head is None:
                continue
            wait = max(0.0, head[2].l - now)
            if best is None or wait < best:
                best = wait
        return best

    def drain_eligible(self, max_items: int = 1 << 30) -> List[object]:
        out = []
        while len(out) < max_items:
            item = self.dequeue()
            if item is None:
                break
            out.append(item)
        return out

    def __len__(self) -> int:
        return sum(len(r.queue) for r in self._clients.values())
