"""dmClock: reservation/weight/limit QoS scheduling.

Behavioral analog of the reference's dmClock op scheduling
(src/dmclock/ vendored library + mClockOpClassQueue / mClockClientQueue,
src/osd/mClockOpClassQueue.h): each client class gets a QoS spec
(reservation = guaranteed ops/s, weight = proportional share of spare
capacity, limit = ops/s cap); every request is stamped with reservation/
proportion/limit tags derived from the previous tag (the dmClock paper's
tag arithmetic), and dequeue serves reservation-eligible requests by
R-tag first, then spare capacity by P-tag, never past the L-tag.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class QoSSpec:
    """Client-class service parameters (dmclock ClientInfo)."""

    reservation: float = 0.0   # guaranteed ops/s (0 = none)
    weight: float = 1.0        # share of spare capacity
    limit: float = 0.0         # ops/s cap (0 = unlimited)


@dataclass
class _Tags:
    r: float
    p: float
    l: float


class _ClientRec:
    def __init__(self, spec: QoSSpec):
        self.spec = spec
        self.prev: Optional[_Tags] = None
        self.queue: List[Tuple[int, object]] = []


class DmClockQueue:
    """Single-queue dmClock scheduler (the per-shard queue the reference
    plugs into ShardedOpWQ)."""

    def __init__(self, now=time.monotonic):
        self._clients: Dict[str, _ClientRec] = {}
        self._now = now
        self._seq = itertools.count()

    def ensure_client(self, client: str, default: QoSSpec) -> None:
        """Install ``default`` only on first sight of the client."""
        if client not in self._clients:
            self._clients[client] = _ClientRec(default)

    def set_client(self, client: str, spec: QoSSpec) -> None:
        """Install/update a client's QoS spec; queued requests and tag
        history survive a spec change (injectargs-style live update)."""
        rec = self._clients.get(client)
        if rec is None:
            self._clients[client] = _ClientRec(spec)
        else:
            rec.spec = spec

    def enqueue(self, client: str, item) -> None:
        rec = self._clients.setdefault(client, _ClientRec(QoSSpec()))
        now = self._now()
        s = rec.spec
        prev = rec.prev
        # dmClock tag arithmetic: advance from the previous tag at the
        # class's configured rate, but never fall behind real time
        if prev is None:
            tags = _Tags(r=now, p=now, l=now)
        else:
            tags = _Tags(
                r=max(now, prev.r + (1.0 / s.reservation
                                     if s.reservation else 0.0)),
                p=max(now, prev.p + 1.0 / max(s.weight, 1e-9)),
                l=max(now, prev.l + (1.0 / s.limit if s.limit else 0.0)),
            )
        rec.prev = tags
        rec.queue.append((next(self._seq), item, tags))

    def _head(self, rec: _ClientRec):
        return rec.queue[0] if rec.queue else None

    def dequeue(self) -> Optional[object]:
        """One scheduling decision (dmclock PullPriorityQueue::pull):
        1. any reservation-eligible request (R-tag <= now) — smallest R;
        2. else the smallest P-tag whose limit allows service (L <= now);
        3. else nothing is currently eligible."""
        now = self._now()
        best_r = None
        best_p = None
        for name, rec in self._clients.items():
            head = self._head(rec)
            if head is None:
                continue
            _, _, tags = head
            if rec.spec.reservation and tags.r <= now:
                if best_r is None or tags.r < best_r[0]:
                    best_r = (tags.r, name)
            if tags.l <= now:
                if best_p is None or tags.p < best_p[0]:
                    best_p = (tags.p, name)
        pick = best_r or best_p
        if pick is None:
            return None
        rec = self._clients[pick[1]]
        _, item, _ = rec.queue.pop(0)
        return item

    def next_eligible_in(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the earliest queued head becomes limit-eligible
        (None when the queue is empty; 0 when something is ready)."""
        if now is None:
            now = self._now()
        best = None
        for rec in self._clients.values():
            head = self._head(rec)
            if head is None:
                continue
            wait = max(0.0, head[2].l - now)
            if best is None or wait < best:
                best = wait
        return best

    def drain_eligible(self, max_items: int = 1 << 30) -> List[object]:
        out = []
        while len(out) < max_items:
            item = self.dequeue()
            if item is None:
                break
            out.append(item)
        return out

    def __len__(self) -> int:
        return sum(len(r.queue) for r in self._clients.values())
