"""Object classes: server-side ops executing inside the OSD.

Behavioral mirror of the reference's cls plugin system (src/cls/ +
src/objclass/ hooks): a registry of named classes, each exposing named
methods invoked through the client "exec" op against one object; the
method runs ON the primary with transactional access to the object's
data, xattrs, and omap — the seam RBD/RGW/lock/refcount build on.

Python classes register with ``register(name)`` the way the reference's
``CLS_INIT`` entry points do (cls_hello, cls_lock, cls_refcount analogs
are built in below).
"""

from __future__ import annotations

import pickle
from typing import Callable, Dict, Optional


class ClsError(Exception):
    def __init__(self, errno_: int, msg: str = ""):
        super().__init__(msg)
        self.errno = errno_


class MethodContext:
    """What a class method may do to its object (objclass.h ops subset).

    Reads happen against the store; mutations are collected into the
    op's transaction so they commit + replicate atomically with the op.
    """

    def __init__(self, store, coll: str, oid: str, txn):
        self._store = store
        self._coll = coll
        self.oid = oid
        self._txn = txn

    # -- reads --
    def read(self, offset: int = 0, length: Optional[int] = None) -> bytes:
        try:
            return self._store.read(self._coll, self.oid, offset, length)
        except FileNotFoundError:
            return b""

    def stat(self) -> Optional[int]:
        return self._store.stat(self._coll, self.oid)

    def getxattr(self, name: str) -> Optional[bytes]:
        return self._store.getattr(self._coll, self.oid, "_" + name)

    def omap_get(self) -> Dict[str, bytes]:
        return self._store.omap_get(self._coll, self.oid)

    # -- writes (transactional) --
    def write(self, offset: int, data: bytes) -> None:
        self._txn.write(self._coll, self.oid, offset, data)

    def setxattr(self, name: str, value: bytes) -> None:
        self._txn.setattr(self._coll, self.oid, "_" + name, value)

    def rmxattr(self, name: str) -> None:
        self._txn.rmattr(self._coll, self.oid, "_" + name)

    def omap_set(self, kv: Dict[str, bytes]) -> None:
        self._txn.omap_set(self._coll, self.oid, kv)

    def omap_rmkeys(self, keys) -> None:
        self._txn.omap_rmkeys(self._coll, self.oid, list(keys))


Method = Callable[[MethodContext, bytes], bytes]


class ClassRegistry:
    _instance: Optional["ClassRegistry"] = None

    def __init__(self):
        self._classes: Dict[str, Dict[str, Method]] = {}

    @classmethod
    def instance(cls) -> "ClassRegistry":
        if cls._instance is None:
            cls._instance = ClassRegistry()
        return cls._instance

    def register(self, cls_name: str, method: str, fn: Method) -> None:
        self._classes.setdefault(cls_name, {})[method] = fn

    def call(self, cls_name: str, method: str, ctx: MethodContext,
             indata: bytes) -> bytes:
        methods = self._classes.get(cls_name)
        if methods is None:
            raise ClsError(-95, f"no such class {cls_name}")  # EOPNOTSUPP
        fn = methods.get(method)
        if fn is None:
            raise ClsError(-95, f"{cls_name} has no method {method}")
        return fn(ctx, indata)


def register(cls_name: str, method: str):
    def deco(fn: Method) -> Method:
        ClassRegistry.instance().register(cls_name, method, fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# Built-in classes (reference cls_hello / cls_lock / cls_refcount analogs)
# ---------------------------------------------------------------------------


@register("hello", "say_hello")
def _hello(ctx: MethodContext, indata: bytes) -> bytes:
    name = indata.decode() if indata else "world"
    return f"Hello, {name}!".encode()


@register("lock", "lock")
def _lock(ctx: MethodContext, indata: bytes) -> bytes:
    """Exclusive advisory lock (cls_lock subset): indata = pickled
    {name, cookie}; fails with -16 (EBUSY) when held by another cookie."""
    req = pickle.loads(indata)
    key = f"lock.{req['name']}"
    cur = ctx.getxattr(key)
    if cur is not None and cur != req["cookie"].encode():
        raise ClsError(-16, "lock held")
    ctx.setxattr(key, req["cookie"].encode())
    return b""


@register("lock", "unlock")
def _unlock(ctx: MethodContext, indata: bytes) -> bytes:
    req = pickle.loads(indata)
    key = f"lock.{req['name']}"
    cur = ctx.getxattr(key)
    if cur is None:
        raise ClsError(-2, "no such lock")
    if cur != req["cookie"].encode():
        raise ClsError(-16, "lock held by another cookie")
    ctx.rmxattr(key)
    return b""


@register("refcount", "get")
def _ref_get(ctx: MethodContext, indata: bytes) -> bytes:
    refs = pickle.loads(ctx.getxattr("refcount") or pickle.dumps(set()))
    refs.add(indata.decode())
    ctx.setxattr("refcount", pickle.dumps(refs))
    return b""


@register("refcount", "put")
def _ref_put(ctx: MethodContext, indata: bytes) -> bytes:
    refs = pickle.loads(ctx.getxattr("refcount") or pickle.dumps(set()))
    refs.discard(indata.decode())
    ctx.setxattr("refcount", pickle.dumps(refs))
    return pickle.dumps(len(refs))


@register("inotable", "alloc")
def _ino_alloc(ctx: MethodContext, indata: bytes) -> bytes:
    """Atomic inode-number allocation (reference InoTable): the
    read-increment-write runs under the OSD's PG serialization."""
    cur = ctx.omap_get().get("next", b"2")
    ino = int(cur)
    ctx.omap_set({"next": str(ino + 1).encode()})
    return str(ino).encode()


@register("dirfrag", "link")
def _dirfrag_link(ctx: MethodContext, indata: bytes) -> bytes:
    """Create-exclusive dentry insert (reference MDS dirfrag link):
    EEXIST when the name is already present — atomic under PG order."""
    req = pickle.loads(indata)
    if req["name"] in ctx.omap_get():
        raise ClsError(-17, "dentry exists")  # EEXIST
    ctx.omap_set({req["name"]: req["value"]})
    return b""

@register("rbd_journal", "append")
def _rbd_journal_append(ctx: MethodContext, indata: bytes) -> bytes:
    """Atomic journal append (reference cls_journal): allocate the next
    sequence under PG serialization and store the event at it, so two
    racing writers can never claim the same journal slot."""
    import pickle as _p

    omap = ctx.omap_get()
    seq = int(omap.get("_head", b"0")) + 1
    ctx.omap_set({"_head": str(seq).encode(),
                  f"{seq:016d}": indata})
    return str(seq).encode()


@register("rbd_journal", "trim")
def _rbd_journal_trim(ctx: MethodContext, indata: bytes) -> bytes:
    """Drop entries at or below the committed position (reference
    cls_journal client-commit + trim)."""
    upto = int(indata)
    omap = ctx.omap_get()
    dead = [k for k in omap
            if not k.startswith("_") and int(k) <= upto]
    if dead:
        ctx.omap_rmkeys(dead)
    return str(len(dead)).encode()



@register("rgw_mp", "alloc")
def _mp_alloc(ctx: MethodContext, indata: bytes) -> bytes:
    """Atomic multipart upload-id allocation (reference cls_rgw keeps
    multipart meta under the bucket index the same way): the counter
    read-increment-write runs under PG serialization, so two racing
    InitMultipart calls can never mint the same id.  The counter key is
    underscore-prefixed so registry listings can filter it."""
    seq = int(ctx.omap_get().get("_next", b"1"))
    ctx.omap_set({"_next": str(seq + 1).encode()})
    return str(seq).encode()


@register("rgw_bilog", "append")
def _bilog_append(ctx: MethodContext, indata: bytes) -> bytes:
    """Atomic bucket-index-log append (reference cls_rgw bilog ops):
    seq allocation + entry write + window trim run as ONE transaction
    under PG serialization, so concurrent index mutations can never
    collide on a sequence number or lose an entry.  indata: pickled
    {"entry": bytes, "max": int}; returns the allocated seq."""
    import pickle as _p

    req = _p.loads(indata)
    head_b = ctx.getxattr("bilog.head")
    seq = (int(head_b) if head_b else 0) + 1
    ctx.omap_set({f"{seq:012d}": req["entry"]})
    ctx.setxattr("bilog.head", str(seq).encode())
    maxlen = int(req.get("max", 1000))
    if seq > maxlen:
        cutoff = seq - maxlen
        ctx.omap_rmkeys([f"{s:012d}"
                         for s in range(max(1, cutoff - 64), cutoff + 1)])
        ctx.setxattr("bilog.tail", str(cutoff).encode())
    return str(seq).encode()
