"""RGW HTTP frontend: an S3-shaped REST gateway over the RGW core.

Round 4 (VERDICT r3 missing #9): the reference serves S3 through an
embedded HTTP frontend (src/rgw/rgw_civetweb_frontend.cc) with REST op
dispatch (rgw_rest_s3.cc) and signature auth (rgw_auth_s3.cc).  This is
that stack's analog on asyncio TCP: request parsing, signature-v2-style
HMAC auth, bucket/object REST verbs with S3 XML bodies, x-amz-meta-*
user metadata, and MULTIPART uploads (initiate/part/complete/abort,
rgw_op.cc RGWInitMultipart/RGWCompleteMultipart) assembled into the
final RADOS object.

Auth-lite, documented: AWS signature VERSION 2 shape over
(method, path, x-amz-date) with HMAC-SHA256 — per-account secrets, the
presented signature proves key possession; v4's canonical-request/
scope derivation is not implemented.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster.rgw import RGW


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


class S3Request:
    def __init__(self, method, path, query, headers, body):
        self.method = method
        self.path = path
        self.query: Dict[str, str] = query
        self.headers: Dict[str, str] = headers
        self.body = body


class RGWFrontend:
    """The civetweb-frontend analog: accept loop + REST dispatch."""

    def __init__(self, rgw: RGW,
                 accounts: Optional[Dict[str, str]] = None):
        self.rgw = rgw
        # access key -> secret (RGWUserInfo keys analog); None = no auth
        self.accounts = accounts
        self._server = None
        self.addr: Optional[Tuple[str, int]] = None
        self._conns: List = []

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._serve, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # close live keep-alive connections, or wait_closed()
            # (which since py3.12 awaits every handler) blocks on
            # clients parked in their next readline
            for w in self._conns:
                try:
                    w.close()
                except (ConnectionError, OSError, RuntimeError):
                    pass  # best-effort close of a dying keep-alive
            await self._server.wait_closed()

    # -- HTTP plumbing -----------------------------------------------------

    async def _serve(self, reader, writer) -> None:
        self._conns.append(writer)
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except ValueError:
                    # malformed request line/header: answer 400, drop
                    body = self._error_xml("BadRequest", "malformed")
                    writer.write(
                        (f"HTTP/1.1 400 Bad Request\r\nContent-Length: "
                         f"{len(body)}\r\nConnection: close\r\n\r\n"
                         ).encode() + body)
                    await writer.drain()
                    break
                if req is None:
                    break
                status, headers, body = await self._dispatch(req)
                resp = [f"HTTP/1.1 {status}"]
                headers.setdefault("Content-Length", str(len(body)))
                headers.setdefault("Connection", "keep-alive")
                for k, v in headers.items():
                    resp.append(f"{k}: {v}")
                writer.write(("\r\n".join(resp) + "\r\n\r\n").encode()
                             + body)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()
            try:
                self._conns.remove(writer)
            except ValueError:
                pass

    async def _read_request(self, reader) -> Optional[S3Request]:
        line = await reader.readline()
        if not line:
            return None
        method, target, _ = line.decode().split(" ", 2)
        headers: Dict[str, str] = {}
        while True:
            h = (await reader.readline()).decode().strip()
            if not h:
                break
            k, v = h.split(":", 1)
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0"))
        body = await reader.readexactly(n) if n else b""
        parsed = urllib.parse.urlsplit(target)
        query = {k: v[0] for k, v in
                 urllib.parse.parse_qs(parsed.query,
                                       keep_blank_values=True).items()}
        path = urllib.parse.unquote(parsed.path)
        return S3Request(method, path, query, headers, body)

    # -- auth (signature-v2-lite) ------------------------------------------

    # replay window for x-amz-date (the reference allows 15 min skew,
    # rgw_auth_s3.cc RGW_AUTH_GRACE)
    AUTH_GRACE_SECS = 900.0

    @staticmethod
    def _string_to_sign(method: str, path: str, query: Dict[str, str],
                        date: str, body: bytes) -> str:
        """Binds method, path, the FULL query string, the date, and a
        body digest (ADVICE r4: signing only method/path/date let one
        captured PUT signature replay forever with arbitrary content)."""
        # percent-encode keys/values so distinct query dicts can never
        # collide to one canonical string (e.g. {"a": "1&b=2"} vs
        # {"a": "1", "b": "2"})
        canon_q = "&".join(
            f"{urllib.parse.quote(k, safe='')}="
            f"{urllib.parse.quote(v, safe='')}"
            for k, v in sorted(query.items()))
        return "\n".join([method, path, canon_q, date,
                          hashlib.sha256(body).hexdigest()])

    def _authenticate(self, req: S3Request) -> Optional[str]:
        """-> error string, or None when authorized."""
        if self.accounts is None:
            return None
        auth = req.headers.get("authorization", "")
        if not auth.startswith("AWS "):
            return "missing AWS authorization"
        try:
            access, sig = auth[4:].split(":", 1)
        except ValueError:
            return "malformed authorization"
        secret = self.accounts.get(access)
        if secret is None:
            return "unknown access key"
        date = req.headers.get("x-amz-date", "")
        try:
            skew = abs(time.time() - float(date))
        except ValueError:
            return "bad x-amz-date"
        # inverted comparison so a NaN date can never pass the window
        if not (skew <= self.AUTH_GRACE_SECS):
            return "request time too skewed"
        want = hmac.new(
            secret.encode(),
            self._string_to_sign(req.method, req.path, req.query,
                                 date, req.body).encode(),
            hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            return "signature mismatch"
        return None

    @classmethod
    def sign(cls, method: str, path: str, date: str, access: str,
             secret: str, body: bytes = b"",
             query: Optional[Dict[str, str]] = None) -> str:
        """Client-side signer (the boto analog for tests/tools)."""
        sig = hmac.new(
            secret.encode(),
            cls._string_to_sign(method, path, query or {}, date,
                                body).encode(),
            hashlib.sha256).hexdigest()
        return f"AWS {access}:{sig}"

    # -- REST dispatch (rgw_rest_s3.cc op table) ---------------------------

    async def _dispatch(self, req: S3Request):
        if req.path == "/swift/auth" and "x-auth-user" in req.headers:
            # tempauth's GET /auth/v1.0: X-Auth-User/X-Auth-Key in,
            # time-limited X-Auth-Token out.  Conditional on the tempauth
            # header so an S3 object at bucket 'swift', key 'auth' stays
            # reachable through the S3 path
            return self._swift_issue_token(req)
        if req.path == "/swift/v1" or req.path.startswith("/swift/v1/"):
            # exact-prefix guard: an S3 bucket named 'swift' with key
            # 'v1.txt' must stay on the S3 path (and its auth)
            return await self._dispatch_swift(req)
        err = self._authenticate(req)
        if err is not None:
            return "403 Forbidden", {}, self._error_xml(
                "AccessDenied", err)
        parts = req.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        try:
            if not bucket:
                return await self._list_buckets()
            if not key:
                return await self._bucket_op(req, bucket)
            return await self._object_op(req, bucket, key)
        except FileNotFoundError as e:
            return "404 Not Found", {}, self._error_xml("NoSuchKey", str(e))
        except Exception as e:  # noqa: BLE001 — 500 with the error body
            return ("500 Internal Server Error", {},
                    self._error_xml("InternalError", repr(e)))

    # -- Swift API (the reference gateway's second protocol,
    #    rgw_rest_swift.cc: same RGW core, container/object dialect) ----

    def _swift_auth(self, req: S3Request) -> Optional[str]:
        """Swift tempauth-lite: X-Auth-Token =
        '<access>:<expiry>:<hmac(secret, access:expiry)>' — time-limited
        (ADVICE r4: the old static per-account token was valid forever).
        Tokens come from GET /swift/auth (tempauth's /auth/v1.0) or the
        swift_token helper."""
        if self.accounts is None:
            return None
        token = req.headers.get("x-auth-token", "")
        parts = token.split(":")
        if len(parts) != 3:
            return "missing or malformed X-Auth-Token"
        access, expiry, proof = parts
        secret = self.accounts.get(access)
        if secret is None:
            return "unknown account"
        try:
            if float(expiry) < time.time():
                return "token expired"
        except ValueError:
            return "malformed token expiry"
        want = hmac.new(secret.encode(),
                        f"{access}:{expiry}".encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, proof):
            return "bad token"
        return None

    @staticmethod
    def swift_token(access: str, secret: str, ttl: float = 3600.0) -> str:
        expiry = f"{time.time() + ttl:.0f}"
        proof = hmac.new(secret.encode(), f"{access}:{expiry}".encode(),
                         hashlib.sha256).hexdigest()
        return f"{access}:{expiry}:{proof}"

    def _swift_issue_token(self, req: S3Request):
        user = req.headers.get("x-auth-user", "")
        key = req.headers.get("x-auth-key", "")
        secret = (self.accounts or {}).get(user)
        # compare as bytes: str compare_digest raises on non-ASCII input
        if secret is None or not hmac.compare_digest(
                secret.encode(), key.encode()):
            return "401 Unauthorized", {}, b"bad credentials"
        return "200 OK", {
            "X-Auth-Token": self.swift_token(user, secret),
            "X-Storage-Url": "/swift/v1",
        }, b""

    async def _dispatch_swift(self, req: S3Request):
        err = self._swift_auth(req)
        if err is not None:
            return "401 Unauthorized", {}, err.encode()
        # strip the prefix + ONE leading slash: a trailing '/' is part of
        # the object name (Swift pseudo-directory markers)
        rest = req.path[len("/swift/v1"):]
        if rest.startswith("/"):
            rest = rest[1:]
        parts = rest.split("/", 1)
        container = parts[0]
        obj = parts[1] if len(parts) > 1 else ""
        try:
            if not container:
                if req.method != "GET":
                    return "405 Method Not Allowed", {}, b""
                # account GET: newline-separated container listing
                names = await self.rgw.list_buckets()
                return ("200 OK", {"Content-Type": "text/plain"},
                        ("\n".join(names) + "\n").encode()
                        if names else b"")
            if not obj:
                return await self._swift_container_op(req, container)
            return await self._object_core(
                req, container, obj, meta_prefix="x-object-meta-",
                created_status="201 Created", quote_etag=False)
        except FileNotFoundError as e:
            return "404 Not Found", {}, str(e).encode()
        except ValueError as e:
            return "412 Precondition Failed", {}, str(e).encode()
        except OSError as e:
            if e.errno == 39:   # ENOTEMPTY: Swift's delete-conflict
                return "409 Conflict", {}, b"container not empty"
            raise
        except Exception as e:  # noqa: BLE001
            return "500 Internal Server Error", {}, repr(e).encode()

    async def _swift_container_op(self, req: S3Request, container: str):
        if req.method == "PUT":
            try:
                await self.rgw.create_bucket(container)
                return "201 Created", {}, b""
            except FileExistsError:
                return "202 Accepted", {}, b""
        if req.method == "DELETE":
            await self.rgw.delete_bucket(container)
            return "204 No Content", {}, b""
        if req.method in ("GET", "HEAD"):
            try:
                limit = int(req.query.get("limit", "10000"))
            except ValueError:
                raise ValueError("limit must be an integer")
            res = await self.rgw.list_objects(
                container,
                prefix=req.query.get("prefix", ""),
                marker=req.query.get("marker", ""),
                max_keys=limit)
            # the header is the container's TOTAL object count, not the
            # returned page's
            total = len((await self.rgw._index(container)))
            body = ("\n".join(m.key for m in res.keys)
                    + ("\n" if res.keys else "")).encode()
            hdrs = {"Content-Type": "text/plain",
                    "X-Container-Object-Count": str(total)}
            return "200 OK", hdrs, (b"" if req.method == "HEAD" else body)
        return "405 Method Not Allowed", {}, b""

    async def _object_core(self, req: S3Request, bucket: str, key: str,
                           meta_prefix: str, created_status: str,
                           quote_etag: bool):
        """Object verbs shared by BOTH protocol dialects (the reference
        routes S3 and Swift into the same RGWPutObj/RGWGetObj ops);
        dialects differ only in meta-header prefix, ETag quoting, and
        the created status line."""
        def etag_hdr(e):
            return f'"{e}"' if quote_etag else e

        if req.method == "PUT":
            user_meta = {k[len(meta_prefix):]: v
                         for k, v in req.headers.items()
                         if k.startswith(meta_prefix)}
            etag = await self.rgw.put_object(
                bucket, key, req.body,
                content_type=req.headers.get(
                    "content-type", "application/octet-stream"),
                user_meta=user_meta)
            return created_status, {"ETag": etag_hdr(etag)}, b""
        if req.method in ("GET", "HEAD"):
            meta = await self.rgw.head_object(bucket, key)
            hdrs = {
                "ETag": etag_hdr(meta.etag),
                "Content-Type": meta.content_type,
                "Last-Modified": time.strftime(
                    "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(meta.mtime)),
            }
            for k, v in meta.user_meta.items():
                hdrs[meta_prefix.title().rstrip("-") + "-" + k] = v
            if req.method == "HEAD":
                hdrs["Content-Length"] = str(meta.size)
                return "200 OK", hdrs, b""
            _, data = await self.rgw.get_object(bucket, key)
            return "200 OK", hdrs, data
        if req.method == "DELETE":
            await self.rgw.delete_object(bucket, key)
            return "204 No Content", {}, b""
        return "405 Method Not Allowed", {}, b""

    @staticmethod
    def _error_xml(code: str, msg: str) -> bytes:
        return (f"<?xml version='1.0'?><Error><Code>{code}</Code>"
                f"<Message>{_xml_escape(msg)}</Message></Error>").encode()

    async def _list_buckets(self):
        names = await self.rgw.list_buckets()
        inner = "".join(
            f"<Bucket><Name>{_xml_escape(n)}</Name></Bucket>"
            for n in names)
        body = (f"<?xml version='1.0'?><ListAllMyBucketsResult>"
                f"<Buckets>{inner}</Buckets>"
                f"</ListAllMyBucketsResult>").encode()
        return "200 OK", {"Content-Type": "application/xml"}, body

    async def _bucket_op(self, req: S3Request, bucket: str):
        if req.method == "PUT":
            await self.rgw.create_bucket(bucket)
            return "200 OK", {}, b""
        if req.method == "DELETE":
            await self.rgw.delete_bucket(bucket)
            return "204 No Content", {}, b""
        if req.method == "GET":
            res = await self.rgw.list_objects(
                bucket,
                prefix=req.query.get("prefix", ""),
                marker=req.query.get("marker", ""),
                max_keys=int(req.query.get("max-keys", "1000")))
            rows = "".join(
                f"<Contents><Key>{_xml_escape(m.key)}</Key>"
                f"<Size>{m.size}</Size><ETag>&quot;{m.etag}&quot;</ETag>"
                f"</Contents>" for m in res.keys)
            trunc = "true" if res.is_truncated else "false"
            nm = (f"<NextMarker>{_xml_escape(res.next_marker)}</NextMarker>"
                  if res.next_marker else "")
            body = (f"<?xml version='1.0'?><ListBucketResult>"
                    f"<Name>{_xml_escape(bucket)}</Name>"
                    f"<IsTruncated>{trunc}</IsTruncated>{nm}{rows}"
                    f"</ListBucketResult>").encode()
            return "200 OK", {"Content-Type": "application/xml"}, body
        return "405 Method Not Allowed", {}, b""

    async def _object_op(self, req: S3Request, bucket: str, key: str):
        # -- multipart sub-protocol (rgw_op.cc multipart ops), served
        #    by the DURABLE core (round 15): the upload registry lives
        #    in RADOS, so a frontend restart mid-upload loses nothing
        #    and reclaim_multipart can always finish an interrupted
        #    complete/abort --
        if "uploads" in req.query and req.method == "POST":
            upload_id = await self.rgw.create_multipart(bucket, key)
            body = (f"<?xml version='1.0'?><InitiateMultipartUploadResult>"
                    f"<Bucket>{_xml_escape(bucket)}</Bucket>"
                    f"<Key>{_xml_escape(key)}</Key>"
                    f"<UploadId>{upload_id}</UploadId>"
                    f"</InitiateMultipartUploadResult>").encode()
            return "200 OK", {"Content-Type": "application/xml"}, body
        if "uploadId" in req.query:
            return await self._multipart_op(req, bucket, key,
                                            req.query["uploadId"])

        return await self._object_core(
            req, bucket, key, meta_prefix="x-amz-meta-",
            created_status="200 OK", quote_etag=True)

    # -- multipart ---------------------------------------------------------

    async def _multipart_op(self, req: S3Request, bucket: str, key: str,
                            upload_id: str):
        try:
            if req.method == "PUT":
                n = int(req.query["partNumber"])
                etag = await self.rgw.upload_part(bucket, key,
                                                  upload_id, n, req.body)
                return "200 OK", {"ETag": f'"{etag}"'}, b""
            if req.method == "POST":
                etag = await self.rgw.complete_multipart(bucket, key,
                                                         upload_id)
                body = (f"<?xml version='1.0'?>"
                        f"<CompleteMultipartUploadResult>"
                        f"<Key>{_xml_escape(key)}</Key>"
                        f"<ETag>&quot;{etag}&quot;</ETag>"
                        f"</CompleteMultipartUploadResult>").encode()
                return ("200 OK", {"Content-Type": "application/xml"},
                        body)
            if req.method == "DELETE":   # abort
                await self.rgw.abort_multipart(bucket, key, upload_id)
                return "204 No Content", {}, b""
        except FileNotFoundError:
            return "404 Not Found", {}, self._error_xml(
                "NoSuchUpload", upload_id)
        return "405 Method Not Allowed", {}, b""
