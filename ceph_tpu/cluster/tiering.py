"""Cache tiering: hit sets, promote/proxy/forward, and the tier agent.

Behavioral analog of the reference's cache-tier axis of PrimaryLogPG
(src/osd/PrimaryLogPG.h:904 hit_set_persist, :919-923 agent_work,
maybe_handle_cache / do_proxy_read / promote_object) and the TierAgent
(src/osd/TierAgentState.h), re-seamed for this framework:

- The objecter's overlay redirect (objecter._overlay_pool) sends base-pool
  traffic to the CACHE pool; these mixin hooks run on the cache pool's
  primaries.
- On a cache MISS the op either PROMOTES the object (writeback — the
  promote is literally the local `copy_from` verb pulling from the base
  pool), PROXIES the read (readproxy), or forwards the whole vector to
  the base (forward mode, used to drain a cache).
- Every access records into a per-PG bloom HitSet, rotated every
  ``hit_set_period`` seconds and archived ``hit_set_count`` deep on the
  PG (reference hit_set_persist/trim); the agent uses recency for evict
  ordering.
- Writes on a tier mark the object DIRTY via a replicated attr; the tier
  agent flushes dirty objects to the base (the BASE primary pulls them
  with copy_from, reusing the cross-pool copy seam) and evicts clean
  objects past ``target_max_objects``.
"""

from __future__ import annotations

import asyncio
import pickle
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.pg import PGMETA, PGRB, _coll
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.ops import jenkins

DIRTY_ATTR = "tier_dirty"
# NUL-prefixed like the snapdir marker: client object names can never
# collide with it, and every internal listing/scrub/split path filters it
HITSET_PREFIX = "\x00hitset_"


class BloomHitSet:
    """Bloom-filter hit set (reference BloomHitSet, CompressibleBloom):
    fixed 2^14-bit array, 4 jenkins-derived probes."""

    BITS = 1 << 14
    K = 4

    def __init__(self, bits: Optional[bytearray] = None):
        self.bits = bits if bits is not None else bytearray(self.BITS // 8)

    def _probes(self, oid: str):
        h = jenkins.str_hash_rjenkins(oid.encode())
        for i in range(self.K):
            p = int(jenkins.hash2(h & 0xFFFFFFFF, i)) % self.BITS
            yield p

    def insert(self, oid: str) -> None:
        for p in self._probes(oid):
            self.bits[p >> 3] |= 1 << (p & 7)

    def contains(self, oid: str) -> bool:
        return all(self.bits[p >> 3] & (1 << (p & 7))
                   for p in self._probes(oid))

    def encode(self) -> bytes:
        return bytes(self.bits)

    @classmethod
    def decode(cls, blob: bytes) -> "BloomHitSet":
        return cls(bytearray(blob))


class _PGHitSets:
    def __init__(self):
        self.current = BloomHitSet()
        self.started = time.monotonic()
        self.archive: deque = deque()


class TieringMixin:
    """Cache-pool behavior for OSDDaemon (composed like the other PG
    mixins)."""

    # ---------------------------------------------------------- hit sets

    def _hitsets_for(self, st) -> _PGHitSets:
        hs = getattr(self, "_tier_hitsets", None)
        if hs is None:
            hs = self._tier_hitsets = {}
        cur = hs.get(st.pgid)
        if cur is None:
            cur = hs[st.pgid] = _PGHitSets()
        return cur

    def _hit_set_record(self, pool, st, oid: str) -> None:
        hs = self._hitsets_for(st)
        now = time.monotonic()
        if now - hs.started > pool.hit_set_period:
            self._hit_set_rotate(pool, st, hs)
        hs.current.insert(oid)

    def _hit_set_rotate(self, pool, st, hs: _PGHitSets) -> None:
        """Archive the current set on the PG and start a fresh one
        (reference hit_set_persist + hit_set_trim)."""
        coll = _coll(st.pgid)
        stamp = int(time.time() * 1000)
        name = f"{HITSET_PREFIX}{stamp}"
        txn = Transaction().write(coll, name, 0, hs.current.encode())
        hs.archive.appendleft((name, hs.current))
        while len(hs.archive) > max(1, pool.hit_set_count):
            old_name, _ = hs.archive.pop()
            txn.remove(coll, old_name)
        self.store.queue_transaction(txn)
        hs.current = BloomHitSet()
        hs.started = time.monotonic()
        self.perf.inc("osd_tier_hitset_rotations")

    def _hit_recency(self, st, oid: str) -> int:
        """How many recent hit sets (current first) contain ``oid``;
        0 = cold (reference agent_estimate_temp)."""
        hs = self._hitsets_for(st)
        n = 1 if hs.current.contains(oid) else 0
        for _, b in hs.archive:
            if b.contains(oid):
                n += 1
        return n

    # ------------------------------------------------------- interception

    _TIER_READ_ONLY = frozenset({
        "read", "stat", "getxattr", "getxattrs", "omap_get", "list",
        "watch", "unwatch", "notify", "notify_ack", "cmpxattr"})

    def _tier_mode(self, pool) -> Optional[str]:
        if not pool.is_tier() or pool.cache_mode in ("none", ""):
            return None
        return pool.cache_mode

    async def _tier_intercept(self, conn, msg, m, pool, st) -> bool:
        """Cache-pool admission (reference maybe_handle_cache): returns
        True when the op was fully handled (reply sent)."""
        mode = self._tier_mode(pool)
        if mode is None:
            return False
        base_id = pool.tier_of
        if base_id not in m.pools:
            return False
        opnames = [o[0] for o in msg.ops]
        if "list" in opnames:
            return False  # listings stay local (cache contents)
        self._hit_set_record(pool, st, msg.oid)

        head_here = self.store.stat(_coll(st.pgid), msg.oid) is not None
        if "delete" in opnames:
            # delete-through (all modes): remove from BOTH tiers so a
            # later miss cannot resurrect the object from the base.
            # Guard ops (cmpxattr) in the vector still gate the delete.
            for gname, gargs in msg.ops:
                if gname in self._GUARD_OPS:
                    gr, _ = await self._do_one_op(conn, msg, m, pool, st,
                                                  gname, gargs)
                    if gr < 0:
                        await conn.send(M.MOSDOpReply(
                            reqid=msg.reqid, result=gr, epoch=m.epoch))
                        return True
            # stable derived reqid: a RESENT delete must hit the base's
            # dup detection, not re-execute
            r_base = await self.internal_op(
                base_id, msg.oid, [("delete", {})], snapc=msg.snapc,
                reqid_override=(f"{msg.reqid[0]}#tdel", msg.reqid[1]))
            r_local = 0
            if head_here:
                async with st.lock:
                    r_local = await self._op_delete(pool, st, msg.oid,
                                                    snapc=msg.snapc)
            ok = (r_base.result == 0) or (head_here and r_local == 0)
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid,
                result=0 if ok else -2, epoch=m.epoch))
            self.perf.inc("osd_tier_delete_through")
            return True
        if mode == "forward":
            # forward mode: the cache takes nothing NEW — misses forward
            # wholesale to the base.  Objects still in the cache keep
            # serving locally (they are newer than the base until the
            # draining agent flushes them out).  The derived reqid stays
            # stable across client resends for the base's dup detection.
            if head_here:
                return False
            reply = await self.internal_op(
                base_id, msg.oid, msg.ops,
                snapid=msg.snapid, snapc=msg.snapc,
                reqid_override=(f"{msg.reqid[0]}#fwd", msg.reqid[1]))
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=reply.result, data=reply.data,
                epoch=m.epoch))
            self.perf.inc("osd_tier_forward")
            return True
        if head_here:
            return False  # cache hit: run locally
        pure_read = all(o in self._TIER_READ_ONLY for o in opnames)
        full_overwrite = all(o in ("write_full", "create") for o in opnames)
        if full_overwrite:
            return False  # no promote needed; the write replaces anyway
        if mode == "readproxy" and pure_read:
            # proxy the reads through to the base, no promotion
            reply = await self.internal_op(
                base_id, msg.oid, msg.ops,
                snapid=msg.snapid, snapc=msg.snapc)
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=reply.result, data=reply.data,
                epoch=m.epoch))
            self.perf.inc("osd_tier_proxy_read")
            return True
        # writeback (or readproxy+write): PROMOTE — the local copy_from
        # verb pulls the object from the base, then the op runs locally
        r, _ = await self._do_one_op(
            conn, msg, m, pool, st, "copy_from",
            {"src_pool": base_id, "src_oid": msg.oid})
        if r == -2:
            if pure_read:
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-2, epoch=m.epoch))
                return True
            return False  # new object: writes proceed locally
        if r < 0:
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=r, epoch=m.epoch))
            return True
        # promoted copies are CLEAN until a local write dirties them
        await self._tier_set_dirty(st, msg.oid, False)
        self.perf.inc("osd_tier_promotions")
        return False

    # ------------------------------------------------------ dirty tracking

    async def _tier_set_dirty(self, st, oid: str, dirty: bool,
                              expect_version: Optional[int] = None) -> bool:
        """Replicated dirty flag (object_info_t FLAG_DIRTY analog): rides
        a logged transaction so a failed-over cache primary still knows
        what needs flushing.  With ``expect_version`` the flag only
        changes if the object is still at that version (the flush/write
        race interlock) — returns False when the object moved."""
        coll = _coll(st.pgid)
        async with st.lock:
            if expect_version is not None and \
                    self.store.get_version(coll, oid) != expect_version:
                return False
            txn = Transaction()
            if dirty:
                txn.setattr(coll, oid, DIRTY_ATTR, b"1")
            else:
                txn.rmattr(coll, oid, DIRTY_ATTR)
            version = self._next_version(st)
            txn.set_version(coll, oid, version[1])
            await self._replicate_txn(st, txn, "modify", oid, version)
        return True

    def _tier_is_dirty(self, st, oid: str) -> bool:
        return self.store.getattr(_coll(st.pgid), oid, DIRTY_ATTR) \
            is not None

    async def _tier_mark_dirty_after_write(self, pool, st, msg) -> None:
        """Called after a successful mutating vector on a cache pool."""
        if self._tier_mode(pool) is None:
            return
        if self.store.stat(_coll(st.pgid), msg.oid) is None:
            return  # vector deleted the object
        await self._tier_set_dirty(st, msg.oid, True)

    # ------------------------------------------------------------- agent

    async def _tier_agent_loop(self) -> None:
        """Background flush/evict (reference agent_work / TierAgentState):
        per cache-pool PG this OSD primaries — flush dirty objects to the
        base (the base primary PULLS them via copy_from, reusing the
        cross-pool seam), then evict cold clean objects past
        target_max_objects.  Forward-mode caches drain completely."""
        while not self._stopped:
            await asyncio.sleep(self.config.osd_tier_agent_interval)
            m = self.osdmap
            if m is None:
                continue
            for pgid, st in list(self.pgs.items()):
                pool = m.pools.get(pgid.pool)
                if pool is None or self._tier_mode(pool) is None:
                    continue
                if st.primary != self.osd_id:
                    continue
                try:
                    await self._tier_agent_pg(m, pool, st)
                except Exception:
                    self.perf.inc("osd_tier_agent_errors")

    def _tier_objects(self, st) -> List[str]:
        from ceph_tpu.cluster import snaps as snapmod

        return [o for o in self._list_pg_objects(st.pgid)
                if not snapmod.is_snap_key(o)]

    async def _tier_agent_pg(self, m, pool, st) -> None:
        base_id = pool.tier_of
        if base_id not in m.pools:
            return
        drain = pool.cache_mode == "forward"
        objs = self._tier_objects(st)
        dirty = [o for o in objs if self._tier_is_dirty(st, o)]
        # flush: base pulls the object; then the copy is clean — but only
        # if no write landed DURING the flush (version interlock, the
        # reference's flush/dirty race guard), else it stays dirty for
        # the next pass
        coll = _coll(st.pgid)
        for oid in dirty:
            v0 = self.store.get_version(coll, oid)
            reply = await self.internal_op(
                base_id, oid,
                [("copy_from", {"src_pool": st.pgid.pool,
                                "src_oid": oid})])
            if reply.result == 0:
                if await self._tier_set_dirty(st, oid, False,
                                              expect_version=v0):
                    self.perf.inc("osd_tier_flushes")
        if not drain and not pool.target_max_objects:
            return
        objs = self._tier_objects(st)
        clean = [o for o in objs if not self._tier_is_dirty(st, o)]
        # per-PG share of the pool target (reference divides by pg_num)
        per_pg_target = 0 if drain else max(
            1, pool.target_max_objects // max(1, pool.pg_num))
        excess = len(objs) - per_pg_target
        if excess <= 0:
            return
        # evict coldest first (lowest hit-set recency)
        clean.sort(key=lambda o: self._hit_recency(st, o))
        for oid in clean[:excess]:
            async with st.lock:
                r = await self._op_delete(pool, st, oid)
            if r == 0:
                self.perf.inc("osd_tier_evictions")
