"""Object snapshots: SnapContext, SnapSet, clone naming + read resolution.

Behavioral analog of the reference snapshot axis that every storage
surface builds on: struct SnapContext (src/common/snap_types.h:41 — seq
+ existent snaps, descending), struct SnapSet (src/osd/osd_types.h:4431
— per-head clone directory: clones ascending, clone_snaps descending,
clone_size), clone-on-write in PrimaryLogPG::make_writeable
(src/osd/PrimaryLogPG.cc:7019), and snap-read resolution in
PrimaryLogPG::find_object_context.

Storage model: clones are ordinary store objects named by
``clone_oid(head, cloneid)``; a store-level ``clone`` transaction op
copies data+xattrs shard-locally (EC pools clone each shard in place —
no data moves over the wire, the ECBackend rollback/clone philosophy).
The SnapSet is pickled into the head's "ss" xattr while the head exists
and onto the snapdir object after head deletion (the reference's snapdir
ghobject)."""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# clone/snapdir object naming: NUL can't appear in client oids (the
# tools/librados layer rejects it), so these keys never collide and are
# filtered from client listings by _list_pg_objects
_SEP = "\x00snap\x00"
_SNAPDIR = "\x00snapdir"

SNAP_HEAD: Optional[int] = None  # read the live object


def clone_oid(oid: str, cloneid: int) -> str:
    return f"{oid}{_SEP}{cloneid:016d}"


def snapdir_oid(oid: str) -> str:
    return f"{oid}{_SNAPDIR}"


def is_snap_key(name: str) -> bool:
    """True for clone/snapdir store keys (hidden from client listings)."""
    return _SEP in name or name.endswith(_SNAPDIR)


def head_of(name: str) -> str:
    if _SEP in name:
        return name.split(_SEP, 1)[0]
    if name.endswith(_SNAPDIR):
        return name[: -len(_SNAPDIR)]
    return name


@dataclass(frozen=True)
class SnapContext:
    """snap_types.h:41 — seq is the newest snap id the writer knows;
    snaps lists existent snaps, descending."""

    seq: int = 0
    snaps: Tuple[int, ...] = ()

    def is_valid(self) -> bool:
        if self.snaps and self.seq < self.snaps[0]:
            return False
        return all(self.snaps[i] > self.snaps[i + 1]
                   for i in range(len(self.snaps) - 1))


@dataclass
class SnapSet:
    """osd_types.h:4431 — the per-object clone directory."""

    seq: int = 0
    clones: List[int] = field(default_factory=list)         # ascending
    clone_snaps: Dict[int, List[int]] = field(default_factory=dict)
    clone_size: Dict[int, int] = field(default_factory=dict)
    # snapc.seq at head (re)creation: snaps taken at-or-before it existed
    # before the head did, so they must never resolve to it (the
    # reference encodes this through object_info/whiteout bookkeeping)
    head_since: int = 0
    # mutation counter: stamped onto the snapdir store object so (a)
    # version-gated backfill notices snapset changes (setattr alone never
    # bumps a store version) and (b) a stale snap_sync push can never
    # overwrite a newer snapset (see _handle_push)
    version: int = 0

    def encode(self) -> bytes:
        return pickle.dumps(self)

    @staticmethod
    def decode(blob: Optional[bytes]) -> "SnapSet":
        return pickle.loads(blob) if blob else SnapSet()

    # -- clone-on-write decision (make_writeable, PrimaryLogPG.cc:7019) --

    def needs_clone(self, snapc: Optional[SnapContext],
                    head_exists: bool) -> bool:
        """A mutation under ``snapc`` must preserve the pre-write head
        when snaps newer than our seq exist and there is a head to
        preserve."""
        if snapc is None or not head_exists:
            return False
        return snapc.seq > self.seq and \
            any(s > self.seq for s in snapc.snaps)

    def add_clone(self, snapc: SnapContext, head_size: int) -> int:
        """Record the clone for the snaps in (self.seq, snapc.seq];
        returns the clone id (== snapc.seq, as the reference names
        clones by the snapc seq at write time)."""
        newest = [s for s in snapc.snaps if s > self.seq]  # descending
        cloneid = snapc.seq
        self.clones.append(cloneid)
        self.clone_snaps[cloneid] = newest
        self.clone_size[cloneid] = head_size
        self.seq = snapc.seq
        self.version += 1
        return cloneid

    def advance_seq(self, snapc: Optional[SnapContext]) -> None:
        if snapc is not None and snapc.seq > self.seq:
            self.seq = snapc.seq
            self.version += 1

    # -- snap-read resolution (find_object_context) ----------------------

    def resolve_read(self, snapid: Optional[int],
                     head_exists: bool) -> Tuple[str, Optional[int]]:
        """-> ("head", None) | ("clone", cloneid) | ("enoent", None).

        First clone with cloneid >= snapid serves the read iff the snap
        falls inside its coverage (>= the oldest snap the clone was made
        for); no such clone -> the head (which represents all states
        since the newest clone) if it exists."""
        if snapid is None:
            return ("head", None) if head_exists else ("enoent", None)
        for c in self.clones:
            if c >= snapid:
                covered = self.clone_snaps.get(c, [])
                if covered and snapid >= covered[-1]:
                    return ("clone", c)
                return ("enoent", None)
        if head_exists and snapid > self.head_since:
            return ("head", None)
        return ("enoent", None)

    # -- trimming (snap removal) -----------------------------------------

    def trim(self, removed: set) -> Tuple[List[int], bool]:
        """Drop removed snaps from clone coverage; returns (clone ids
        whose coverage became empty — their objects must be deleted,
        dirty)."""
        dead: List[int] = []
        dirty = False
        for c in list(self.clones):
            snaps = self.clone_snaps.get(c, [])
            kept = [s for s in snaps if s not in removed]
            if kept != snaps:
                dirty = True
                if kept:
                    self.clone_snaps[c] = kept
                else:
                    dead.append(c)
                    self.clones.remove(c)
                    self.clone_snaps.pop(c, None)
                    self.clone_size.pop(c, None)
        if dirty:
            self.version += 1
        return dead, dirty

    @property
    def empty(self) -> bool:
        return not self.clones and self.seq == 0


# -- store-facing helpers (shared by both PG backends) ---------------------
#
# The SnapSet lives in the "ss" xattr of the snapdir object — ONE
# location whether or not the head exists (the reference migrates it
# between head and snapdir; a fixed home is simpler and equivalent).
# All ops are plain store-transaction tuples so they ride the replicated
# txn fan-out / EC sub-write pre_ops unchanged.

def load_snapset(store, coll: str, oid: str) -> SnapSet:
    return SnapSet.decode(store.getattr(coll, snapdir_oid(oid), "ss"))


def make_writeable_ops(store, coll: str, oid: str,
                       snapc_raw, head_size: int):
    """Clone-on-write decision for a mutation of ``oid`` under snapc
    (PrimaryLogPG::make_writeable analog).  Returns (pre_ops, cloned):
    store-level ops to apply atomically BEFORE the mutation.  snapc_raw
    is the wire form (seq, (snaps...)) or None."""
    if snapc_raw is None:
        return [], False
    snapc = SnapContext(seq=snapc_raw[0], snaps=tuple(snapc_raw[1]))
    if not snapc.is_valid():
        return [], False
    ss = load_snapset(store, coll, oid)
    head_exists = store.stat(coll, oid) is not None
    ops = []
    cloned = False
    if ss.needs_clone(snapc, head_exists):
        cid = ss.add_clone(snapc, head_size)
        ops.append(("clone", coll, oid, clone_oid(oid, cid)))
        cloned = True
    else:
        if snapc.seq <= ss.seq and (head_exists or
                                    snapc.seq <= ss.head_since):
            return [], False  # nothing new to record
        if not head_exists and snapc.seq > ss.head_since:
            # head (re)creation: snaps <= snapc.seq predate it
            ss.head_since = snapc.seq
            ss.version += 1
        ss.advance_seq(snapc)
    ops.extend(snapset_ops(coll, oid, ss))
    return ops, cloned


def snapset_ops(coll: str, head: str, ss: SnapSet):
    """Persist a SnapSet: the xattr plus a version stamp on the snapdir
    store object (setattr alone never bumps a store version, which would
    make version-gated backfill skip snapset changes forever)."""
    sd = snapdir_oid(head)
    return [("setattr", coll, sd, "ss", ss.encode()),
            ("set_version", coll, sd, ss.version)]


def prune_clone_ops(store, coll: str, head: str, ss: SnapSet):
    """Remove-ops for clone objects the SnapSet no longer lists."""
    live = {clone_oid(head, c) for c in ss.clones}
    prefix = head + _SEP
    return [("remove", coll, name) for name in store.list_objects(coll)
            if name.startswith(prefix) and name not in live]


def trim_ops(store, coll: str, snapdir_key: str, removed: set):
    """Snap-trim one object's snapset (reference PrimaryLogPG::SnapTrimmer):
    returns store ops deleting fully-trimmed clones + persisting the
    shrunk snapset, or [] when this object is untouched."""
    head = head_of(snapdir_key)
    ss = SnapSet.decode(store.getattr(coll, snapdir_key, "ss"))
    dead, dirty = ss.trim(removed)
    if not dirty:
        return []
    ops = [("remove", coll, clone_oid(head, c)) for c in dead]
    ops.extend(snapset_ops(coll, head, ss))
    return ops
