"""Per-tick stripe-batch coalescing: the OSD's group-commit encode seam.

Round 11 (ROADMAP items 1-2): concurrent EC writes must stop crossing
the host/device boundary alone.  Every `_ec_write` submits its stripe
range here instead of dispatching its own encode; requests that arrive
while a tick is in flight accumulate, and the next tick encodes ALL of
them as one `PlanarBatch` round trip (`ec/stripe.encode_stripes_multi`:
one to_planar conversion, one fused Pallas dispatch, one crc32c batch),
scattering shard rows back to each op's sub-write fan-out.

The tick is SELF-CLOCKING (group commit): a request hitting an idle
profile encodes immediately — a lone op (t1 latency) never waits — and
under load the encode-in-flight window is exactly what accumulates the
next tick's batch.  That also gives the double-buffering the design
calls for: while tick T encodes in the executor, tick T-1's ops are
already fanning out sub-writes and tick T+1 is accumulating.
`osd_batch_tick_ops` bounds a tick's batch; `osd_batch_tick_window`
optionally stretches accumulation after an idle-start request.

This module is the ONE sanctioned device-dispatch seam for per-op EC
encodes under cluster/ — the `per-op-device-dispatch` graftlint rule
polices the rest of the tree.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Tuple


class _Req:
    __slots__ = ("data", "want_crc", "fut")

    def __init__(self, data, want_crc: bool, fut: asyncio.Future):
        self.data = data
        self.want_crc = want_crc
        self.fut = fut


class SubWriteBatcher:
    """Per-peer group commit for EC shard sub-writes: the tick's
    sub-writes destined for one peer ride ONE MOSDECSubOpWriteBatch
    frame (one pickle, one session frame, one transport ack, one
    batched reply) instead of one frame per op.  Same self-clocking
    shape as EncodeBatcher: a lone sub-write sends immediately as a
    plain MOSDECSubOpWrite — the wire format of the unbatched path."""

    def __init__(self, osd):
        self._osd = osd
        self._pending: Dict[int, List] = {}      # target osd -> [(sub, fut)]
        self._workers: Dict[int, asyncio.Task] = {}

    async def send(self, target: int, sub) -> None:
        """Queue one sub-write for ``target``; returns when the frame
        carrying it was handed to the session (raises like _send_osd on
        a failed send, so _ec_write's every-shard-durable rule holds)."""
        fut = asyncio.get_event_loop().create_future()
        self._pending.setdefault(target, []).append((sub, fut))
        if target not in self._workers:
            task = asyncio.get_event_loop().create_task(
                self._drain(target))
            self._workers[target] = task
            self._osd._track(task)
        # resolved by the local worker's finally even on cancellation
        # (exception), never a cross-daemon wait
        await fut  # graftlint: ignore[rpc-timeout]

    async def _drain(self, target: int) -> None:
        from ceph_tpu.cluster import messages as M

        osd = self._osd
        batch: List = []
        try:
            while not osd._stopped:
                pending = self._pending.get(target)
                if not pending:
                    break
                cap = max(1, osd.config.osd_batch_tick_ops)
                batch = pending[:cap]
                self._pending[target] = pending[cap:]
                try:
                    if len(batch) == 1:
                        await osd._send_osd(target, batch[0][0])
                    else:
                        await osd._send_osd(
                            target, M.MOSDECSubOpWriteBatch(
                                items=[s for s, _f in batch],
                                epoch=osd.osdmap.epoch))
                        osd.perf.inc("osd_subwrite_batches")
                        osd.perf.inc("osd_subwrite_batched_items",
                                     len(batch))
                    # crash seam: THIS peer's tick frame left, other
                    # peers' frames (and these acks) never happen — the
                    # partial fan-out peering must rule on
                    osd._chaos_point("commit_mid_fanout")
                    for _s, f in batch:
                        if not f.done():
                            f.set_result(None)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    for _s, f in batch:
                        if not f.done():
                            f.set_exception(e)
                batch = []
        finally:
            self._workers.pop(target, None)
            leftovers = batch + (self._pending.pop(target, None) or [])
            for _s, f in leftovers:
                if not f.done():
                    f.set_exception(
                        ConnectionError("sub-write batcher stopped"))


class OpBatcher:
    """Round 18: the CLIENT-edge twin of SubWriteBatcher, living in the
    objecter.  Ops targeting one OSD park here and ship as ONE
    MOSDOpBatch frame per tick (one pickle, one session frame, one
    transport ack) instead of one MOSDOp frame per op — the per-op
    frame churn PR 6's attribution measured dominating the t16 wall.
    Same self-clocking group-commit shape: a lone op sends immediately
    as a plain MOSDOp (the wire format of the unbatched path, so the
    ``objecter_batch_tick_ops=0`` anchor and a 1-op tick are
    bit-identical on the wire), and the send-in-flight window is
    exactly what accumulates the next tick's batch.

    Per-op semantics survive batching end to end: each item keeps its
    own reqid/future in ``objecter._inflight`` (a shed item un-acks
    only itself — the SubWriteBatcher per-item rule), and each item's
    trace header gets the amortized ``objecter:batch_tick`` /
    ``objecter:batch_sent`` stamps the ``client_batch_wait`` /
    ``client_batch_send`` attribution stages are computed from."""

    def __init__(self, objecter):
        self._obj = objecter
        self._pending: Dict[Tuple, List] = {}   # osd addr -> [(msg, fut)]
        self._workers: Dict[Tuple, asyncio.Task] = {}

    async def send(self, addr: Tuple, msg) -> None:
        """Park one MOSDOp for ``addr``; returns when the frame carrying
        it was handed to the session (raises like send_message on a
        failed send, so the submit loop's retarget/retry rule holds)."""
        fut = asyncio.get_event_loop().create_future()
        self._pending.setdefault(addr, []).append((msg, fut))
        if addr not in self._workers:
            task = asyncio.get_event_loop().create_task(self._drain(addr))
            self._workers[addr] = task
            self._obj._track(task)
        # resolved by the local worker's finally even on cancellation
        # (exception), never a cross-daemon wait
        await fut  # graftlint: ignore[rpc-timeout]

    async def _drain(self, addr: Tuple) -> None:
        import time as _time

        from ceph_tpu.cluster import messages as M

        obj = self._obj
        batch: List = []
        try:
            while not obj._stopped:
                pending = self._pending.get(addr)
                if not pending:
                    break
                t0 = _time.time()
                window = obj.config.objecter_batch_tick_window
                if window and len(pending) == 1:
                    # optional accumulation stretch after an idle start
                    await asyncio.sleep(window)
                    pending = self._pending.get(addr) or []
                cap = max(1, obj.config.objecter_batch_tick_ops)
                batch = pending[:cap]
                self._pending[addr] = pending[cap:]
                try:
                    if len(batch) == 1:
                        # lone op: the plain legacy frame, byte-exact
                        # with the objecter_batch_tick_ops=0 anchor
                        await obj.messenger.send_message(batch[0][0],
                                                         addr)
                    else:
                        # amortized tick attribution (the batch_wait/
                        # batch_encode convention): each op books its
                        # share of the tick window as client_batch_send
                        # and the rest of its park time as
                        # client_batch_wait.  Stamped BEFORE the send —
                        # the header pickles with the frame.
                        t1 = _time.time()
                        share = (t1 - t0) / len(batch)
                        for m, _f in batch:
                            tr = getattr(m, "trace", None)
                            if tr is not None:
                                tr["events"].append(
                                    ("objecter:batch_tick", t1 - share))
                                tr["events"].append(
                                    ("objecter:batch_sent", t1))
                        obj._batch_ticks += 1
                        obj._batch_tick_ops += len(batch)
                        if obj.flight:
                            obj.flight.record("client_batch_tick",
                                              osd=f"{addr[0]}:{addr[1]}",
                                              items=len(batch))
                        await obj.messenger.send_message(
                            M.MOSDOpBatch(
                                items=[m for m, _f in batch],
                                epoch=max(m.epoch for m, _f in batch)),
                            addr)
                    for _m, f in batch:
                        if not f.done():
                            f.set_result(None)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    for _m, f in batch:
                        if not f.done():
                            f.set_exception(e)
                batch = []
        finally:
            self._workers.pop(addr, None)
            leftovers = batch + (self._pending.pop(addr, None) or [])
            for _m, f in leftovers:
                if not f.done():
                    f.set_exception(
                        ConnectionError("op batcher stopped"))


class ClientReplyBatcher:
    """Round 18: the OSD's reply-edge coalescer — terminal MOSDOpReply
    frames destined for one client connection park here and ship as ONE
    MOSDOpReplyBatch per reply tick.  Same self-clocking shape: a lone
    reply sends immediately as a plain MOSDOpReply (the legacy wire
    format), so replies are never delayed waiting for tick-mates — the
    zero-acked-past-deadline gate depends on that.  Shed ops never
    enter (no reply exists), so absence-means-unacked holds per item."""

    def __init__(self, osd):
        self._osd = osd
        self._pending: Dict[int, List] = {}     # id(conn) -> [(conn, reply)]
        self._workers: Dict[int, asyncio.Task] = {}

    def send(self, conn, reply) -> None:
        """Park one terminal reply for ``conn`` (fire-and-forget, like
        conn.send: a dead client conn drops replies and the client's
        resend machinery covers it)."""
        key = id(conn)
        self._pending.setdefault(key, []).append((conn, reply))
        if key not in self._workers:
            task = asyncio.get_event_loop().create_task(self._drain(key))
            self._workers[key] = task
            self._osd._track(task)

    async def _drain(self, key: int) -> None:
        from ceph_tpu.cluster import messages as M

        osd = self._osd
        try:
            while not osd._stopped:
                pending = self._pending.get(key)
                if not pending:
                    break
                cap = max(1, osd.config.objecter_batch_tick_ops)
                batch = pending[:cap]
                self._pending[key] = pending[cap:]
                conn = batch[0][0]
                try:
                    if len(batch) == 1:
                        await conn.send(batch[0][1])
                    else:
                        await conn.send(M.MOSDOpReplyBatch(
                            items=[r for _c, r in batch]))
                        osd.perf.inc("osd_client_batch_reply_frames")
                        osd.perf.inc("osd_client_batch_reply_items",
                                     len(batch))
                except asyncio.CancelledError:
                    raise
                except (ConnectionError, OSError, RuntimeError):
                    # client conn died mid-tick: the un-acked items are
                    # covered by the client's resend machinery — count
                    # the drop and keep draining later ticks
                    osd.perf.inc("osd_client_batch_reply_drops",
                                 len(batch))
        finally:
            self._workers.pop(key, None)
            self._pending.pop(key, None)


class ReadBatcher:
    """Per-tick coalescer for the READ half of the data plane (round
    16): a tick's read gathers share one layout conversion + one fused
    decode (``ec/stripe.decode_stripes_multi``), recovery rebuilds
    share one decode+reencode round trip (``reencode_stripes_multi``),
    and shard crc verification rides one crc32c batch per tick.  Same
    self-clocking group-commit shape as EncodeBatcher: a lone request
    never waits, and the compute-in-flight window is exactly what
    accumulates the next tick's batch.  Together with EncodeBatcher
    this module is the ONE sanctioned device-dispatch seam under
    cluster/ — with this class, on the read/recovery/verify paths too
    (the three round-11 ``per-op-device-dispatch`` baseline remnants
    retire here)."""

    def __init__(self, osd):
        self._osd = osd
        self._pending: Dict[Tuple, List] = {}
        self._workers: Dict[Tuple, asyncio.Task] = {}

    async def decode(self, codec, sinfo, shards, logical_size,
                     planar: bool = False) -> bytes:
        """Coalesced decode of one gather's shard ranges -> logical
        bytes (the ``decode_stripes`` contract, tick-batched).
        ``planar`` (round 19): the shards are AT-REST plane matrices
        and the decode runs in the plane domain end to end
        (``decode_planes_multi``) — the assemble's planes->bytes hop is
        the read's ONE sanctioned egress conversion."""
        from ceph_tpu.cluster.optracker import CURRENT_OP, mark_current

        if all(s in shards for s in range(sinfo.k)):
            # every data shard present: the "decode" is a pure host
            # interleave — no device work exists to coalesce, and the
            # tick/executor round trip would only add latency to the
            # hottest read shape (same bytes as decode_stripes' own
            # non-missing fast path, so bit-exactness is unaffected)
            from ceph_tpu.ec import stripe as stripemod

            if planar:
                return stripemod.decode_planes_multi(
                    codec, sinfo, [(shards, logical_size)])[0]
            return stripemod.assemble_data_stripes(sinfo, shards,
                                                   logical_size)
        mark_current("read_batch_parked")
        data, (t0, t1, batch_n) = await self._submit(
            ("decode", id(codec), sinfo.k, sinfo.chunk_size, planar),
            codec, sinfo, (shards, logical_size))
        op = CURRENT_OP.get()
        if op is not None:
            # amortized attribution, mirroring the write tick: this
            # op's share of the fused decode wall; the rest of the
            # window books as parked time
            share = (t1 - t0) / max(batch_n, 1)
            op.mark_at("read_batch_tick", t1 - share)
            op.mark_at("read_batch_decoded", t1)
        return data

    async def reencode(self, codec, sinfo, shards, logical_size,
                       planar: bool = False):
        """Coalesced recovery rebuild -> the op's (k+m, shard_len)
        matrix (the ``reencode_stripes`` contract, tick-batched).
        ``planar``: at-rest plane matrices in, (n, 8, cols) plane
        matrices out — ZERO layout conversions
        (``reencode_planes_multi``)."""
        rows, _tick = await self._submit(
            ("reencode", id(codec), sinfo.k, sinfo.chunk_size, planar),
            codec, sinfo, (shards, logical_size))
        return rows

    async def verify(self, rows, crcs, planar: bool = False) -> List[bool]:
        """Batched shard-crc verification: ``rows[i]`` checks against
        the stored ``ceph_crc32c(~0, row)`` value ``crcs[i]``; a tick's
        verifies share one crc32c batch per row-length group.  Returns
        the per-row pass/fail list.

        ``planar``: each row is an AT-REST plane blob; the crc runs on
        plane-major rows (``crc32c_planar_rows``) and stays bit-exact
        with the byte-anchor hinfo crc — no layout conversion.

        Hardware-crc hosts short-circuit inline: the per-row C pass
        (5.6 GB/s, GIL-releasing) beats any batching scheme — exactly
        crc32c_rows' own rule — so the tick/executor round trip would
        only tax the read hot path for nothing.  Device backends keep
        the coalesced crc32c batch."""
        from ceph_tpu.ops import crc32c as crcmod

        if crcmod._gcrc is not None:
            if planar:
                from ceph_tpu.ec import planar_store

                return [crc is None or
                        int(crcmod.crc32c_planar_rows(
                            planar_store.blob_to_planes(row))[0])
                        == int(crc)
                        for row, crc in zip(rows, crcs)]
            return [crc is None or
                    crcmod.crc32c(0xFFFFFFFF, row) == int(crc)
                    for row, crc in zip(rows, crcs)]
        oks, _tick = await self._submit(
            ("verify_planar",) if planar else ("verify",), None, None,
            (rows, crcs))
        return oks

    async def _submit(self, key, codec, sinfo, payload):
        fut = asyncio.get_event_loop().create_future()
        self._pending.setdefault(key, []).append(_Req(payload, False, fut))
        if key not in self._workers:
            task = asyncio.get_event_loop().create_task(
                self._drain(key, codec, sinfo))
            self._workers[key] = task
            self._osd._track(task)
        # resolved by the local worker's finally even on cancellation —
        # never a cross-daemon wait (the EncodeBatcher contract)
        return await fut  # graftlint: ignore[rpc-timeout]

    @staticmethod
    def _verify_multi(reqs):
        """One tick's crc verifications: every row of every request,
        batched per row-length group through ``crc32c_rows`` (hardware
        crc per row on CPU hosts, the GF(2) matmul batch on device)."""
        import numpy as np

        from ceph_tpu.ops.crc32c import crc32c_rows

        flat: List = []           # (req index, row index, bytes, crc)
        for ri, (rows, crcs) in enumerate(reqs):
            for j, (row, crc) in enumerate(zip(rows, crcs)):
                flat.append((ri, j, row, crc))
        by_len: Dict[int, List] = {}
        for item in flat:
            by_len.setdefault(len(item[2]), []).append(item)
        out = [[True] * len(rows) for rows, _c in reqs]
        for _length, group in by_len.items():
            stacked = np.stack([np.frombuffer(row, dtype=np.uint8)
                                for _ri, _j, row, _c in group])
            got = crc32c_rows(stacked)
            for (ri, j, _row, crc), g in zip(group, got):
                out[ri][j] = (crc is None) or (int(g) == int(crc))
        return out

    @staticmethod
    def _verify_planar_multi(reqs):
        """One tick's PLANAR crc verifications: every at-rest plane
        blob of every request, batched per length group through
        ``crc32c_planar_rows`` (plane-major rows, bit-exact with the
        byte-anchor hinfo crcs) — zero layout conversions."""
        import numpy as np

        from ceph_tpu.ec import planar_store
        from ceph_tpu.ops.crc32c import crc32c_planar_rows

        flat: List = []           # (req index, row index, planes, crc)
        for ri, (rows, crcs) in enumerate(reqs):
            for j, (row, crc) in enumerate(zip(rows, crcs)):
                flat.append((ri, j, planar_store.blob_to_planes(row),
                             crc))
        by_len: Dict[int, List] = {}
        for item in flat:
            by_len.setdefault(item[2].shape[1], []).append(item)
        out = [[True] * len(rows) for rows, _c in reqs]
        for _cols, group in by_len.items():
            stacked = np.vstack([planes for _ri, _j, planes, _c in group])
            got = crc32c_planar_rows(stacked)
            for (ri, j, _p, crc), g in zip(group, got):
                out[ri][j] = (crc is None) or (int(g) == int(crc))
        return out

    async def _drain(self, key, codec, sinfo) -> None:
        from ceph_tpu.ec import stripe as stripemod

        osd = self._osd
        mode = key[0]
        # one dispatcher per key: the planar flag rides the key (round
        # 19), so a planar tick and a byte tick of the same codec never
        # coalesce — their payload types differ
        if mode == "decode":
            fn = stripemod.decode_planes_multi if key[4] \
                else stripemod.decode_stripes_multi

            def compute(reqs):
                return osd._compute(fn, codec, sinfo, reqs)
        elif mode == "reencode":
            fn = stripemod.reencode_planes_multi if key[4] \
                else stripemod.reencode_stripes_multi

            def compute(reqs):
                return osd._compute(fn, codec, sinfo, reqs)
        elif mode == "verify_planar":
            def compute(reqs):
                return osd._compute(self._verify_planar_multi, reqs)
        else:
            def compute(reqs):
                return osd._compute(self._verify_multi, reqs)
        batch: List[_Req] = []
        try:
            while not osd._stopped:
                pending = self._pending.get(key)
                if not pending:
                    break
                cap = max(1, osd.config.osd_batch_tick_ops)
                batch = pending[:cap]
                self._pending[key] = pending[cap:]
                t0 = osd.clock.monotonic()
                try:
                    results = await compute([r.data for r in batch])
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # per-item fault isolation (the batched-frame rule):
                    # one op's bad inputs must not fail its tick-mates —
                    # re-run each request alone so only the poisoned one
                    # surfaces its error
                    if len(batch) == 1:
                        if not batch[0].fut.done():
                            batch[0].fut.set_exception(e)
                    else:
                        for r in batch:
                            if r.fut.done():
                                continue
                            try:
                                [res] = await compute([r.data])
                                r.fut.set_result(
                                    (res, (t0, osd.clock.monotonic(), 1)))
                            except asyncio.CancelledError:
                                raise
                            except Exception as e1:
                                r.fut.set_exception(e1)
                    batch = []
                    continue
                t1 = osd.clock.monotonic()
                osd.perf.inc("osd_read_batch_ticks")
                osd.perf.inc("osd_read_batch_coalesced", len(batch))
                tick = (t0, t1, len(batch))
                for r, res in zip(batch, results):
                    if not r.fut.done():
                        r.fut.set_result((res, tick))
                batch = []
        finally:
            self._workers.pop(key, None)
            leftovers = batch + (self._pending.pop(key, None) or [])
            for r in leftovers:
                if not r.fut.done():
                    r.fut.set_exception(
                        ConnectionError("read batcher stopped"))


class EncodeBatcher:
    """One per OSD daemon; keyed by codec identity so only same-profile
    writes coalesce (mixed-profile ticks run as independent batches —
    their math never mixes)."""

    def __init__(self, osd):
        self._osd = osd
        self._pending: Dict[Tuple, List[_Req]] = {}
        self._workers: Dict[Tuple, asyncio.Task] = {}

    async def encode(self, codec, sinfo, data, want_crc: bool,
                     planar: bool = False):
        """Coalesced encode of one op's stripe-aligned byte range.

        Returns ``(shards, crcs, (t0, t1, batch_n))``: the op's
        (k+m, nstripes*unit) shard matrix, the per-shard-row crcs (full
        rewrites only, else None), and the tick's encode window +
        batch size for amortized attribution.  ``planar`` (round 19):
        the tick runs ``encode_planes_multi`` — the op gets (n, 8,
        cols) AT-REST plane matrices and plane-major crcs; the client
        bytes -> planes hop inside the tick is the write's ONE
        sanctioned ingest conversion."""
        key = (id(codec), sinfo.k, sinfo.chunk_size, planar)
        fut = asyncio.get_event_loop().create_future()
        self._pending.setdefault(key, []).append(
            _Req(data, want_crc, fut))
        if key not in self._workers:
            task = asyncio.get_event_loop().create_task(
                self._drain(key, codec, sinfo))
            self._workers[key] = task
            self._osd._track(task)
        # not a cross-daemon RPC wait: the resolver is the local worker
        # task just armed above, whose finally resolves EVERY parked
        # request (exception on cancellation) — a bound here would only
        # add a spurious failure mode under first-call XLA compiles
        return await fut  # graftlint: ignore[rpc-timeout]

    async def encode_once(self, codec, sinfo, data,
                          planar: bool = False):
        """The ``osd_batch_tick_ops=0`` legacy per-op encode — the
        round-10 bisection anchor — hosted INSIDE the sanctioned
        dispatch seam: exactly the per-op ``encode_stripes`` executor
        hop, no coalescing, no batch crc (replicas re-checksum, the
        round-10 contract).  Living here rather than in backend_ec
        keeps the ``per-op-device-dispatch`` rule honest: every device
        dispatch of the cluster data plane, legacy branch included,
        routes through this module.  ``planar``: the per-op variant of
        the planar tick — a 1-request ``encode_planes_multi``."""
        from ceph_tpu.ec import stripe as stripemod

        if planar:
            [(planes, _crcs)] = await self._osd._compute(
                stripemod.encode_planes_multi, codec, sinfo, [data],
                [False])
            return planes
        return await self._osd._compute(
            stripemod.encode_stripes, codec, sinfo, data)

    async def _drain(self, key, codec, sinfo) -> None:
        """Tick loop for one codec profile; exits when idle (the next
        request re-arms it).  The empty-check/exit runs with no await in
        between, so an enqueue can never race the worker's death."""
        from ceph_tpu.ec import stripe as stripemod

        osd = self._osd
        # the planar flag rides the key: a planar tick returns plane
        # matrices + plane-major crcs, a byte tick returns shard rows —
        # same-profile writes still coalesce within each mode
        encode_fn = stripemod.encode_planes_multi if key[3] \
            else stripemod.encode_stripes_multi
        batch: List[_Req] = []
        try:
            while not osd._stopped:
                pending = self._pending.get(key)
                if not pending:
                    break
                window = osd.config.osd_batch_tick_window
                if window and len(pending) == 1:
                    # optional accumulation stretch after an idle start
                    await asyncio.sleep(window)
                    pending = self._pending.get(key) or []
                cap = max(1, osd.config.osd_batch_tick_ops)
                batch = pending[:cap]
                self._pending[key] = pending[cap:]
                # crash seam: the tick's batch is composed but the
                # encode never runs — every parked op dies un-encoded
                osd._chaos_point("tick_mid_encode")
                t0 = osd.clock.monotonic()
                try:
                    results = await osd._compute(
                        encode_fn, codec, sinfo,
                        [r.data for r in batch],
                        [r.want_crc for r in batch])
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    for r in batch:
                        if not r.fut.done():
                            r.fut.set_exception(e)
                    batch = []
                    continue
                t1 = osd.clock.monotonic()
                # crash seam: encoded but no op of the tick has entered
                # its commit section — nothing may survive as acked
                osd._chaos_point("tick_post_encode")
                osd.perf.inc("osd_batch_ticks")
                osd.perf.inc("osd_batch_coalesced_ops", len(batch))
                tick = (t0, t1, len(batch))
                for r, (shards, crcs) in zip(batch, results):
                    if not r.fut.done():
                        r.fut.set_result((shards, crcs, tick))
                batch = []
        finally:
            self._workers.pop(key, None)
            # cancellation mid-tick (daemon stop): parked requests must
            # fail loudly, never hang their ops to the full timeout
            leftovers = batch + (self._pending.pop(key, None) or [])
            for r in leftovers:
                if not r.fut.done():
                    r.fut.set_exception(
                        ConnectionError("encode batcher stopped"))
