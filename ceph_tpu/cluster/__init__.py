"""Mini-RADOS: messenger, monitor, OSD daemons, object store, client.

The cluster control plane around the TPU compute core, mirroring the
reference's daemon capability surface (SURVEY §2.3): an async messenger
(src/msg analog), a map-authority monitor (src/mon), OSD daemons with
replicated and erasure-coded PG backends whose encode/decode and placement
run through the TPU engine (src/osd), an in-memory ObjectStore (src/os
MemStore), and a client op engine (src/osdc Objecter + librados surface).
"""
