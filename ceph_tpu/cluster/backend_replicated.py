"""ReplicatedBackend: local txn + MOSDRepOp fan-out, pull/push
(reference src/osd/ReplicatedBackend.cc via the PGBackend seam)."""

from __future__ import annotations

import asyncio
import pickle
from typing import Optional

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.pglog import LogEntry
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.cluster.pg import PGMETA, PGState, _coll
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.osdmap.osdmap import PGPool


class ReplicatedBackendMixin:

    # --- replicated txn shapes (ONE builder per verb, round 12): the
    # serial _op_* methods and the pipelined client_ops routing both
    # build through these, so the two paths are txn-identical by
    # construction (the replicated analog of _ec_prepare_write).

    def _txn_write_full(self, st: PGState, oid: str, data: bytes,
                        snapc, version) -> Transaction:
        return (self._snap_pre_txn(st, oid, snapc)
                .remove(_coll(st.pgid), oid)
                .write(_coll(st.pgid), oid, 0, data)
                .set_version(_coll(st.pgid), oid, version[1]))

    def _txn_write(self, st: PGState, oid: str, offset: int,
                   data: bytes, snapc, version) -> Transaction:
        return (self._snap_pre_txn(st, oid, snapc)
                .write(_coll(st.pgid), oid, offset, data)
                .set_version(_coll(st.pgid), oid, version[1]))

    def _txn_truncate(self, st: PGState, oid: str, size: int,
                      snapc, version) -> Transaction:
        return (self._snap_pre_txn(st, oid, snapc)
                .truncate(_coll(st.pgid), oid, size)
                .set_version(_coll(st.pgid), oid, version[1]))

    # replicated write: local txn + MOSDRepOp fan-out (ReplicatedBackend)
    async def _op_write_full(self, pool: PGPool, st: PGState, oid: str,
                             data: bytes, snapc=None) -> int:
        if pool.is_erasure():
            return await self._ec_write(pool, st, oid, data, offset=None,
                                        snapc=snapc)
        version = self._next_version(st)
        txn = self._txn_write_full(st, oid, data, snapc, version)
        return await self._replicate_txn(st, txn, "modify", oid, version)

    async def _op_write(self, pool: PGPool, st: PGState, oid: str,
                        offset: int, data: bytes, snapc=None) -> int:
        """Partial write at (offset, len) — the RMW path for EC pools
        (reference ECBackend::start_rmw, ECBackend.cc:1785)."""
        if pool.is_erasure():
            return await self._ec_write(pool, st, oid, data, offset=offset,
                                        snapc=snapc)
        version = self._next_version(st)
        txn = self._txn_write(st, oid, offset, data, snapc, version)
        return await self._replicate_txn(st, txn, "modify", oid, version)

    def _head_size(self, pool: PGPool, st: PGState, oid: str,
                   missing=0):
        """Logical object size (EC pools: the 'size' xattr, the shard
        stat would be 1/k of it); ``missing`` for absent objects."""
        coll = _coll(st.pgid)
        if pool.is_erasure():
            sa = self.store.getattr(coll, oid, "size")
            if sa is not None:
                return int(sa)
            return missing if self.store.stat(coll, oid) is None else 0
        s = self.store.stat(coll, oid)
        return missing if s is None else s

    async def _op_truncate(self, pool: PGPool, st: PGState, oid: str,
                           size: int, snapc=None) -> int:
        """CEPH_OSD_OP_TRUNCATE.  Replicated: a store truncate in the
        replicated txn.  EC: re-encode the surviving prefix (the
        reference routes EC truncates through the RMW machinery too)."""
        if pool.is_erasure():
            cur = self._head_size(pool, st, oid)
            if size == cur:
                return 0
            if size < cur:
                head = await self._op_read(pool, st, oid, 0, size)
                head = head.ljust(size, b"\0")
            else:
                head = (await self._op_read(pool, st, oid, 0, cur)
                        ).ljust(size, b"\0")
            return await self._ec_write(pool, st, oid, head, offset=None,
                                        snapc=snapc)
        version = self._next_version(st)
        txn = self._txn_truncate(st, oid, size, snapc, version)
        return await self._replicate_txn(st, txn, "modify", oid, version)

    async def _op_delete_pipelined(self, pool: PGPool, st: PGState,
                                   oid: str, snapc=None) -> int:
        """Pipelined delete: same txn shape as ``_op_delete`` (COW
        pre-ops + EC rollback capture + remove), built under the PG
        lock inside the commit section, acks awaited outside.  On EC
        pools the commit additionally holds the OBJECT write lock: a
        delete slipping inside an in-flight RMW's read-merge window
        would be resurrected by the RMW's merged full-stripe commit —
        the lost-update race the object lock exists to exclude."""
        coll = _coll(st.pgid)

        def _build(version):
            txn = Transaction()
            txn.ops.extend(self._cow_pre_ops(st, oid, snapc,
                                             erasure=pool.is_erasure()))
            if pool.is_erasure():
                from ceph_tpu.cluster.pg import PGRB

                txn.rb_capture(coll, oid, PGRB,
                               self._rb_key(version[1]))
            txn.remove(coll, oid)
            return txn

        if pool.is_erasure():
            async with self._obj_write_lock(st, oid):
                return await self._rep_mutate_pipelined(st, oid, _build,
                                                        op="delete")
        return await self._rep_mutate_pipelined(st, oid, _build,
                                                op="delete")

    def _cow_pre_ops(self, st: PGState, oid: str, snapc,
                     erasure: bool) -> list:
        """Clone-on-write pre-ops for a mutation (make_writeable,
        PrimaryLogPG.cc:7019) — the ONE seam both backends and delete go
        through.  The returned ops must ride the same transaction /
        sub-write as the mutation so clone + snapset apply atomically."""
        from ceph_tpu.cluster import snaps as snapmod

        if snapc is None:
            return []
        coll = _coll(st.pgid)
        if erasure:
            sa = self.store.getattr(coll, oid, "size")
            size = int(sa) if sa else 0
        else:
            size = self.store.stat(coll, oid) or 0
        ops, cloned = snapmod.make_writeable_ops(
            self.store, coll, oid, snapc, size)
        if cloned:
            self.perf.inc("osd_snap_clones")
        return ops

    def _snap_pre_txn(self, st: PGState, oid: str, snapc) -> Transaction:
        txn = Transaction()
        txn.ops.extend(self._cow_pre_ops(st, oid, snapc, erasure=False))
        return txn

    async def _replicate_txn(self, st: PGState, txn: Transaction,
                             op: str, oid: str,
                             version: pglog.Eversion) -> int:
        """Apply locally + fan out with the log entry; commit when all
        acting replicas ack (reference PrimaryLogPG::issue_repop,
        PrimaryLogPG.cc:9173).  Serial shape — the caller holds st.lock
        across the whole call (compound/meta/trim mutations and the
        ``osd_pipeline_writes=0`` fallback).  The hot data path uses
        the start/finish split so the ack wait runs with the PG lock
        released (round 12: one durability story with pipelined EC)."""
        token = await self._replicate_txn_start(st, txn, op, oid, version)
        return await self._replicate_txn_finish(st, token)

    async def _replicate_txn_start(self, st: PGState, txn: Transaction,
                                   op: str, oid: str,
                                   version: pglog.Eversion):
        """Ordered commit section of a replicated mutation (runs under
        st.lock): local txn apply, log append, commit-frontier
        registration, and the MOSDRepOp fan-out SENDS.  Returns the
        token ``_replicate_txn_finish`` resolves — with the lock
        RELEASED on the pipelined path."""
        from ceph_tpu.cluster.optracker import mark_current
        from ceph_tpu.cluster.pg import CURRENT_OP_DEADLINE

        self.store.queue_transaction(txn)
        mark_current("store:journal_queued")
        entry = self._log_mutation(st, op, oid, version)
        # commit-frontier registration (round 11): replicated mutations
        # share the PG's watermark with pipelined EC writes, so every
        # advance routes through the contiguous-prefix frontier
        self._frontier_open(st, version)
        peers = [o for o in st.acting
                 if o != self.osd_id and o != CRUSH_ITEM_NONE]
        fut = None
        reqid = None
        try:
            self._chaos_point("commit_pre_fanout")
            if peers:
                reqid = self._next_reqid()
                fut = self._make_waiter(reqid, len(peers))
                # span propagation: replicas' apply spans join this op's
                # tree.  Message built PER PEER: send_message stamps hop
                # events into msg.trace, so a shared dict would leak one
                # replica's send stamp into the next replica's header
                subctx = self.tracer.context()
                txn_blob = txn.encode()
                # sub-writes inherit the client op's deadline (None for
                # recovery/trim traffic): replicas shed the dead legs
                sub_deadline = CURRENT_OP_DEADLINE.get()
                for o in peers:
                    rep = M.MOSDRepOp(reqid=reqid, pgid=st.pgid,
                                      txn_blob=txn_blob,
                                      entry=entry,
                                      epoch=self.osdmap.epoch,
                                      deadline=sub_deadline)
                    if subctx is not None:
                        rep.trace = dict(subctx)
                    try:
                        await self._send_osd(o, rep)
                    except (ConnectionError, OSError, RuntimeError):
                        # peer unreachable (map lag around a failure):
                        # the op proceeds on the reachable set; the
                        # logged entry delta-recovers the peer at rejoin
                        # (reference: acting shrinks, missing grows)
                        self._waiter_dec(reqid)
                mark_current("sub_op_sent")
        except BaseException:
            if reqid is not None:
                self._pending.pop(reqid, None)
            self._frontier_done(st, version, ok=False)
            raise
        return (reqid, version, fut, entry)

    async def _replicate_txn_finish(self, st: PGState, token) -> int:
        """Ack-wait half of a replicated mutation; resolves the commit
        frontier however it exits."""
        from ceph_tpu.cluster.optracker import mark_current

        reqid, version, fut, entry = token
        try:
            if fut is not None:
                try:
                    if not fut.done():
                        await asyncio.wait_for(
                            fut, timeout=self._ack_wait_timeout())
                    mark_current("sub_op_acked")
                except asyncio.TimeoutError:
                    self._frontier_done(st, version, ok=False)
                    return -110
                finally:
                    self._pending.pop(reqid, None)
        except BaseException:
            self._frontier_done(st, version, ok=False)
            raise
        if not self._entry_still_logged(st, entry):
            # entry rewound/replaced by a concurrent peering round
            # mid-ack-wait: no longer part of the PG's history — stay
            # un-acked (see the EC finish; same race, same
            # identity-based rule)
            self._frontier_done(st, version, ok=False)
            return -110
        # all acting members acked: advance the never-roll-back watermark
        # (through the frontier, clamped below any pending pipelined op)
        self._chaos_point("frontier_pre_done")
        self._frontier_done(st, version, ok=True)
        mark_current("commit")
        return 0

    async def _rep_mutate_pipelined(self, st: PGState, oid: str,
                                    build, op: str = "modify") -> int:
        """Pipelined replicated mutation (round 12): take the PG lock
        only for version assignment + txn build + the commit-start
        section, await the fan-out acks with it released.
        ``build(version) -> Transaction`` runs UNDER the lock, so
        reads it does (snap COW state, current size) are consistent
        with the version order exactly as in the serial path."""
        async with st.lock:
            version = self._next_version(st)
            txn = build(version)
            token = await self._replicate_txn_start(
                st, txn, op, oid, version)
        self.perf.inc("osd_rep_pipelined")
        return await self._replicate_txn_finish(st, token)

    async def _op_delete(self, pool: PGPool, st: PGState, oid: str,
                         snapc=None) -> int:
        """Delete is ack-gated exactly like writes — fire-and-forget
        MOSDRepOps let a slow replica resurrect the object.  Under a
        SnapContext the pre-delete head is cloned first (whiteout
        semantics: snaps keep seeing the object; for EC pools the clone
        op copies each member's SHARD object in place)."""
        coll = _coll(st.pgid)
        version = self._next_version(st)
        txn = Transaction()
        txn.ops.extend(self._cow_pre_ops(st, oid, snapc,
                                         erasure=pool.is_erasure()))
        if pool.is_erasure():
            # rollback record for the delete, captured MEMBER-LOCALLY by
            # the store op (each member journals its own shard bytes) so
            # an un-acked delete can rewind during peering
            from ceph_tpu.cluster.pg import PGRB

            txn.rb_capture(coll, oid, PGRB, self._rb_key(version[1]))
        txn.remove(coll, oid)
        return await self._replicate_txn(st, txn, "delete", oid, version)

    async def _op_read(self, pool: PGPool, st: PGState, oid: str,
                       offset: int = 0, length: Optional[int] = None) -> bytes:
        if pool.is_erasure():
            return await self._ec_read(pool, st, oid, offset, length)
        return self.store.read(_coll(st.pgid), oid, offset, length)

    async def _pull_rep_object(self, st: PGState, source: int,
                               oid: str) -> bool:
        """Fetch a full replicated object from a member (pull recovery,
        reference ReplicatedBackend::prepare_pull).  Returns success: the
        caller must NOT claim the authoritative version for objects it
        failed to pull."""
        return await self._pull_rep_object_st(st, source, oid) == "ok"

    async def _pull_rep_object_st(self, st: PGState, source: int,
                                  oid: str) -> str:
        """Pull with outcome: "ok" | "enoent" (source lacks the object —
        definitive, not a failure) | "fail" (unreachable/timeout)."""
        reqid = self._next_reqid()
        fut = self._make_waiter(reqid, 1)
        try:
            await self._send_osd(source, M.MOSDECSubOpRead(
                reqid=reqid, pgid=st.pgid, oid=oid, shard=-1))
            acc = await asyncio.wait_for(fut, timeout=2.0)
            result, reply = acc[0]
            if result == -2:
                return "enoent"
            if result == 0 and reply is not None:
                txn = (Transaction()
                       .remove(_coll(st.pgid), oid)
                       .write(_coll(st.pgid), oid, 0, reply.data)
                       .set_version(_coll(st.pgid), oid,
                                    reply.hinfo.get("version", 0)))
                for k, v in reply.hinfo.get("xattrs", {}).items():
                    txn.setattr(_coll(st.pgid), oid, k, v)
                self.store.queue_transaction(txn)
                return "ok"
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            self._pending.pop(reqid, None)
        return "fail"

    async def _push_object(self, pool: PGPool, st: PGState, osd: int,
                           oid: str, entry: LogEntry) -> bool:
        """Replay one log entry onto a stale member (delta recovery).
        Returns False when the push failed (the member stays stale and
        the recovery round must be retried)."""
        if entry.op == "delete":
            try:
                await self._send_osd(osd, M.MOSDPGPush(
                    pgid=st.pgid, oid=oid, op="delete",
                    version=entry.version[1], entry=entry))
                self.perf.inc("osd_pushes_sent")
                return True
            except ConnectionError:
                return False
        ok = True
        if entry.op == "trim" or self._has_snap_state(st, oid):
            # snapshot-bearing object: the logged head mutation implies
            # clone/snapset changes that must travel with it
            ok = await self._push_snap_state(pool, st, osd, oid)
        if entry.op == "trim":
            return ok
        if pool.is_erasure():
            return ok & await self._recover_ec_object(
                pool, st, oid, targets=[osd], entry=entry)
        coll = _coll(st.pgid)
        if self.store.stat(coll, oid) is None:
            return ok  # deleted since: a later entry carries the delete
        data = self.store.read(coll, oid)
        try:
            await self._send_osd(osd, M.MOSDPGPush(
                pgid=st.pgid, oid=oid, data=data,
                xattrs=self.store.get_xattrs(coll, oid),
                version=entry.version[1], entry=entry))
            self.perf.inc("osd_pushes_sent")
        except ConnectionError:
            ok = False
        return ok

    async def _repull_after_rewind(self, st: PGState, oids) -> None:
        """Re-fetch objects a record-less rewind had to remove, from the
        acting primary (the instruction sender).  Failed pulls retry
        under capped seeded backoff: this runs on a NON-primary, so the
        primary-side incomplete-round re-arm (recovery.py
        _queue_recovery_retry) never covers it — dropping a failure here
        would leave the shard missing until an unrelated map change."""
        pool = self.osdmap.pools.get(st.pgid.pool)
        if pool is None:
            return
        from ceph_tpu.chaos.rng import stream
        from ceph_tpu.utils.backoff import ExpBackoff

        rng = stream(self.config.chaos_seed,
                     f"repull:osd.{self.osd_id}:{st.pgid}") \
            if self.config.chaos_seed else None
        bo = ExpBackoff(base=0.25, cap=3.0, rng=rng)
        pending = list(oids)
        for _ in range(6):
            failed = []
            for oid in pending:
                try:
                    if pool.is_erasure():
                        ok = await self._recover_ec_object(
                            pool, st, oid, targets=[self.osd_id])
                    elif st.primary >= 0 and st.primary != self.osd_id:
                        ok = await self._pull_rep_object(st, st.primary,
                                                         oid)
                    else:
                        ok = True
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    ok = False
                if not ok:
                    failed.append(oid)
                    self.perf.inc("osd_recovery_incomplete")
            if not failed:
                return
            pending = failed
            if self._stopped or self.pgs.get(st.pgid) is not st:
                return
            await asyncio.sleep(bo.next())

    def _has_snap_state(self, st: PGState, oid: str) -> bool:
        from ceph_tpu.cluster import snaps as snapmod

        return self.store.getattr(_coll(st.pgid),
                                  snapmod.snapdir_oid(oid), "ss") is not None

    async def _push_snap_state(self, pool: PGPool, st: PGState, osd: int,
                               head: str) -> bool:
        """Sync one head's snapshot state to a member: the authoritative
        SnapSet (as a snap_sync push — the receiver also deletes clones
        the set no longer lists, covering missed trims) plus every live
        clone object.  Returns False when any push failed."""
        from ceph_tpu.cluster import snaps as snapmod

        coll = _coll(st.pgid)
        blob = self.store.getattr(coll, snapmod.snapdir_oid(head), "ss")
        if blob is None:
            return True
        try:
            await self._send_osd(osd, M.MOSDPGPush(
                pgid=st.pgid, oid=head, op="snap_sync", data=blob))
        except ConnectionError:
            return False
        ss = snapmod.SnapSet.decode(blob)
        ok = True
        for c in ss.clones:
            cname = snapmod.clone_oid(head, c)
            if self.store.stat(coll, cname) is None:
                continue
            if pool.is_erasure():
                ok &= await self._recover_ec_object(pool, st, cname,
                                                    targets=[osd])
            else:
                try:
                    await self._send_osd(osd, M.MOSDPGPush(
                        pgid=st.pgid, oid=cname,
                        data=self.store.read(coll, cname),
                        xattrs=self.store.get_xattrs(coll, cname),
                        version=self.store.get_version(coll, cname)))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    ok = False
        return ok


    def _handle_push(self, msg: M.MOSDPGPush) -> None:
        coll = _coll(msg.pgid)
        st = self.pgs.get(msg.pgid)
        if msg.op == "log_sync":
            if st is not None:
                st.last_update, st.log = pickle.loads(msg.data)
                self._save_pg_meta(st)
            else:
                # backfill target OUTSIDE acting (pg_temp handoff): we
                # hold the pushed data but not the PGState yet — it
                # materializes when the temp entry clears and the map
                # puts us in acting.  Persist the shipped meta now, and
                # stamp last_complete at the shipped head so the resume
                # path (_frontier_rebuild) doesn't treat every adopted
                # entry as an open frontier needing re-verification.
                tmp = PGState(msg.pgid, [], [], -1)
                tmp.last_update, tmp.log = pickle.loads(msg.data)
                self._save_pg_meta(tmp)
                txn = Transaction()
                txn.setattr(coll, PGMETA, "last_complete",
                            pickle.dumps(tmp.last_update))
                self.store.queue_transaction(txn)
            self.perf.inc("osd_pushes_applied")
            return
        if msg.op == "rewind":
            # primary-instructed divergent-log rewind (PGLog.cc:287):
            # undo our entries beyond the authoritative head from the
            # local rollback journal.  Self-protection: never rewind
            # below our own commit watermark — entries acked to clients
            # are not rollbackable, whatever a (possibly stale) primary
            # says
            if st is not None:
                target = pickle.loads(msg.data)
                if st.last_update > target >= st.last_complete:
                    need = self.rewind_divergent_log(st, target)
                    if need:
                        # fallback removals (lost records): re-pull the
                        # authoritative copies off the dispatch path,
                        # tracked so the task self-discards (task-spawn
                        # lint: a bare spawn here leaked one dead Task
                        # per rewind for the daemon's life)
                        import asyncio as _aio

                        self._track(_aio.get_event_loop().create_task(
                            self._repull_after_rewind(st, list(need))))
            self.perf.inc("osd_pushes_applied")
            return
        if msg.op == "snap_sync":
            # adopt the authoritative SnapSet; clones it no longer lists
            # were trimmed while we were away.  Version-guarded like data
            # pushes: an old primary still draining its push queue must
            # never overwrite a newer snapset (and destroy its clones)
            from ceph_tpu.cluster import snaps as snapmod

            ss = snapmod.SnapSet.decode(msg.data)
            local = snapmod.load_snapset(self.store, coll, msg.oid)
            if local.version >= ss.version:
                return
            txn = Transaction()
            txn.ops.extend(snapmod.snapset_ops(coll, msg.oid, ss))
            txn.ops.extend(snapmod.prune_clone_ops(
                self.store, coll, msg.oid, ss))
            self.store.queue_transaction(txn)
            self.perf.inc("osd_pushes_applied")
            return
        if msg.op == "delete":
            # version-guarded like pushes: a stale delete (old primary's
            # backfill racing a newer primary's push) must not remove a
            # newer object
            cur = self.store.get_version(coll, msg.oid)
            if cur <= msg.version:
                self.store.queue_transaction(
                    Transaction().remove(coll, msg.oid))
        else:
            cur = self.store.get_version(coll, msg.oid)
            exists = self.store.stat(coll, msg.oid) is not None
            # op == "repair": scrub found silent corruption (same version,
            # wrong bytes) — apply unconditionally
            if msg.op == "repair" or not (exists and cur >= msg.version):
                txn = (Transaction()
                       .remove(coll, msg.oid)
                       .write(coll, msg.oid, 0, msg.data)
                       .set_version(coll, msg.oid, msg.version))
                for k, v in msg.xattrs.items():
                    txn.setattr(coll, msg.oid, k, v)
                self.store.queue_transaction(txn)
        if st is not None and msg.entry is not None:
            self._log_mutation(st, msg.entry.op, msg.entry.oid,
                               msg.entry.version, entry=msg.entry)
        self.perf.inc("osd_pushes_applied")
