"""CephX-lite: mon-issued time-limited tickets, per-entity keys,
per-session signing keys, and capability enforcement.

Behavioral analog of the reference cephx protocol
(src/auth/cephx/CephxProtocol.h:412 CephXTicketBlob/CephXAuthorizer,
CephxServiceHandler.h:23): the monitor authenticates an entity with its
per-entity key and issues a TICKET — {entity, caps, session key, expiry}
sealed under the SERVICE key — which services validate OFFLINE (no mon
round-trip per connection, cephx's core design).  A connection presents
the ticket plus an authorizer proof of the session key; all subsequent
frames on the session are HMAC-signed with the session key.

Lite-ness, documented: (a) per-entity keys derive from the cluster
master key (HMAC(master, entity)) instead of a provisioned keyring — the
keys are still distinct per entity and never travel in clear, but there
is no external keyring file; (b) sealing uses an HMAC-SHA256 keystream
(hashlib/hmac are the only crypto primitives in this environment)
instead of AES; (c) "rotation" is ticket expiry + renewal rather than
rotating service keys.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import struct
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

SIG_LEN = 16


# -- key derivation ---------------------------------------------------------

def entity_key(master: bytes, name: str) -> bytes:
    """Per-entity secret (keyring analog): distinct per entity name."""
    return hmac.new(master, b"entity:" + name.encode(),
                    hashlib.sha256).digest()


def service_key(master: bytes) -> bytes:
    """Shared mon/daemon key sealing tickets (the rotating service
    secret's stand-in)."""
    return hmac.new(master, b"service", hashlib.sha256).digest()


# -- sealed boxes (HMAC-CTR keystream + MAC) --------------------------------

def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hmac.new(key, nonce + ctr.to_bytes(8, "big"),
                        hashlib.sha256).digest()
        ctr += 1
    return out[:n]


def seal(key: bytes, obj) -> bytes:
    """Encrypt-then-MAC a pickled payload."""
    plain = pickle.dumps(obj)
    nonce = os.urandom(16)
    ks = _keystream(key, nonce, len(plain))
    ct = bytes(a ^ b for a, b in zip(plain, ks))
    mac = hmac.new(key, nonce + ct, hashlib.sha256).digest()[:SIG_LEN]
    return nonce + ct + mac


def unseal(key: bytes, blob: bytes):
    """Verify + decrypt; raises ValueError on tamper/garbage."""
    if len(blob) < 16 + SIG_LEN:
        raise ValueError("short sealed blob")
    nonce, ct, mac = blob[:16], blob[16:-SIG_LEN], blob[-SIG_LEN:]
    want = hmac.new(key, nonce + ct, hashlib.sha256).digest()[:SIG_LEN]
    if not hmac.compare_digest(mac, want):
        raise ValueError("sealed blob MAC mismatch")
    ks = _keystream(key, nonce, len(ct))
    return pickle.loads(bytes(a ^ b for a, b in zip(ct, ks)))


# -- tickets ----------------------------------------------------------------

@dataclass
class Ticket:
    """CephXTicketBlob analog (the decrypted view)."""

    entity: str
    caps: Dict[str, str]          # service -> "r" | "rw" | ""
    session_key: bytes = b""
    valid_until: float = 0.0

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.time()) > self.valid_until


def issue_ticket(master: bytes, entity: str, caps: Dict[str, str],
                 ttl: float) -> Tuple[bytes, bytes, bytes]:
    """Mon side: -> (ticket_blob sealed under the service key,
    session_key sealed under the ENTITY key, session_key) — the client
    can open only the second; services only the first
    (CephxServiceHandler::handle_request)."""
    skey = os.urandom(32)
    t = Ticket(entity=entity, caps=dict(caps), session_key=skey,
               valid_until=time.time() + ttl)
    blob = seal(service_key(master), t)
    for_client = seal(entity_key(master, entity), skey)
    return blob, for_client, skey


def validate_ticket(master: bytes, blob: bytes) -> Ticket:
    """Service side, OFFLINE: unseal + expiry check; raises ValueError
    for tampered/expired tickets."""
    t = unseal(service_key(master), blob)
    if not isinstance(t, Ticket):
        raise ValueError("not a ticket")
    if t.expired():
        raise ValueError(f"ticket for {t.entity} expired")
    return t


# -- authorizers (per-connection proof of the session key) ------------------

def make_authorizer(ticket_blob: bytes, session_key: bytes) -> bytes:
    """Fixed binary layout (u32 ticket_len | ticket | nonce16 | proof16):
    an authorizer arrives on an UNauthenticated connection, so its outer
    framing must be parseable without a deserializer; the only pickled
    content sits inside the sealed ticket, whose MAC `unseal` verifies
    before decoding."""
    nonce = os.urandom(16)
    proof = hmac.new(session_key, b"authorizer:" + nonce,
                     hashlib.sha256).digest()[:SIG_LEN]
    return struct.pack("<I", len(ticket_blob)) + ticket_blob + nonce + proof


def verify_authorizer(master: bytes, authorizer: bytes) -> Ticket:
    """Service side: validate the ticket, then the possession proof.
    Returns the ticket (entity + caps + session key) on success."""
    if len(authorizer) < 4:
        raise ValueError("short authorizer")
    (tl,) = struct.unpack_from("<I", authorizer)
    if len(authorizer) != 4 + tl + 16 + SIG_LEN:
        raise ValueError("malformed authorizer")
    ticket = authorizer[4:4 + tl]
    nonce = authorizer[4 + tl:4 + tl + 16]
    proof = authorizer[4 + tl + 16:]
    t = validate_ticket(master, ticket)
    want = hmac.new(t.session_key, b"authorizer:" + nonce,
                    hashlib.sha256).digest()[:SIG_LEN]
    if not hmac.compare_digest(proof, want):
        raise ValueError("authorizer proof mismatch")
    return t


# -- capability checks ------------------------------------------------------

def allows(caps: Dict[str, str], service: str, access: str) -> bool:
    """access "r" or "rw" against this entity's grant for a service
    (MonCap/OSDCap's role, radically simplified to r/rw grants)."""
    grant = caps.get(service, "")
    if access == "r":
        return "r" in grant
    return grant == "rw" or "w" in grant


DEFAULT_CAPS = {
    # entity-type prefix -> caps granted by the mon at authentication
    # (reference: default profiles, e.g. 'profile osd')
    "client": {"mon": "r", "osd": "rw", "mds": "rw"},
    "osd": {"mon": "rw", "osd": "rw"},
    "mon": {"mon": "rw", "osd": "rw"},
    "mds": {"mon": "rw", "osd": "rw", "mds": "rw"},
    "mgr": {"mon": "rw", "osd": "r"},
}


def default_caps_for(entity: str) -> Dict[str, str]:
    if entity == "client.admin":
        # the admin keyring's 'allow *' analog
        return {"mon": "rw", "osd": "rw", "mds": "rw"}
    kind = entity.split(".", 1)[0]
    return dict(DEFAULT_CAPS.get(kind, {"mon": "r"}))


class CephxContext:
    """Per-messenger auth state.

    Daemons hold the cluster MASTER key and self-issue their tickets
    (they could mint anything anyway — possession of the master key IS
    cluster membership, as with the reference's mon./osd. keyring
    entries).  Clients hold only their per-entity key and must bootstrap
    a ticket from a monitor (Messenger.cephx_bootstrap)."""

    def __init__(self, entity: str, master: Optional[bytes] = None,
                 entity_secret: Optional[bytes] = None,
                 ttl: float = 3600.0,
                 caps: Optional[Dict[str, str]] = None):
        self.entity = entity
        self.master = master
        self.entity_secret = entity_secret if entity_secret is not None \
            else (entity_key(master, entity) if master else None)
        self.ttl = ttl
        self.caps = caps
        self.ticket_blob: Optional[bytes] = None
        self.session_key: Optional[bytes] = None
        self.valid_until: float = 0.0

    def ticket_expired(self) -> bool:
        return time.time() > self.valid_until - 1.0

    def ensure_ticket(self) -> None:
        """Self-issue (master holders); clients must have bootstrapped."""
        if self.ticket_blob is not None and not self.ticket_expired():
            return
        if self.master is None:
            raise PermissionError(
                f"{self.entity}: no valid ticket (bootstrap from a mon)")
        self.ticket_blob, _, self.session_key = issue_ticket(
            self.master, self.entity,
            self.caps or default_caps_for(self.entity), self.ttl)
        self.valid_until = time.time() + self.ttl

    def adopt(self, ticket_blob: bytes, sealed_key: bytes,
              ttl_hint: float) -> None:
        """Client side: accept a mon-issued ticket."""
        self.session_key = unseal(self.entity_secret, sealed_key)
        self.ticket_blob = ticket_blob
        self.valid_until = time.time() + ttl_hint
