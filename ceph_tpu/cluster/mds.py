"""MDS daemon: the metadata SERVER for the CephFS-analog.

Round 4 (VERDICT r3 item 7): moves fs.py's metadata authority out of the
client library into a daemon, the reference's MDSRank shape
(/root/reference/src/mds/MDSRank.cc): clients send metadata ops
(MClientRequest) to the active MDS, which serializes them, journals them
WRITE-AHEAD into a RADOS-backed metadata journal
(/root/reference/src/mds/journal.cc MDLog analog — an omap event log in
the meta pool), applies them through the cls-atomic dirfrag engine
(cluster/fs.py, kept as the storage layer), and replies with short-TTL
read leases (Locker caps-lite, /root/reference/src/mds/Locker.cc: the
client may cache a lookup until the lease expires; every mutation goes
to the MDS, so two clients always observe a single serialized order).

An MDS restart REPLAYS unapplied journal events before serving
(MDSRank::boot_start replay stage).  Active MDS addresses ride the
cluster map via rank-tagged beacons (MDSMap-lite).

Round 5: MULTI-ACTIVE subtree partitioning (the Migrator analog — see
the subtree-authority section) and fs SNAPSHOTS (SnapServer-lite: the
.snap pseudo-paths over pool-level selfmanaged COW, metadata included
via the dirfrag exec/omap SnapContext seam).
"""

from __future__ import annotations

import asyncio
import pickle
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.fs import FileSystem, Inode
from ceph_tpu.cluster.messenger import (
    Addr,
    Connection,
    Dispatcher,
    EntityName,
    Messenger,
)
from ceph_tpu.utils import Config, DepLock, PerfCounters

JOURNAL_OID = "mds_journal.0"   # rank 0 (kept name: store compat)
SUBTREE_OID = "mds_subtrees"    # omap {dir path: owner rank} (auth table)


@dataclass
class MClientRequest(M.Message):
    """Client metadata op (reference MClientRequest)."""

    tid: int = 0
    client: str = ""                  # incarnation-unique client identity
    op: str = ""                      # mkdir|create|stat|listdir|...
    args: Tuple = ()


@dataclass
class MClientReply(M.Message):
    tid: int = 0
    result: int = 0
    data: object = None
    error: str = ""
    lease_ttl: float = 0.0            # read-cacheable until now+ttl
    snapc: Optional[Tuple] = None     # data-pool write context (stat)
    snapid: Optional[int] = None      # data-pool read snap (.snap stat)


@dataclass
class MMDSBeacon(M.Message):
    """MDS -> mon registration (reference MMDSBeacon)."""

    addr: Optional[Tuple] = None
    rank: int = 0


def norm_path(path: str) -> str:
    return "/" + "/".join(p for p in str(path).split("/") if p)


def owner_rank(subtrees: Dict[str, int], path: str) -> int:
    """Longest-prefix subtree authority lookup — ONE implementation
    shared by daemon routing and client targeting, so the two can never
    disagree (component-boundary aware)."""
    path = norm_path(path)
    best, best_len = 0, -1
    for prefix, rank in subtrees.items():
        if prefix == "/" or path == prefix or \
                path.startswith(prefix + "/"):
            if len(prefix) > best_len:
                best, best_len = rank, len(prefix)
    return best


# journal ops that mutate dirfrag state (everything except pure reads)
_MUTATING = {"mkdir", "create", "unlink", "rename", "set_size"}
# ops routed by subtree authority (args[0] is always the primary path)
_ROUTED = _MUTATING | {"stat", "listdir", "snap_create", "snap_rm",
                       "export_dir"}


class MDSDaemon(Dispatcher):
    def __init__(self, mon_addr, meta_pool: int, data_pool: int,
                 config: Optional[Config] = None, rank: int = 0):
        self.rank = rank
        self.config = Config(**config.show()) if config else Config()
        self.messenger = Messenger(
            EntityName("mds", rank),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"mds.{rank}"),
            config=self.config)
        self.messenger.add_dispatcher(self)
        self.mon_addr = mon_addr
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.perf = PerfCounters(f"mds.{rank}")
        from ceph_tpu.utils import AdminSocket

        self.asok = AdminSocket()
        self.asok.register_common(self.perf, self.config)
        self.asok.register(
            "status", lambda cmd: {"rank": self.rank,
                                   "meta_pool": self.meta_pool,
                                   "data_pool": self.data_pool},
            "this MDS rank's identity")
        self._client = None               # our own RADOS client
        self.fs: Optional[FileSystem] = None
        self._lock = DepLock("mds.big_lock")  # the single-MDS big lock
        # self-discarding background-task registry (the messenger/osd
        # _track pattern; task-spawn lint invariant)
        self._tasks: set = set()
        self._stopped = False
        self.lease_ttl = self.config.mds_lease_ttl
        # completed-request cache (the OSD reqid dup cache's MDS twin,
        # reference MDCache request dedup): a client retry of a mutating
        # op whose reply was merely delayed gets the ORIGINAL reply
        # instead of a spurious EEXIST/ENOENT re-execution
        from collections import OrderedDict as _OD

        self._completed: "_OD[Tuple[str, int], MClientReply]" = _OD()
        # chaos crash points (round 15): the MDS is a daemon, so an
        # armed seam crashes it through the launcher's callback like an
        # OSD.  The MDS has no local store (all state lives in RADOS),
        # so "power cut" = stop serving at this instant; the restarted
        # rank replays its journal.
        self._chaos_crash_cb = None

    def _chaos_point(self, name: str) -> None:
        """Named crash seam (the OSD._chaos_point twin for MDS ranks):
        when the armed ``chaos_crash_point`` matches, this rank dies AT
        THIS INSTANT — ``_stopped`` flips before anything else runs,
        teardown is handed to the launcher's callback, and ChaosCrash
        unwinds the current request like a task dying mid-await.  One
        falsy test when unarmed (no-op contract).

        The armed value may be a CHAIN ("mds_journal_mid,mds_replay_mid"):
        firing pops the head and arms the remainder in this rank's
        config — and since a restarted rank RESUMES its per-rank config,
        the chain spans incarnations (crash mid-append, then crash the
        next boot's replay of that very event).  An empty remainder
        disarms, so a replay-seam point can never crash-loop the rank.
        """
        if not self.config.chaos_crash_point or self._stopped:
            return
        from ceph_tpu.chaos import ChaosCrash
        from ceph_tpu.chaos.counters import CHAOS
        from ceph_tpu.chaos.points import resolve_fire

        if not resolve_fire(self.config, name):
            return
        self._stopped = True
        CHAOS.inc("crash_points_fired")
        CHAOS.inc("mds_crash_points_fired")
        cb = self._chaos_crash_cb
        if cb is not None:
            # the callback task is OWNED BY THE LAUNCHER (it outlives
            # this daemon's stop())
            cb(name)
        raise ChaosCrash(f"mds chaos crash point {name!r} fired")

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        from ceph_tpu.cluster.objecter import RadosClient

        addr = await self.messenger.bind(host, port)
        self._client = RadosClient(self.mon_addr, name=f"mds{self.rank}",
                                   config=self.config)
        await self._client.connect()
        # HOLD these instances: ioctx() mints a fresh IoCtx per call,
        # and the snapshot SnapContexts install onto these exact objects
        self._meta_io = self._client.ioctx(self.meta_pool)
        self._data_io = self._client.ioctx(self.data_pool)
        meta_io, data_io = self._meta_io, self._data_io
        self.fs = FileSystem(meta_io, data_io)
        try:
            await self.fs.stat("/")
        except FileNotFoundError:
            await self.fs.mkfs()
        await self._load_subtrees(create=(self.rank == 0))
        await self._load_snaptable()
        await self._replay_journal()
        await self._beacon()
        loop = asyncio.get_event_loop()
        self._track(loop.create_task(self._beacon_loop()))
        return addr

    # -- subtree authority (Migrator analog) --------------------------------
    #
    # Reference src/mds/Migrator.h:52: multi-active MDS partitions the
    # namespace into subtrees, each owned by one rank; export_dir moves
    # authority.  In this framework EVERY dirfrag lives in shared RADOS,
    # so "migration" is an authority-table flip (one atomic omap write) —
    # no cache or journal segments travel, and the per-op WRITE-AHEAD
    # journal means there is no unflushed state to hand over.  Requests
    # that land on the wrong rank bounce with ESTALE + the owner hint and
    # the client retargets (the reference's forward-to-auth).

    async def _load_subtrees(self, create: bool = False) -> None:
        io = self._meta_io
        try:
            om = await io.omap_get(SUBTREE_OID)
        except (FileNotFoundError, IOError):
            om = {}
        if not om:
            if create:
                await io.omap_set(SUBTREE_OID, {"/": b"0"})
            om = {"/": b"0"}
        self.subtrees = {p: int(r) for p, r in om.items()}

    @staticmethod
    def _norm(path: str) -> str:
        return norm_path(path)

    def _owner_rank(self, path: str) -> int:
        return owner_rank(self.subtrees, path)

    async def _export_dir(self, path: str, target: int) -> None:
        """Move subtree authority (Migrator::export_dir): one atomic
        authority-table write; the journal is already flushed per-op."""
        path = self._norm(path)
        io = self._meta_io
        await self.fs.stat(path)  # must exist (and be resolvable)
        await io.omap_set(SUBTREE_OID, {path: str(target).encode()})
        await self._load_subtrees()
        self.perf.inc("mds_exports")

    # -- snapshots (SnapServer/SnapRealm-lite) ------------------------------
    #
    # Reference src/mds/SnapServer.h snaptable + snaprealms: a snapshot
    # of directory D freezes D's subtree.  Here both pools already COW
    # under selfmanaged SnapContexts (dirfrag omaps included, via the
    # exec/omap snapc seam), so an fs snapshot = allocate one snapid in
    # each pool, record (name -> ids, dir) in the snaptable object, and
    # extend every MDS's write SnapContext.  The realm is GLOBAL (one
    # context covers the whole fs — objects outside the snapped dir may
    # grow clones if modified, which costs space, never correctness);
    # .snap path reads resolve with the recorded ids.

    SNAPTABLE_OID = "mds_snaptable"

    async def _load_snaptable(self) -> None:
        io = self._meta_io
        try:
            om = await io.omap_get(self.SNAPTABLE_OID)
        except (FileNotFoundError, IOError):
            om = {}
        self.snaptable = {name: pickle.loads(blob)
                          for name, blob in om.items()}
        self._install_snapc()

    def _install_snapc(self) -> None:
        metas = sorted((v["meta_id"] for v in self.snaptable.values()),
                       reverse=True)
        datas = sorted((v["data_id"] for v in self.snaptable.values()),
                       reverse=True)
        self._meta_io.set_snap_context(metas[0] if metas else 0,
                                       metas)
        self._data_io.set_snap_context(datas[0] if datas else 0, datas)

    def _data_snapc(self) -> Tuple[int, Tuple[int, ...]]:
        datas = tuple(sorted((v["data_id"]
                              for v in self.snaptable.values()),
                             reverse=True))
        return (datas[0] if datas else 0, datas)

    async def _snap_create(self, dirpath: str, name: str) -> int:
        dirpath = self._norm(dirpath)
        ino = await self.fs.stat(dirpath)
        if ino.mode != "dir":
            raise NotADirectoryError(dirpath)
        if name in self.snaptable:
            raise FileExistsError(f"{dirpath}/.snap/{name}")
        meta_id = await self._meta_io.selfmanaged_snap_create()
        data_id = await self._data_io.selfmanaged_snap_create()
        rec = {"dir": dirpath, "meta_id": meta_id, "data_id": data_id,
               "stamp": time.time()}
        # omap_set auto-creates (the meta txn touches the object)
        await self._meta_io.omap_set(self.SNAPTABLE_OID,
                                     {name: pickle.dumps(rec)})
        await self._load_snaptable()
        return data_id

    async def _snap_rm(self, dirpath: str, name: str) -> None:
        rec = self.snaptable.get(name)
        if rec is None or rec["dir"] != self._norm(dirpath):
            raise FileNotFoundError(f"{dirpath}/.snap/{name}")
        io = self._meta_io
        await io.omap_rmkeys(self.SNAPTABLE_OID, [name])
        try:
            await self._meta_io.selfmanaged_snap_remove(rec["meta_id"])
            await self._data_io.selfmanaged_snap_remove(rec["data_id"])
        except (IOError, OSError, TimeoutError, ConnectionError):
            pass  # trimming is advisory; the table entry is gone
        await self._load_snaptable()

    def _split_snap_path(self, path: str):
        """'/d/.snap/name[/rest]' -> (live '/d[/rest]', snap record) or
        (path, None)."""
        parts = [p for p in path.split("/") if p]
        if ".snap" not in parts:
            return self._norm(path), None
        i = parts.index(".snap")
        if i + 1 >= len(parts):
            return self._norm(path), "LIST"   # '/d/.snap' itself
        name = parts[i + 1]
        rec = self.snaptable.get(name)
        if rec is None:
            raise FileNotFoundError(path)
        base = self._norm("/" + "/".join(parts[:i]))
        d = rec["dir"]
        if base != d and not (d == "/" or base.startswith(d + "/")):
            raise FileNotFoundError(path)
        live = "/" + "/".join(parts[:i] + parts[i + 2:])
        return self._norm(live), rec

    def _track(self, task: asyncio.Task) -> asyncio.Task:
        from ceph_tpu.utils.tasks import track_task

        return track_task(self._tasks, task)

    async def stop(self) -> None:
        self._stopped = True
        for t in list(self._tasks):
            t.cancel()
        if self._client is not None:
            await self._client.shutdown()
        await self.messenger.shutdown()

    async def _beacon(self) -> None:
        try:
            await self.messenger.send_message(
                MMDSBeacon(addr=self.messenger.my_addr, rank=self.rank),
                self.mon_addr)
        except (ConnectionError, OSError):
            pass

    async def _beacon_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.config.mds_beacon_interval)
            await self._beacon()
            # converge shared tables across ranks (subtree authority +
            # snap contexts); cheap omap reads
            try:
                await self._load_subtrees()
                await self._load_snaptable()
            except Exception:
                # table convergence retries next beacon; counted so a
                # persistently-failing load is visible in perf dump
                self.perf.inc("mds_table_load_errors")

    # -- journal (MDLog analog) --------------------------------------------

    @property
    def _journal_oid(self) -> str:
        # per-rank journals (reference: each MDSRank owns its own MDLog)
        return f"mds_journal.{self.rank}"

    async def _journal_append(self, seq: int, event: Tuple) -> None:
        """WRITE-AHEAD: the event lands in the journal before any
        dirfrag mutation (journal.cc: EUpdate logged before apply)."""
        io = self._meta_io
        await io.omap_set(self._journal_oid,
                          {f"{seq:016d}": pickle.dumps(event)})

    async def _journal_commit(self, seq: int) -> None:
        """Advance applied-through and TRIM the applied events (MDLog
        segment expiry): the journal holds only the unapplied tail, so
        restart replay is O(tail), not O(all ops ever)."""
        io = self._meta_io
        await io.setxattr(self._journal_oid, "applied", str(seq).encode())
        try:
            events = await io.omap_get(self._journal_oid)
            dead = [k for k in events if int(k) <= seq]
            if dead:
                await io.omap_rmkeys(self._journal_oid, dead)
        except (IOError, FileNotFoundError):
            pass

    async def _journal_state(self) -> Tuple[int, Dict[str, bytes]]:
        io = self._meta_io
        try:
            events = await io.omap_get(self._journal_oid)
        except (IOError, FileNotFoundError):
            events = {}
        try:
            applied = int(await io.getxattr(self._journal_oid, "applied"))
        except (KeyError, IOError, FileNotFoundError, ValueError):
            applied = 0
        return applied, events

    async def _replay_journal(self) -> None:
        """Apply journal events beyond the applied watermark (MDSRank
        replay): a crash between append and apply re-runs the event;
        the dirfrag ops tolerate replays (EEXIST/ENOENT mean the
        event's effect is already present).

        Round-15 hardening (found by the mds-journal-replay scenario):
        a TRANSIENT apply failure (meta-pool op timeout while the
        cluster is still converging) used to be swallowed alongside the
        idempotent-replay errors — the watermark then advanced past the
        never-applied event and the trim ATE IT, silently losing an
        acked metadata op.  Now transient failures retry, and if they
        persist the replay commits only the contiguous applied prefix
        and fails the boot loudly: trim can never pass an unreplayed
        segment, and the next boot replays it again."""
        applied, events = await self._journal_state()
        top = applied
        for key in sorted(events):
            seq = int(key)
            if seq <= applied:
                continue
            event = pickle.loads(events[key])
            self._chaos_point("mds_replay_mid")
            for attempt in range(3):
                try:
                    await self._apply(event)
                    self.perf.inc("mds_journal_replays")
                    break
                except (FileExistsError, FileNotFoundError):
                    break  # replayed event already (partially) applied
                except (IOError, OSError, TimeoutError,
                        ConnectionError):
                    if attempt == 2:
                        if top > applied:
                            await self._journal_commit(top)
                        raise
                    await asyncio.sleep(0.2 * (attempt + 1))
            top = max(top, seq)
        if top > applied:
            await self._journal_commit(top)
        self._seq = top

    async def _apply(self, event: Tuple) -> object:
        op = event[0]
        if op == "mkdir":
            return await self.fs.mkdir(event[1])
        if op == "create":
            return await self.fs.create(event[1])
        if op == "unlink":
            return await self.fs.unlink(event[1])
        if op == "rename":
            return await self.fs.rename(event[1], event[2])
        if op == "set_size":
            return await self.fs.set_size(event[1], event[2])
        raise ValueError(f"unknown journal op {op}")

    # -- request serving ---------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        from ceph_tpu.cluster import messages as _M

        if self._stopped:
            # a crashed rank serves nothing (the power-cut contract);
            # the client's retry loop re-resolves the restarted rank
            return True
        if isinstance(msg, _M.MCommand):
            # 'ceph daemon mds.N ...' admin surface
            result, data = await self.asok.dispatch(msg.cmd)
            await conn.send(_M.MCommandReply(
                tid=msg.tid, result=result, data=data))
            return True
        if not isinstance(msg, MClientRequest):
            return False
        self.perf.inc("mds_requests")
        dup_key = (msg.client, msg.tid)
        try:
            # subtree authority routing (the reference forwards to auth;
            # we bounce with ESTALE + owner hint and the client retargets)
            if msg.op in _ROUTED and msg.args:
                path = str(msg.args[0])
                live, _snap = (path, None)
                if ".snap" in path:
                    live, _snap = self._split_snap_path(path)
                owner = self._owner_rank(live)
                if owner != self.rank:
                    await self._load_subtrees()  # maybe stale: re-check
                    owner = self._owner_rank(live)
                if owner != self.rank:
                    await conn.send(MClientReply(
                        tid=msg.tid, result=-116, error=str(owner)))
                    self.perf.inc("mds_bounced")
                    return True
            if msg.op in _MUTATING:
                # snapshots are a read-only namespace: a literal '.snap'
                # component in a mutation would create a shadowed dentry
                # (the reference returns EPERM from the snap realm check)
                for a in msg.args[:2 if msg.op == "rename" else 1]:
                    if ".snap" in [p for p in str(a).split("/") if p]:
                        await conn.send(MClientReply(
                            tid=msg.tid, result=-1,
                            error=".snap is a reserved name"))
                        return True
            if msg.op == "rename":
                if self._owner_rank(msg.args[0]) != \
                        self._owner_rank(msg.args[1]):
                    # cross-subtree rename needs multi-MDS transactions
                    # (reference slave requests); refused like early
                    # multi-active — copy+unlink instead
                    await conn.send(MClientReply(
                        tid=msg.tid, result=-18,
                        error="cross-subtree rename"))
                    return True
            if msg.op in _MUTATING:
                async with self._lock:     # the MDS serialization point
                    cached = self._completed.get(dup_key)
                    if cached is not None:
                        self.perf.inc("mds_dup_requests")
                        await conn.send(cached)
                        return True
                    # authority can flip while we queued for the lock
                    # (export_dir is lock-serialized too): re-check, or
                    # two ranks could mutate one subtree unserialized
                    if msg.args and self._owner_rank(
                            str(msg.args[0])) != self.rank:
                        await conn.send(MClientReply(
                            tid=msg.tid, result=-116,
                            error=str(self._owner_rank(
                                str(msg.args[0])))))
                        return True
                    self._seq += 1
                    seq = self._seq
                    await self._journal_append(seq, (msg.op,) + msg.args)
                    # journalled but not yet applied: a crash here is
                    # the canonical replay case (append -> apply gap)
                    self._chaos_point("mds_journal_mid")
                    data = await self._apply((msg.op,) + msg.args)
                    await self._journal_commit(seq)
                reply = MClientReply(tid=msg.tid, result=0, data=data)
            elif msg.op == "stat":
                live, rec = self._split_snap_path(str(msg.args[0]))
                if rec == "LIST":
                    raise FileNotFoundError(msg.args[0])
                snapid = rec["meta_id"] if rec else None
                ino = await self.fs.stat(live, snapid=snapid)
                reply = MClientReply(
                    tid=msg.tid, result=0, data=pickle.dumps(ino),
                    lease_ttl=self.lease_ttl,
                    snapc=self._data_snapc(),
                    snapid=rec["data_id"] if rec else None)
            elif msg.op == "listdir":
                live, rec = self._split_snap_path(str(msg.args[0]))
                if rec == "LIST":
                    # '/d/.snap': the dir's snapshot names
                    base = self._norm(live[: -len("/.snap")]
                                      if live.endswith("/.snap") else live)
                    names = sorted(n for n, r in self.snaptable.items()
                                   if r["dir"] == base)
                else:
                    names = await self.fs.listdir(
                        live, snapid=rec["meta_id"] if rec else None)
                reply = MClientReply(tid=msg.tid, result=0, data=names,
                                     lease_ttl=self.lease_ttl)
            elif msg.op in ("snap_create", "snap_rm", "export_dir"):
                # durable admin mutations: dup-cached like journal ops,
                # so a retry after a lost reply gets the ORIGINAL answer
                # instead of a spurious EEXIST/ENOENT
                barrier = 0.0
                async with self._lock:
                    cached = self._completed.get(dup_key)
                    if cached is not None:
                        self.perf.inc("mds_dup_requests")
                        await conn.send(cached)
                        return True
                    if msg.op == "snap_create":
                        data = await self._snap_create(msg.args[0],
                                                       msg.args[1])
                        reply = MClientReply(tid=msg.tid, result=0,
                                             data=data)
                        # lease barrier OUTSIDE the lock: clients cache
                        # stat replies (and their data snapc) up to
                        # lease_ttl, and other ranks adopt the snaptable
                        # on their beacon tick — by reply time every rank
                        # refreshed and every pre-refresh lease expired,
                        # so no write can miss the new COW context (caps
                        # revocation by timeout).  The lock is NOT held:
                        # this rank's own snapc is already installed.
                        barrier = self.lease_ttl + \
                            self.config.mds_beacon_interval
                    elif msg.op == "snap_rm":
                        await self._snap_rm(msg.args[0], msg.args[1])
                        reply = MClientReply(tid=msg.tid, result=0)
                    else:
                        await self._export_dir(msg.args[0],
                                               int(msg.args[1]))
                        reply = MClientReply(tid=msg.tid, result=0)
                    self._completed[dup_key] = reply
                if barrier:
                    await asyncio.sleep(barrier)
            else:
                reply = MClientReply(tid=msg.tid, result=-95,
                                     error=f"bad op {msg.op}")
        except FileExistsError as e:
            reply = MClientReply(tid=msg.tid, result=-17, error=str(e))
        except FileNotFoundError as e:
            reply = MClientReply(tid=msg.tid, result=-2, error=str(e))
        except NotADirectoryError as e:
            reply = MClientReply(tid=msg.tid, result=-20, error=str(e))
        except Exception as e:
            self.perf.inc("mds_errors")
            reply = MClientReply(tid=msg.tid, result=-5, error=repr(e))
        if msg.op in _MUTATING or msg.op in ("snap_create", "snap_rm",
                                             "export_dir"):
            self._completed[dup_key] = reply
            while len(self._completed) > 3000:
                self._completed.popitem(last=False)
        try:
            await conn.send(reply)
        except (ConnectionError, OSError, RuntimeError):
            pass
        return True


class MDSClient:
    """Client-side CephFS surface through the MDS (reference Client.cc):
    metadata ops go to the active MDS (address from the cluster map,
    MDSMap-lite); file DATA rides the striper straight to the OSDs.
    stat/listdir replies carry a read lease — cached until expiry, so
    repeated lookups don't round-trip (Locker caps-lite)."""

    def __init__(self, rados_client, data_pool: int,
                 meta_pool: Optional[int] = None):
        self.client = rados_client
        self.objecter = rados_client.objecter
        self.data_io = rados_client.ioctx(data_pool)
        self.meta_io = rados_client.ioctx(meta_pool) \
            if meta_pool is not None else None
        self._tid = 0
        self._lease: Dict[Tuple, Tuple[float, object]] = {}
        self._subtrees: Dict[str, int] = {"/": 0}

    def _mds_addr(self, rank: int = 0):
        addrs = getattr(self.objecter.osdmap, "mds_addrs", None) or {}
        addr = addrs.get(rank)
        if addr is None and rank == 0:
            addr = getattr(self.objecter.osdmap, "mds_addr", None)
        if addr is None:
            raise ConnectionError(f"no active MDS rank {rank} in the map")
        return tuple(addr)

    def _owner_rank(self, path: str) -> int:
        return owner_rank(self._subtrees, path)

    async def _refresh_subtrees(self) -> None:
        if self.meta_io is None:
            return
        try:
            om = await self.meta_io.omap_get("mds_subtrees")
            self._subtrees = {p: int(r) for p, r in om.items()}
        except (FileNotFoundError, IOError):
            pass

    async def _call(self, op: str, *args, timeout: float = 30.0):
        self._tid += 1
        tid = self._tid
        deadline = asyncio.get_event_loop().time() + timeout
        rank = self._owner_rank(args[0]) if args else 0
        while True:
            # fresh future per attempt: wait_for CANCELS on timeout, and
            # re-awaiting a cancelled future would kill the retry loop
            fut = asyncio.get_event_loop().create_future()
            self.objecter._mds_inflight[tid] = fut
            try:
                await self.objecter.messenger.send_message(
                    MClientRequest(tid=tid,
                                   client=self.objecter.client_name,
                                   op=op, args=tuple(args)),
                    self._mds_addr(rank))
                reply = await asyncio.wait_for(fut, timeout=5.0)
                if reply.result == -116:
                    # wrong rank: adopt the owner hint / fresh subtree
                    # map and retarget (reference forward-to-auth)
                    self.objecter._mds_inflight.pop(tid, None)
                    await self._refresh_subtrees()
                    try:
                        rank = int(reply.error)
                    except (TypeError, ValueError):
                        rank = self._owner_rank(args[0]) if args else 0
                    if asyncio.get_event_loop().time() > deadline:
                        raise TimeoutError(f"mds op {op} kept bouncing")
                    continue
                break
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # MDS restarting: refresh the map for the new address;
                # the MDS dup cache makes the mutating retry safe
                self.objecter._mds_inflight.pop(tid, None)
                if asyncio.get_event_loop().time() > deadline:
                    raise TimeoutError(f"mds op {op} timed out")
                try:
                    await self.objecter._refresh_map()
                    await self._refresh_subtrees()
                    rank = self._owner_rank(args[0]) if args else 0
                except (IOError, OSError, TimeoutError,
                        ConnectionError):
                    pass  # stale map/rank: the retry loop re-resolves
                await asyncio.sleep(0.2)
        if reply.result == -17:
            raise FileExistsError(reply.error)
        if reply.result == -2:
            raise FileNotFoundError(reply.error)
        if reply.result == -20:
            raise NotADirectoryError(reply.error)
        if reply.result == -18:
            raise OSError(18, f"cross-device: {reply.error}")
        if reply.result != 0:
            raise IOError(f"mds {op}: {reply.result} {reply.error}")
        return reply

    # -- metadata surface --------------------------------------------------

    async def mkdir(self, path: str) -> int:
        self._lease.clear()
        return (await self._call("mkdir", path)).data

    async def create(self, path: str) -> int:
        self._lease.clear()
        return (await self._call("create", path)).data

    async def unlink(self, path: str) -> None:
        self._lease.clear()
        await self._call("unlink", path)

    async def rename(self, src: str, dst: str) -> None:
        self._lease.clear()
        await self._call("rename", src, dst)

    async def stat(self, path: str) -> Inode:
        ino, _ = await self._stat_full(path)
        return ino

    async def _stat_full(self, path: str):
        """(inode, snapid) — also adopts the reply's data-pool write
        SnapContext (the caps-carried snapc analog), so subsequent data
        writes COW correctly across fs snapshots."""
        now = time.monotonic()
        hit = self._lease.get(("stat", path))
        if hit is not None and hit[0] > now:
            return hit[1]
        reply = await self._call("stat", path)
        ino = pickle.loads(reply.data)
        if reply.snapc is not None:
            seq, snaps = reply.snapc
            self.data_io.set_snap_context(seq, list(snaps))
        out = (ino, reply.snapid)
        if reply.lease_ttl > 0:
            self._lease[("stat", path)] = (now + reply.lease_ttl, out)
        return out

    # -- snapshots (.snap surface) ------------------------------------------

    async def snap_create(self, dirpath: str, name: str) -> int:
        """mkdir dir/.snap/name analog (reference ceph fs snapshots)."""
        self._lease.clear()
        return (await self._call("snap_create", dirpath, name)).data

    async def snap_rm(self, dirpath: str, name: str) -> None:
        self._lease.clear()
        await self._call("snap_rm", dirpath, name)

    async def export_dir(self, path: str, rank: int) -> None:
        """Move subtree authority to ``rank`` (Migrator::export_dir)."""
        self._lease.clear()
        await self._call("export_dir", path, rank)
        await self._refresh_subtrees()

    async def listdir(self, path: str = "/") -> List[str]:
        now = time.monotonic()
        hit = self._lease.get(("ls", path))
        if hit is not None and hit[0] > now:
            return hit[1]
        reply = await self._call("listdir", path)
        if reply.lease_ttl > 0:
            self._lease[("ls", path)] = (now + reply.lease_ttl, reply.data)
        return reply.data

    # -- data surface (direct to OSDs, reference file I/O semantics) -------

    _DEFAULT_LAYOUT = None

    def _file_layout(self, ino: Inode):
        if ino.layout is not None:
            return ino.layout
        from ceph_tpu.cluster.striper import FileLayout

        return FileLayout(stripe_unit=1 << 16, stripe_count=1,
                          object_size=1 << 20)  # fs.py default layout

    async def write(self, path: str, offset: int, data: bytes) -> None:
        ino, snapid = await self._stat_full(path)
        if snapid is not None:
            raise PermissionError(f"{path}: snapshots are read-only")
        from ceph_tpu.cluster.striper import StripedReader, file_to_extents

        fmt = f"{ino.ino:x}.%016x"   # fs.py FileSystem._fmt layout
        extents = file_to_extents(fmt, self._file_layout(ino),
                                  offset, len(data))
        per_object = StripedReader.scatter(extents, data)
        await asyncio.gather(*[
            self.data_io.write(oid, blob, offset=obj_off)
            for oid, parts in per_object.items()
            for obj_off, blob in parts])
        new_size = max(ino.size, offset + len(data))
        if new_size != ino.size:
            self._lease.pop(("stat", path), None)
            await self._call("set_size", path, new_size)

    async def read(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        ino, snapid = await self._stat_full(path)
        from ceph_tpu.cluster.striper import StripedReader, file_to_extents

        if length is None:
            length = max(0, ino.size - offset)
        length = min(length, max(0, ino.size - offset))
        if length == 0:
            return b""
        fmt = f"{ino.ino:x}.%016x"
        extents = file_to_extents(fmt, self._file_layout(ino),
                                  offset, length)

        async def fetch(ex):
            try:
                return ex.oid, await self.data_io.read(
                    ex.oid, offset=ex.offset, length=ex.length,
                    snapid=snapid)
            except FileNotFoundError:
                return ex.oid, b""

        got = dict(await asyncio.gather(*[fetch(ex) for ex in extents]))
        return StripedReader.assemble(extents, got, length, relative=True)
