"""MDS daemon: the metadata SERVER for the CephFS-analog.

Round 4 (VERDICT r3 item 7): moves fs.py's metadata authority out of the
client library into a daemon, the reference's MDSRank shape
(/root/reference/src/mds/MDSRank.cc): clients send metadata ops
(MClientRequest) to the active MDS, which serializes them, journals them
WRITE-AHEAD into a RADOS-backed metadata journal
(/root/reference/src/mds/journal.cc MDLog analog — an omap event log in
the meta pool), applies them through the cls-atomic dirfrag engine
(cluster/fs.py, kept as the storage layer), and replies with short-TTL
read leases (Locker caps-lite, /root/reference/src/mds/Locker.cc: the
client may cache a lookup until the lease expires; every mutation goes
to the MDS, so two clients always observe a single serialized order).

An MDS restart REPLAYS unapplied journal events before serving
(MDSRank::boot_start replay stage).  The active MDS address rides the
cluster map via beacons (MDSMap-lite, like the mgr's registration).

Not implemented (documented): multi-active subtree partitioning
(Migrator.h:52) — single active MDS, standby takeover by restart.
"""

from __future__ import annotations

import asyncio
import pickle
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.fs import FileSystem, Inode
from ceph_tpu.cluster.messenger import (
    Addr,
    Connection,
    Dispatcher,
    EntityName,
    Messenger,
)
from ceph_tpu.utils import Config, PerfCounters

JOURNAL_OID = "mds_journal.0"


@dataclass
class MClientRequest(M.Message):
    """Client metadata op (reference MClientRequest)."""

    tid: int = 0
    client: str = ""                  # incarnation-unique client identity
    op: str = ""                      # mkdir|create|stat|listdir|...
    args: Tuple = ()


@dataclass
class MClientReply(M.Message):
    tid: int = 0
    result: int = 0
    data: object = None
    error: str = ""
    lease_ttl: float = 0.0            # read-cacheable until now+ttl


@dataclass
class MMDSBeacon(M.Message):
    """MDS -> mon registration (reference MMDSBeacon)."""

    addr: Optional[Tuple] = None


# journal ops that mutate dirfrag state (everything except pure reads)
_MUTATING = {"mkdir", "create", "unlink", "rename", "set_size"}


class MDSDaemon(Dispatcher):
    def __init__(self, mon_addr, meta_pool: int, data_pool: int,
                 config: Optional[Config] = None, rank: int = 0):
        self.rank = rank
        self.config = Config(**config.show()) if config else Config()
        self.messenger = Messenger(
            EntityName("mds", rank),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"mds.{rank}"))
        self.messenger.add_dispatcher(self)
        self.mon_addr = mon_addr
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.perf = PerfCounters(f"mds.{rank}")
        self._client = None               # our own RADOS client
        self.fs: Optional[FileSystem] = None
        self._lock = asyncio.Lock()       # the single-MDS big lock
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        self.lease_ttl = self.config.mds_lease_ttl
        # completed-request cache (the OSD reqid dup cache's MDS twin,
        # reference MDCache request dedup): a client retry of a mutating
        # op whose reply was merely delayed gets the ORIGINAL reply
        # instead of a spurious EEXIST/ENOENT re-execution
        from collections import OrderedDict as _OD

        self._completed: "_OD[Tuple[str, int], MClientReply]" = _OD()

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        from ceph_tpu.cluster.objecter import RadosClient

        addr = await self.messenger.bind(host, port)
        self._client = RadosClient(self.mon_addr, name=f"mds{self.rank}",
                                   config=self.config)
        await self._client.connect()
        meta_io = self._client.ioctx(self.meta_pool)
        data_io = self._client.ioctx(self.data_pool)
        self.fs = FileSystem(meta_io, data_io)
        try:
            await self.fs.stat("/")
        except FileNotFoundError:
            await self.fs.mkfs()
        await self._replay_journal()
        await self._beacon()
        loop = asyncio.get_event_loop()
        self._tasks.append(loop.create_task(self._beacon_loop()))
        return addr

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        if self._client is not None:
            await self._client.shutdown()
        await self.messenger.shutdown()

    async def _beacon(self) -> None:
        try:
            await self.messenger.send_message(
                MMDSBeacon(addr=self.messenger.my_addr), self.mon_addr)
        except (ConnectionError, OSError):
            pass

    async def _beacon_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.config.mds_beacon_interval)
            await self._beacon()

    # -- journal (MDLog analog) --------------------------------------------

    async def _journal_append(self, seq: int, event: Tuple) -> None:
        """WRITE-AHEAD: the event lands in the journal before any
        dirfrag mutation (journal.cc: EUpdate logged before apply)."""
        io = self._client.ioctx(self.meta_pool)
        await io.omap_set(JOURNAL_OID,
                          {f"{seq:016d}": pickle.dumps(event)})

    async def _journal_commit(self, seq: int) -> None:
        """Advance applied-through and TRIM the applied events (MDLog
        segment expiry): the journal holds only the unapplied tail, so
        restart replay is O(tail), not O(all ops ever)."""
        io = self._client.ioctx(self.meta_pool)
        await io.setxattr(JOURNAL_OID, "applied", str(seq).encode())
        try:
            events = await io.omap_get(JOURNAL_OID)
            dead = [k for k in events if int(k) <= seq]
            if dead:
                await io.omap_rmkeys(JOURNAL_OID, dead)
        except (IOError, FileNotFoundError):
            pass

    async def _journal_state(self) -> Tuple[int, Dict[str, bytes]]:
        io = self._client.ioctx(self.meta_pool)
        try:
            events = await io.omap_get(JOURNAL_OID)
        except (IOError, FileNotFoundError):
            events = {}
        try:
            applied = int(await io.getxattr(JOURNAL_OID, "applied"))
        except (KeyError, IOError, FileNotFoundError, ValueError):
            applied = 0
        return applied, events

    async def _replay_journal(self) -> None:
        """Apply journal events beyond the applied watermark (MDSRank
        replay): a crash between append and apply re-runs the event;
        the dirfrag ops tolerate replays (EEXIST/ENOENT are fine)."""
        applied, events = await self._journal_state()
        top = applied
        for key in sorted(events):
            seq = int(key)
            if seq <= applied:
                continue
            event = pickle.loads(events[key])
            try:
                await self._apply(event)
                self.perf.inc("mds_journal_replays")
            except (FileExistsError, FileNotFoundError, IOError):
                pass  # replayed event already (partially) applied
            top = max(top, seq)
        if top > applied:
            await self._journal_commit(top)
        self._seq = top

    async def _apply(self, event: Tuple) -> object:
        op = event[0]
        if op == "mkdir":
            return await self.fs.mkdir(event[1])
        if op == "create":
            return await self.fs.create(event[1])
        if op == "unlink":
            return await self.fs.unlink(event[1])
        if op == "rename":
            return await self.fs.rename(event[1], event[2])
        if op == "set_size":
            return await self.fs.set_size(event[1], event[2])
        raise ValueError(f"unknown journal op {op}")

    # -- request serving ---------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if not isinstance(msg, MClientRequest):
            return False
        self.perf.inc("mds_requests")
        dup_key = (msg.client, msg.tid)
        try:
            if msg.op in _MUTATING:
                async with self._lock:     # the MDS serialization point
                    cached = self._completed.get(dup_key)
                    if cached is not None:
                        self.perf.inc("mds_dup_requests")
                        await conn.send(cached)
                        return True
                    self._seq += 1
                    seq = self._seq
                    await self._journal_append(seq, (msg.op,) + msg.args)
                    data = await self._apply((msg.op,) + msg.args)
                    await self._journal_commit(seq)
                reply = MClientReply(tid=msg.tid, result=0, data=data)
            elif msg.op == "stat":
                ino = await self.fs.stat(msg.args[0])
                reply = MClientReply(tid=msg.tid, result=0,
                                     data=pickle.dumps(ino),
                                     lease_ttl=self.lease_ttl)
            elif msg.op == "listdir":
                names = await self.fs.listdir(msg.args[0])
                reply = MClientReply(tid=msg.tid, result=0, data=names,
                                     lease_ttl=self.lease_ttl)
            else:
                reply = MClientReply(tid=msg.tid, result=-95,
                                     error=f"bad op {msg.op}")
        except FileExistsError as e:
            reply = MClientReply(tid=msg.tid, result=-17, error=str(e))
        except FileNotFoundError as e:
            reply = MClientReply(tid=msg.tid, result=-2, error=str(e))
        except NotADirectoryError as e:
            reply = MClientReply(tid=msg.tid, result=-20, error=str(e))
        except Exception as e:
            self.perf.inc("mds_errors")
            reply = MClientReply(tid=msg.tid, result=-5, error=repr(e))
        if msg.op in _MUTATING:
            self._completed[dup_key] = reply
            while len(self._completed) > 3000:
                self._completed.popitem(last=False)
        try:
            await conn.send(reply)
        except (ConnectionError, OSError, RuntimeError):
            pass
        return True


class MDSClient:
    """Client-side CephFS surface through the MDS (reference Client.cc):
    metadata ops go to the active MDS (address from the cluster map,
    MDSMap-lite); file DATA rides the striper straight to the OSDs.
    stat/listdir replies carry a read lease — cached until expiry, so
    repeated lookups don't round-trip (Locker caps-lite)."""

    def __init__(self, rados_client, data_pool: int):
        self.client = rados_client
        self.objecter = rados_client.objecter
        self.data_io = rados_client.ioctx(data_pool)
        self._tid = 0
        self._lease: Dict[Tuple, Tuple[float, object]] = {}

    def _mds_addr(self):
        addr = getattr(self.objecter.osdmap, "mds_addr", None)
        if addr is None:
            raise ConnectionError("no active MDS in the cluster map")
        return tuple(addr)

    async def _call(self, op: str, *args, timeout: float = 30.0):
        self._tid += 1
        tid = self._tid
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            # fresh future per attempt: wait_for CANCELS on timeout, and
            # re-awaiting a cancelled future would kill the retry loop
            fut = asyncio.get_event_loop().create_future()
            self.objecter._mds_inflight[tid] = fut
            try:
                await self.objecter.messenger.send_message(
                    MClientRequest(tid=tid,
                                   client=self.objecter.client_name,
                                   op=op, args=tuple(args)),
                    self._mds_addr())
                reply = await asyncio.wait_for(fut, timeout=5.0)
                break
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # MDS restarting: refresh the map for the new address;
                # the MDS dup cache makes the mutating retry safe
                self.objecter._mds_inflight.pop(tid, None)
                if asyncio.get_event_loop().time() > deadline:
                    raise TimeoutError(f"mds op {op} timed out")
                try:
                    await self.objecter._refresh_map()
                except Exception:
                    pass
                await asyncio.sleep(0.2)
        if reply.result == -17:
            raise FileExistsError(reply.error)
        if reply.result == -2:
            raise FileNotFoundError(reply.error)
        if reply.result == -20:
            raise NotADirectoryError(reply.error)
        if reply.result != 0:
            raise IOError(f"mds {op}: {reply.result} {reply.error}")
        return reply

    # -- metadata surface --------------------------------------------------

    async def mkdir(self, path: str) -> int:
        self._lease.clear()
        return (await self._call("mkdir", path)).data

    async def create(self, path: str) -> int:
        self._lease.clear()
        return (await self._call("create", path)).data

    async def unlink(self, path: str) -> None:
        self._lease.clear()
        await self._call("unlink", path)

    async def rename(self, src: str, dst: str) -> None:
        self._lease.clear()
        await self._call("rename", src, dst)

    async def stat(self, path: str) -> Inode:
        now = time.monotonic()
        hit = self._lease.get(("stat", path))
        if hit is not None and hit[0] > now:
            return hit[1]
        reply = await self._call("stat", path)
        ino = pickle.loads(reply.data)
        if reply.lease_ttl > 0:
            self._lease[("stat", path)] = (now + reply.lease_ttl, ino)
        return ino

    async def listdir(self, path: str = "/") -> List[str]:
        now = time.monotonic()
        hit = self._lease.get(("ls", path))
        if hit is not None and hit[0] > now:
            return hit[1]
        reply = await self._call("listdir", path)
        if reply.lease_ttl > 0:
            self._lease[("ls", path)] = (now + reply.lease_ttl, reply.data)
        return reply.data

    # -- data surface (direct to OSDs, reference file I/O semantics) -------

    _DEFAULT_LAYOUT = None

    def _file_layout(self, ino: Inode):
        if ino.layout is not None:
            return ino.layout
        from ceph_tpu.cluster.striper import FileLayout

        return FileLayout(stripe_unit=1 << 16, stripe_count=1,
                          object_size=1 << 20)  # fs.py default layout

    async def write(self, path: str, offset: int, data: bytes) -> None:
        ino = await self.stat(path)
        from ceph_tpu.cluster.striper import StripedReader, file_to_extents

        fmt = f"{ino.ino:x}.%016x"   # fs.py FileSystem._fmt layout
        extents = file_to_extents(fmt, self._file_layout(ino),
                                  offset, len(data))
        per_object = StripedReader.scatter(extents, data)
        await asyncio.gather(*[
            self.data_io.write(oid, blob, offset=obj_off)
            for oid, parts in per_object.items()
            for obj_off, blob in parts])
        new_size = max(ino.size, offset + len(data))
        if new_size != ino.size:
            self._lease.pop(("stat", path), None)
            await self._call("set_size", path, new_size)

    async def read(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        ino = await self.stat(path)
        from ceph_tpu.cluster.striper import StripedReader, file_to_extents

        if length is None:
            length = max(0, ino.size - offset)
        length = min(length, max(0, ino.size - offset))
        if length == 0:
            return b""
        fmt = f"{ino.ino:x}.%016x"
        extents = file_to_extents(fmt, self._file_layout(ino),
                                  offset, length)

        async def fetch(ex):
            try:
                return ex.oid, await self.data_io.read(
                    ex.oid, offset=ex.offset, length=ex.length)
            except FileNotFoundError:
                return ex.oid, b""

        got = dict(await asyncio.gather(*[fetch(ex) for ex in extents]))
        return StripedReader.assemble(extents, got, length, relative=True)
