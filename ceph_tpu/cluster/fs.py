"""FileSystem: a POSIX-ish file layer over RADOS (CephFS analog).

Behavioral analog of the reference's CephFS core shape (src/mds/ +
src/client/): file DATA is striped over a data pool by the same Striper
layout files share with RBD (file_layout_t, src/include/fs_types.h:84),
while METADATA — the directory tree, dentries, inodes — lives in a
metadata pool as omap-backed directory objects (the reference MDS stores
dirfrags exactly this way: one omap entry per dentry).  The "MDS" here
is a library-side metadata service over IoCtx ops (single-writer
semantics per directory object come from the OSD's per-PG ordering);
subtree partitioning across MDS ranks is future work.

Inodes: pickled dataclasses in the dentry omap value.  Data objects:
"<ino>.%016x" like the reference's file objects.
"""

from __future__ import annotations

import asyncio
import pickle
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ceph_tpu.cluster.objecter import IoCtx
from ceph_tpu.cluster.striper import (
    FileLayout,
    StripedReader,
    file_to_extents,
)

ROOT_INO = 1


@dataclass
class Inode:
    """inode_t subset (reference mdstypes)."""

    ino: int
    mode: str                  # "dir" | "file"
    size: int = 0
    layout: Optional[FileLayout] = None
    mtime: float = 0.0


class FileSystem:
    """Mount-like handle (reference client/Client.cc surface subset)."""

    def __init__(self, meta_ioctx: IoCtx, data_ioctx: IoCtx,
                 layout: Optional[FileLayout] = None):
        self.meta = meta_ioctx
        self.data = data_ioctx
        self.layout = layout or FileLayout(
            stripe_unit=1 << 16, stripe_count=1, object_size=1 << 20)

    # -- metadata primitives ------------------------------------------------

    @staticmethod
    def _dir_oid(ino: int) -> str:
        return f"dir.{ino:x}"

    async def mkfs(self) -> None:
        """Create the root directory object (reference: mds newfs)."""
        await self.meta.write_full(self._dir_oid(ROOT_INO),
                                   pickle.dumps(Inode(ROOT_INO, "dir")))
        await self.meta.omap_set("meta.next_ino",
                                 {"next": str(ROOT_INO + 1).encode()})

    async def _alloc_ino(self) -> int:
        # ino allocator in the meta pool (reference inotable): the
        # read-increment-write runs INSIDE the OSD via the object-class
        # seam, atomic under the PG lock — concurrent creates can never
        # collide
        out = await self.meta.execute("meta.next_ino", "inotable", "alloc")
        return int(out)

    async def _lookup_dir(self, path: str,
                          snapid: Optional[int] = None) -> Tuple[int, str]:
        """Resolve the parent directory of ``path``; returns
        (parent_ino, leaf_name).  ``snapid`` walks the dirfrags as they
        were at that (meta-pool) snapshot — the CephFS .snap read path."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise IsADirectoryError("/")
        ino = ROOT_INO
        for name in parts[:-1]:
            entries = await self.meta.omap_get(self._dir_oid(ino),
                                               snapid=snapid)
            blob = entries.get(name)
            if blob is None:
                raise FileNotFoundError(f"{name} in {path}")
            inode: Inode = pickle.loads(blob)
            if inode.mode != "dir":
                raise NotADirectoryError(name)
            ino = inode.ino
        return ino, parts[-1]

    async def _resolve(self, path: str,
                       snapid: Optional[int] = None
                       ) -> Tuple[int, str, Inode]:
        """ONE walk: (parent_ino, leaf, inode) — callers must not re-walk
        (each component costs an omap round trip)."""
        parent, leaf = await self._lookup_dir(path, snapid=snapid)
        entries = await self.meta.omap_get(self._dir_oid(parent),
                                           snapid=snapid)
        blob = entries.get(leaf)
        if blob is None:
            raise FileNotFoundError(path)
        return parent, leaf, pickle.loads(blob)

    async def _get(self, path: str,
                   snapid: Optional[int] = None) -> Inode:
        if path.strip("/") == "":
            return Inode(ROOT_INO, "dir")
        return (await self._resolve(path, snapid=snapid))[2]

    async def _set_dentry(self, parent: int, name: str,
                          inode: Inode) -> None:
        await self.meta.omap_set(self._dir_oid(parent),
                                 {name: pickle.dumps(inode)})

    async def set_size(self, path: str, size: int) -> None:
        """Extend a file inode's size (the MDS applies this for client
        data writes — the caps writeback analog).  GROW-ONLY: the value
        is computed from the writer's possibly lease-stale stat, so a
        blind absolute write could truncate a concurrent writer's
        committed extension; max() keeps size-writeback monotonic (the
        reference orders size changes through the Locker for the same
        reason).  Explicit truncation would be its own op."""
        import time as _time

        parent, leaf, inode = await self._resolve(path)
        if inode.mode != "file":
            raise IsADirectoryError(path)
        inode.size = max(inode.size, size)
        inode.mtime = _time.time()
        await self._set_dentry(parent, leaf, inode)

    # -- namespace ops ------------------------------------------------------

    async def _link_dentry(self, parent: int, leaf: str,
                           inode: Inode, path: str) -> None:
        """Create-exclusive dentry insert through the object-class seam:
        the check-then-set runs INSIDE the OSD under PG serialization, so
        concurrent creates of one path cannot both succeed."""
        try:
            await self.meta.execute(
                self._dir_oid(parent), "dirfrag", "link",
                pickle.dumps({"name": leaf,
                              "value": pickle.dumps(inode)}))
        except IOError as e:
            if "-17" in str(e):  # EEXIST from the class method
                raise FileExistsError(path) from None
            raise

    async def mkdir(self, path: str) -> int:
        parent, leaf = await self._lookup_dir(path)
        ino = await self._alloc_ino()
        await self.meta.write_full(self._dir_oid(ino),
                                   pickle.dumps(Inode(ino, "dir")))
        await self._link_dentry(parent, leaf, Inode(ino, "dir"), path)
        return ino

    async def create(self, path: str,
                     layout: Optional[FileLayout] = None) -> int:
        parent, leaf = await self._lookup_dir(path)
        ino = await self._alloc_ino()
        inode = Inode(ino, "file", size=0,
                      layout=layout or self.layout, mtime=time.time())
        await self._link_dentry(parent, leaf, inode, path)
        return ino

    async def listdir(self, path: str = "/",
                      snapid: Optional[int] = None) -> List[str]:
        inode = await self._get(path, snapid=snapid)
        if inode.mode != "dir":
            raise NotADirectoryError(path)
        return sorted(await self.meta.omap_get(self._dir_oid(inode.ino),
                                               snapid=snapid))

    async def stat(self, path: str,
                   snapid: Optional[int] = None) -> Inode:
        return await self._get(path, snapid=snapid)

    async def unlink(self, path: str) -> None:
        parent, leaf, inode = await self._resolve(path)
        if inode.mode == "dir":
            if await self.meta.omap_get(self._dir_oid(inode.ino)):
                raise OSError(39, "directory not empty", path)
            await self.meta.remove(self._dir_oid(inode.ino))
        else:
            await self._purge_data(inode)
        await self.meta.omap_rmkeys(self._dir_oid(parent), [leaf])

    async def rename(self, src: str, dst: str) -> None:
        sparent, sleaf, inode = await self._resolve(src)
        dparent, dleaf = await self._lookup_dir(dst)
        if (sparent, sleaf) == (dparent, dleaf):
            return  # POSIX: rename onto itself is a no-op
        existing = (await self.meta.omap_get(
            self._dir_oid(dparent))).get(dleaf)
        if existing is not None:
            # POSIX: replacing a file purges it; a directory must be empty
            old: Inode = pickle.loads(existing)
            if old.mode == "dir":
                if await self.meta.omap_get(self._dir_oid(old.ino)):
                    raise OSError(39, "directory not empty", dst)
                await self.meta.remove(self._dir_oid(old.ino))
            else:
                await self._purge_data(old)
        await self._set_dentry(dparent, dleaf, inode)
        await self.meta.omap_rmkeys(self._dir_oid(sparent), [sleaf])

    # -- file I/O -----------------------------------------------------------

    def _fmt(self, ino: int) -> str:
        return f"{ino:x}.%016x"

    async def write(self, path: str, offset: int, data: bytes) -> None:
        parent, leaf, inode = await self._resolve(path)
        if inode.mode != "file":
            raise IsADirectoryError(path)
        layout = inode.layout or self.layout
        extents = file_to_extents(self._fmt(inode.ino), layout,
                                  offset, len(data))
        per_object = StripedReader.scatter(extents, data)
        await asyncio.gather(*[
            self.data.write(oid, blob, offset=obj_off)
            for oid, parts in per_object.items()
            for obj_off, blob in parts])
        if offset + len(data) > inode.size:
            inode.size = offset + len(data)
        inode.mtime = time.time()
        await self._set_dentry(parent, leaf, inode)

    async def read(self, path: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        inode = await self._get(path)
        if inode.mode != "file":
            raise IsADirectoryError(path)
        if length is None:
            length = max(0, inode.size - offset)
        length = min(length, max(0, inode.size - offset))
        if length == 0:
            return b""
        layout = inode.layout or self.layout
        extents = file_to_extents(self._fmt(inode.ino), layout,
                                  offset, length)

        async def fetch(ex):
            try:
                return ex.oid, await self.data.read(
                    ex.oid, offset=ex.offset, length=ex.length)
            except FileNotFoundError:
                return ex.oid, b""

        got = dict(await asyncio.gather(*[fetch(ex) for ex in extents]))
        return StripedReader.assemble(extents, got, length, relative=True)

    async def _purge_data(self, inode: Inode) -> None:
        layout = inode.layout or self.layout
        period = layout.object_size * layout.stripe_count
        n_sets = (inode.size + period - 1) // period
        for objno in range(n_sets * layout.stripe_count):
            try:
                await self.data.remove(self._fmt(inode.ino) % objno)
            except FileNotFoundError:
                pass  # sparse/never-written object; real errors propagate
