"""RGW-lite: an S3-shaped object gateway over RADOS.

Behavioral analog of the reference radosgw core data model (src/rgw/):
buckets are omap-backed index objects (one entry per key, exactly how
cls_rgw maintains bucket indexes), object payloads live in the data pool
via the librados surface, and the API mirrors the S3 verbs the reference
gateway serves — create/delete bucket, put/get/head/delete object,
prefix+marker listing with truncation, and basic user metadata.  The
HTTP frontend (civetweb/Beast in the reference) is out of scope; this is
the gateway's storage core as a library.
"""

from __future__ import annotations

import hashlib
import pickle
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.utils.deadline import deadline_of, remaining

from ceph_tpu.cluster.objecter import IoCtx


def _chaos(io: IoCtx, name: str) -> None:
    """Client-library chaos seam (round 15): interrupt this gateway op
    AT THIS INSTANT when the client config arms ``name`` (the gateway
    process "died" mid-transaction; reclaim_multipart is the recovery
    pass).  One falsy test when unarmed — the no-op contract."""
    if not io.objecter.config.chaos_crash_point:
        return
    from ceph_tpu.chaos.points import maybe_interrupt

    maybe_interrupt(io.objecter.config, name)


@dataclass
class ObjectMeta:
    """Bucket-index entry (cls_rgw rgw_bucket_dir_entry analog)."""

    key: str
    size: int
    etag: str
    mtime: float
    content_type: str = "application/octet-stream"
    user_meta: Dict[str, str] = field(default_factory=dict)


@dataclass
class ListResult:
    keys: List[ObjectMeta]
    is_truncated: bool
    next_marker: Optional[str]


class RGW:
    """Gateway handle (the radosgw storage core as a library).

    ``zone`` names this gateway's zone for multisite sync (reference
    rgw_zone): every index mutation also appends to the bucket's index
    LOG (cls_rgw bilog analog) and registers the bucket in the zone
    datalog, which RGWSyncAgent (rgw_sync.py) replays into peer zones.
    """

    def __init__(self, ioctx: IoCtx, zone: str = "default"):
        self.ioctx = ioctx
        self.zone = zone
        # gateway telemetry + admin surface (reference radosgw perf
        # counters 'rgw.*' + its admin socket): the frontend and sync
        # agent share this gateway's counters
        from ceph_tpu.utils import AdminSocket, PerfCounters
        from ceph_tpu.utils import perf as perfmod

        self.perf = PerfCounters(f"rgw.{zone}")
        self.perf.add_u64("rgw_put", desc="object puts")
        self.perf.add_u64("rgw_get", desc="object gets")
        self.perf.add_u64("rgw_mp_created", desc="multipart initiates")
        self.perf.add_u64("rgw_mp_parts", desc="multipart parts recorded")
        self.perf.add_u64("rgw_mp_completed", desc="multipart completes")
        self.perf.add_u64("rgw_mp_aborted", desc="multipart aborts")
        self.perf.add_u64("rgw_mp_rolled_forward",
                          desc="interrupted completes finished by reclaim")
        self.perf.add_u64("rgw_mp_orphan_parts",
                          desc="orphaned part objects garbage-collected")
        self.perf.add_u64("rgw_index_repaired",
                          desc="index entries dropped for missing payloads")
        self.perf.add_histogram(
            "rgw_obj_bytes_hist", unit=perfmod.UNIT_BYTES,
            desc="object payload size, log2 byte buckets")
        self.asok = AdminSocket()
        self.asok.register_common(self.perf)

    BUCKETS_OID = ".buckets.list"   # registry of buckets (omap)
    DATALOG_OID = ".datalog"        # bucket -> latest bilog seq (omap)
    BILOG_MAX = 1000                # trimmed window; older -> full sync

    @staticmethod
    def _index_oid(bucket: str) -> str:
        return f".bucket.index.{bucket}"

    @staticmethod
    def _bilog_oid(bucket: str) -> str:
        return f".bucket.log.{bucket}"

    # -- bucket index log (bilog) -------------------------------------------

    async def _bilog_append(self, bucket: str, op: str, key: str,
                            origin: Optional[str] = None) -> None:
        """Append one change record (reference cls_rgw bilog entry) and
        bump the bucket's datalog cursor.  ``origin`` is the zone the
        change FIRST happened in — the sync agent skips entries that
        originated in its own destination, which is what breaks the
        active-active echo loop."""
        log_oid = self._bilog_oid(bucket)
        entry = pickle.dumps({"op": op, "key": key,
                              "origin": origin or self.zone,
                              "stamp": time.time()})
        # cls-atomic append (cls_rgw bilog semantics): the exec txn
        # touches (auto-creates) the log object; seq allocation +
        # entry + trim run as one transaction under PG serialization, so
        # concurrent index mutations never collide or lose entries
        seq = int(await self.ioctx.execute(
            log_oid, "rgw_bilog", "append",
            pickle.dumps({"entry": entry, "max": self.BILOG_MAX})))
        await self.ioctx.omap_set(self.DATALOG_OID,
                                  {bucket: str(seq).encode()})

    async def bilog_window(self, bucket: str) -> Tuple[int, int]:
        """(tail, head) seq bounds of the retained log (0, 0) = empty."""
        log_oid = self._bilog_oid(bucket)
        try:
            head = int(await self.ioctx.getxattr(log_oid, "bilog.head"))
        except (KeyError, FileNotFoundError, IOError):
            return 0, 0
        try:
            tail = int(await self.ioctx.getxattr(log_oid, "bilog.tail"))
        except (KeyError, FileNotFoundError, IOError):
            tail = 0
        return tail, head

    async def bilog_entries(self, bucket: str, after: int) -> List[Tuple[int, Dict]]:
        """Entries with seq > after, oldest first."""
        try:
            om = await self.ioctx.omap_get(self._bilog_oid(bucket))
        except (FileNotFoundError, IOError):
            return []
        out = []
        for k, blob in sorted(om.items()):
            seq = int(k)
            if seq > after:
                out.append((seq, pickle.loads(blob)))
        return out

    async def datalog(self) -> Dict[str, int]:
        """bucket -> latest change seq (reference data changes log)."""
        try:
            om = await self.ioctx.omap_get(self.DATALOG_OID)
        except (FileNotFoundError, IOError):
            return {}
        return {b: int(v) for b, v in om.items()}

    @staticmethod
    def _data_oid(bucket: str, key: str) -> str:
        # length-prefixed: unambiguous for ANY bucket/key bytes (S3 keys
        # may contain any separator we could pick)
        return f"{len(bucket)}:{bucket}:{key}"

    # -- buckets ------------------------------------------------------------

    async def create_bucket(self, bucket: str) -> None:
        try:
            await self.ioctx.stat(self._index_oid(bucket))
            raise FileExistsError(bucket)
        except FileNotFoundError:
            pass
        await self.ioctx.write_full(self._index_oid(bucket),
                                    pickle.dumps({"created": time.time()}))
        await self.ioctx.omap_set(self.BUCKETS_OID, {bucket: b"1"})

    async def delete_bucket(self, bucket: str) -> None:
        idx = await self._index(bucket)
        if idx:
            raise OSError(39, "bucket not empty", bucket)
        await self.ioctx.remove(self._index_oid(bucket))
        await self.ioctx.omap_rmkeys(self.BUCKETS_OID, [bucket])

    async def list_buckets(self) -> List[str]:
        # O(buckets) via the registry omap, not O(pool objects)
        try:
            return sorted(await self.ioctx.omap_get(self.BUCKETS_OID))
        except (FileNotFoundError, IOError):
            return []

    async def _index(self, bucket: str,
                     timeout: float = None) -> Dict[str, bytes]:
        dl = deadline_of(timeout)
        try:
            await self.ioctx.stat(self._index_oid(bucket),
                                  timeout=remaining(dl))
        except FileNotFoundError:
            raise FileNotFoundError(f"bucket {bucket}")
        return await self.ioctx.omap_get(self._index_oid(bucket),
                                         timeout=remaining(dl))

    # -- objects ------------------------------------------------------------

    async def put_object(self, bucket: str, key: str, data: bytes,
                         content_type: str = "application/octet-stream",
                         user_meta: Optional[Dict[str, str]] = None,
                         origin: Optional[str] = None,
                         meta: Optional[ObjectMeta] = None,
                         timeout: float = None) -> str:
        """``origin``/``meta`` are the multisite apply path: the sync
        agent preserves the source zone's metadata (etag/mtime) and
        stamps the entry's TRUE origin for echo suppression."""
        dl = deadline_of(timeout)
        try:
            await self.ioctx.stat(self._index_oid(bucket),
                                  timeout=remaining(dl))  # must exist
        except FileNotFoundError:
            raise FileNotFoundError(f"bucket {bucket}")
        if meta is None:
            etag = hashlib.md5(data).hexdigest()
            meta = ObjectMeta(key=key, size=len(data), etag=etag,
                              mtime=time.time(),
                              content_type=content_type,
                              user_meta=dict(user_meta or {}))
        await self.ioctx.write_full(self._data_oid(bucket, key), data,
                                    timeout=remaining(dl))
        self.perf.inc("rgw_put")
        self.perf.hinc("rgw_obj_bytes_hist", len(data))
        # index update AFTER the payload lands (cls_rgw prepares/completes
        # around the data write for the same reason)
        await self.ioctx.omap_set(self._index_oid(bucket),
                                  {key: pickle.dumps(meta)},
                                  timeout=remaining(dl))
        await self._bilog_append(bucket, "put", key, origin)
        return meta.etag

    async def head_object(self, bucket: str, key: str,
                          timeout: float = None) -> ObjectMeta:
        idx = await self._index(bucket, timeout=timeout)
        blob = idx.get(key)
        if blob is None:
            raise FileNotFoundError(f"{bucket}/{key}")
        return pickle.loads(blob)

    async def get_object(self, bucket: str, key: str,
                         timeout: float = None
                         ) -> Tuple[ObjectMeta, bytes]:
        dl = deadline_of(timeout)
        meta = await self.head_object(bucket, key, timeout=remaining(dl))
        data = await self.ioctx.read(self._data_oid(bucket, key),
                                     timeout=remaining(dl))
        self.perf.inc("rgw_get")
        return meta, data

    async def delete_object(self, bucket: str, key: str,
                            origin: Optional[str] = None,
                            timeout: float = None) -> None:
        dl = deadline_of(timeout)
        await self.head_object(bucket, key,
                               timeout=remaining(dl))  # 404 when absent
        await self.ioctx.remove(self._data_oid(bucket, key),
                                timeout=remaining(dl))
        await self.ioctx.omap_rmkeys(self._index_oid(bucket), [key],
                                     timeout=remaining(dl))
        await self._bilog_append(bucket, "delete", key, origin)

    # -- multipart uploads --------------------------------------------------
    #
    # Reference rgw_op.cc RGWInitMultipart / RGWPutObj (part) /
    # RGWCompleteMultipart / RGWAbortMultipart, made DURABLE: the upload
    # registry is an omap object in RADOS (one record per in-flight
    # upload: key, recorded parts, state machine open -> completing |
    # aborting), part payloads are ordinary pool objects, and every
    # multi-step transition writes its intent record FIRST — so a
    # gateway process dying at any named seam leaves a state
    # ``reclaim_multipart`` can always finish:
    #
    #   rgw_part_mid      part payload landed, registry not updated ->
    #                     an orphaned part object (reclaim GCs it)
    #   rgw_complete_mid  final payload landed, bucket index not
    #                     updated -> the object is INVISIBLE (complete
    #                     is all-or-nothing); the 'completing' record
    #                     lets reclaim roll the complete FORWARD
    #   rgw_abort_mid     'aborting' record written, parts not yet
    #                     deleted -> reclaim finishes the abort
    #
    # Part objects and registry records never collide with client keys:
    # both live under dot-prefixed, length-prefixed names like the
    # bucket index itself.

    @staticmethod
    def _uploads_oid(bucket: str) -> str:
        return f".uploads.{len(bucket)}:{bucket}"

    @staticmethod
    def _mp_prefix(bucket: str) -> str:
        return f".mp.{len(bucket)}:{bucket}:"

    @classmethod
    def _mp_part_oid(cls, bucket: str, upload_id: str, n: int) -> str:
        return f"{cls._mp_prefix(bucket)}{upload_id}.{int(n):05d}"

    async def _mp_record(self, bucket: str, upload_id: str,
                         timeout: float = None) -> Dict:
        try:
            om = await self.ioctx.omap_get(self._uploads_oid(bucket),
                                           timeout=timeout)
        except (FileNotFoundError, IOError):
            raise FileNotFoundError(f"upload {upload_id}")
        blob = om.get(upload_id)
        if blob is None:
            raise FileNotFoundError(f"upload {upload_id}")
        return pickle.loads(blob)

    async def _mp_save(self, bucket: str, upload_id: str, rec: Dict,
                       timeout: float = None) -> None:
        await self.ioctx.omap_set(self._uploads_oid(bucket),
                                  {upload_id: pickle.dumps(rec)},
                                  timeout=timeout)

    async def create_multipart(self, bucket: str, key: str,
                               timeout: float = None) -> str:
        """InitMultipartUpload: allocate an id (cls-atomic counter) and
        write the durable 'open' record.  Until complete lands the
        index, the key stays invisible."""
        dl = deadline_of(timeout)
        try:
            await self.ioctx.stat(self._index_oid(bucket),
                                  timeout=remaining(dl))
        except FileNotFoundError:
            raise FileNotFoundError(f"bucket {bucket}")
        seq = int(await self.ioctx.execute(
            self._uploads_oid(bucket), "rgw_mp", "alloc",
            timeout=remaining(dl)))
        upload_id = f"{seq:06d}{hashlib.md5(key.encode()).hexdigest()[:8]}"
        rec = {"key": key, "state": "open", "parts": {},
               "started": time.time()}
        await self._mp_save(bucket, upload_id, rec,
                            timeout=remaining(dl))
        self.perf.inc("rgw_mp_created")
        return upload_id

    async def upload_part(self, bucket: str, key: str, upload_id: str,
                          part_num: int, data: bytes,
                          timeout: float = None) -> str:
        """UploadPart: part payload first, registry record second — a
        crash between the two (``rgw_part_mid``) orphans the payload
        object, which is exactly what the reclaim pass garbage-collects
        (an unrecorded part never happened, S3 semantics).  Re-uploading
        a part number overwrites (last write wins, as in S3)."""
        dl = deadline_of(timeout)
        rec = await self._mp_record(bucket, upload_id,
                                    timeout=remaining(dl))
        if rec["key"] != key:
            raise FileNotFoundError(f"upload {upload_id} is not {key}")
        if rec["state"] != "open":
            raise IOError(f"upload {upload_id} is {rec['state']}")
        etag = hashlib.md5(data).hexdigest()
        await self.ioctx.write_full(
            self._mp_part_oid(bucket, upload_id, part_num), data,
            timeout=remaining(dl))
        _chaos(self.ioctx, "rgw_part_mid")
        rec["parts"][int(part_num)] = (etag, len(data))
        await self._mp_save(bucket, upload_id, rec,
                            timeout=remaining(dl))
        self.perf.inc("rgw_mp_parts")
        return etag

    async def complete_multipart(self, bucket: str, key: str,
                                 upload_id: str,
                                 timeout: float = None) -> str:
        """CompleteMultipartUpload, all-or-nothing visible: (1) persist
        the 'completing' intent (the manifest is the recorded part set);
        (2) assemble and land the final payload; (3) update the bucket
        index — THE visibility point; (4) clean up parts + record.  A
        crash before (3) leaves the key invisible and a reclaim pass
        rolls the complete forward from the intent record; a crash
        after (3) leaves it visible and reclaim merely finishes the
        cleanup.  Partial visibility does not exist: readers resolve
        through the index, which flips in one omap write."""
        dl = deadline_of(timeout)
        rec = await self._mp_record(bucket, upload_id,
                                    timeout=remaining(dl))
        if rec["key"] != key:
            raise FileNotFoundError(f"upload {upload_id} is not {key}")
        if rec["state"] == "aborting":
            raise IOError(f"upload {upload_id} is aborting")
        if not rec["parts"]:
            raise ValueError(f"upload {upload_id} has no parts")
        if rec["state"] != "completing":   # retry keeps the intent
            rec["state"] = "completing"
            await self._mp_save(bucket, upload_id, rec,
                                timeout=remaining(dl))
        # S3 multipart etag: md5 over the part etags, dash part count —
        # computable from the RECORDED manifest alone, which is what
        # makes the roll-forward idempotence check below possible
        etags = [rec["parts"][n][0] for n in sorted(rec["parts"])]
        etag = (hashlib.md5("".join(etags).encode()).hexdigest()
                + f"-{len(etags)}")
        idx = await self.ioctx.omap_get(self._index_oid(bucket),
                                        timeout=remaining(dl))
        prior = idx.get(key)
        if prior is not None and pickle.loads(prior).etag == etag:
            # the index already flipped for THIS manifest: a previous
            # complete died mid-CLEANUP (some parts may already be
            # gone, so re-assembly is impossible and unnecessary) —
            # skip straight to finishing the cleanup
            pass
        else:
            data = bytearray()
            for n in sorted(rec["parts"]):
                data += await self.ioctx.read(
                    self._mp_part_oid(bucket, upload_id, n),
                    timeout=remaining(dl))
            await self.ioctx.write_full(self._data_oid(bucket, key),
                                        bytes(data),
                                        timeout=remaining(dl))
            _chaos(self.ioctx, "rgw_complete_mid")
            meta = ObjectMeta(key=key, size=len(data), etag=etag,
                              mtime=time.time())
            await self.ioctx.omap_set(self._index_oid(bucket),
                                      {key: pickle.dumps(meta)},
                                      timeout=remaining(dl))
            await self._bilog_append(bucket, "put", key, None)
            self.perf.inc("rgw_put")
            self.perf.hinc("rgw_obj_bytes_hist", len(data))
        for n in sorted(rec["parts"]):
            try:
                await self.ioctx.remove(
                    self._mp_part_oid(bucket, upload_id, n),
                    timeout=remaining(dl))
            except FileNotFoundError:
                pass
        await self.ioctx.omap_rmkeys(self._uploads_oid(bucket),
                                     [upload_id], timeout=remaining(dl))
        self.perf.inc("rgw_mp_completed")
        return etag

    async def abort_multipart(self, bucket: str, key: str,
                              upload_id: str,
                              timeout: float = None) -> None:
        """AbortMultipartUpload: persist the 'aborting' intent, then
        delete parts and the record.  A crash mid-abort
        (``rgw_abort_mid``) leaves the intent + some parts; reclaim
        finishes the abort."""
        dl = deadline_of(timeout)
        rec = await self._mp_record(bucket, upload_id,
                                    timeout=remaining(dl))
        if rec["key"] != key:
            raise FileNotFoundError(f"upload {upload_id} is not {key}")
        rec["state"] = "aborting"
        await self._mp_save(bucket, upload_id, rec,
                            timeout=remaining(dl))
        _chaos(self.ioctx, "rgw_abort_mid")
        for n in sorted(rec["parts"]):
            try:
                await self.ioctx.remove(
                    self._mp_part_oid(bucket, upload_id, n),
                    timeout=remaining(dl))
            except FileNotFoundError:
                pass
        await self.ioctx.omap_rmkeys(self._uploads_oid(bucket),
                                     [upload_id], timeout=remaining(dl))
        self.perf.inc("rgw_mp_aborted")

    async def list_multipart_uploads(self, bucket: str) -> Dict[str, Dict]:
        """upload_id -> record for every registered in-flight upload."""
        try:
            om = await self.ioctx.omap_get(self._uploads_oid(bucket))
        except (FileNotFoundError, IOError):
            return {}
        return {uid: pickle.loads(blob) for uid, blob in om.items()
                if not uid.startswith("_")}

    async def reclaim_multipart(self, bucket: str,
                                abort_open: bool = False) -> Dict[str, int]:
        """The multipart garbage collector + index repair pass
        (reference RGW GC / radosgw-admin bucket check --fix).  Resolves
        every interrupted transaction to a consistent end state:

        - 'completing' records ROLL FORWARD — the complete becomes
          visible exactly once (parts survive until the index flips;
          past the flip, a crash mid-cleanup is detected by the
          recorded manifest's etag already sitting in the index, and
          roll-forward skips straight to finishing the cleanup);
        - 'aborting' records finish their abort;
        - 'open' records are kept (or aborted with ``abort_open=True``,
          the lifecycle-expiry analog a judge pass uses);
        - part objects belonging to NO registered upload are orphans
          (a client died at ``rgw_part_mid``) and are deleted;
        - index entries whose payload object is gone (a client died
          mid-delete, between payload remove and index cleanup) are
          dropped — the bucket listing again matches readable objects.
        """
        stats = {"rolled_forward": 0, "aborts_finished": 0,
                 "orphan_parts": 0, "index_repaired": 0}
        for uid, rec in sorted(
                (await self.list_multipart_uploads(bucket)).items()):
            if rec["state"] == "completing":
                await self.complete_multipart(bucket, rec["key"], uid)
                stats["rolled_forward"] += 1
            elif rec["state"] == "aborting" or abort_open:
                await self.abort_multipart(bucket, rec["key"], uid)
                stats["aborts_finished"] += 1
        live = set()
        for uid, rec in (await self.list_multipart_uploads(bucket)).items():
            live.update(self._mp_part_oid(bucket, uid, n)
                        for n in rec["parts"])
            # recorded-or-not, a surviving upload's id prefix is live
            # (an unrecorded part may be re-recorded by a retry)
            live.add(uid)
        prefix = self._mp_prefix(bucket)
        for oid in await self.ioctx.list_objects():
            if not oid.startswith(prefix):
                continue
            uid = oid[len(prefix):].rsplit(".", 1)[0]
            if uid in live or oid in live:
                continue
            try:
                await self.ioctx.remove(oid)
                stats["orphan_parts"] += 1
            except FileNotFoundError:
                pass
        idx = await self._index(bucket)
        for key in sorted(idx):
            try:
                await self.ioctx.stat(self._data_oid(bucket, key))
            except FileNotFoundError:
                await self.ioctx.omap_rmkeys(self._index_oid(bucket),
                                             [key])
                await self._bilog_append(bucket, "delete", key, None)
                stats["index_repaired"] += 1
        for stat, counter in (("rolled_forward", "rgw_mp_rolled_forward"),
                              ("orphan_parts", "rgw_mp_orphan_parts"),
                              ("index_repaired", "rgw_index_repaired")):
            if stats[stat]:
                self.perf.inc(counter, stats[stat])
        return stats

    async def list_objects(self, bucket: str, prefix: str = "",
                           marker: str = "",
                           max_keys: int = 1000) -> ListResult:
        """S3 ListObjects semantics: lexicographic, after ``marker``,
        filtered by ``prefix``, truncated at ``max_keys``."""
        idx = await self._index(bucket)
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        page = keys[:max_keys]
        return ListResult(
            keys=[pickle.loads(idx[k]) for k in page],
            is_truncated=len(keys) > max_keys,
            next_marker=page[-1] if len(keys) > max_keys else None)
