"""RGW-lite: an S3-shaped object gateway over RADOS.

Behavioral analog of the reference radosgw core data model (src/rgw/):
buckets are omap-backed index objects (one entry per key, exactly how
cls_rgw maintains bucket indexes), object payloads live in the data pool
via the librados surface, and the API mirrors the S3 verbs the reference
gateway serves — create/delete bucket, put/get/head/delete object,
prefix+marker listing with truncation, and basic user metadata.  The
HTTP frontend (civetweb/Beast in the reference) is out of scope; this is
the gateway's storage core as a library.
"""

from __future__ import annotations

import hashlib
import pickle
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster.objecter import IoCtx


@dataclass
class ObjectMeta:
    """Bucket-index entry (cls_rgw rgw_bucket_dir_entry analog)."""

    key: str
    size: int
    etag: str
    mtime: float
    content_type: str = "application/octet-stream"
    user_meta: Dict[str, str] = field(default_factory=dict)


@dataclass
class ListResult:
    keys: List[ObjectMeta]
    is_truncated: bool
    next_marker: Optional[str]


class RGW:
    """Gateway handle (the radosgw storage core as a library).

    ``zone`` names this gateway's zone for multisite sync (reference
    rgw_zone): every index mutation also appends to the bucket's index
    LOG (cls_rgw bilog analog) and registers the bucket in the zone
    datalog, which RGWSyncAgent (rgw_sync.py) replays into peer zones.
    """

    def __init__(self, ioctx: IoCtx, zone: str = "default"):
        self.ioctx = ioctx
        self.zone = zone
        # gateway telemetry + admin surface (reference radosgw perf
        # counters 'rgw.*' + its admin socket): the frontend and sync
        # agent share this gateway's counters
        from ceph_tpu.utils import AdminSocket, PerfCounters
        from ceph_tpu.utils import perf as perfmod

        self.perf = PerfCounters(f"rgw.{zone}")
        self.perf.add_u64("rgw_put", desc="object puts")
        self.perf.add_u64("rgw_get", desc="object gets")
        self.perf.add_histogram(
            "rgw_obj_bytes_hist", unit=perfmod.UNIT_BYTES,
            desc="object payload size, log2 byte buckets")
        self.asok = AdminSocket()
        self.asok.register_common(self.perf)

    BUCKETS_OID = ".buckets.list"   # registry of buckets (omap)
    DATALOG_OID = ".datalog"        # bucket -> latest bilog seq (omap)
    BILOG_MAX = 1000                # trimmed window; older -> full sync

    @staticmethod
    def _index_oid(bucket: str) -> str:
        return f".bucket.index.{bucket}"

    @staticmethod
    def _bilog_oid(bucket: str) -> str:
        return f".bucket.log.{bucket}"

    # -- bucket index log (bilog) -------------------------------------------

    async def _bilog_append(self, bucket: str, op: str, key: str,
                            origin: Optional[str] = None) -> None:
        """Append one change record (reference cls_rgw bilog entry) and
        bump the bucket's datalog cursor.  ``origin`` is the zone the
        change FIRST happened in — the sync agent skips entries that
        originated in its own destination, which is what breaks the
        active-active echo loop."""
        log_oid = self._bilog_oid(bucket)
        entry = pickle.dumps({"op": op, "key": key,
                              "origin": origin or self.zone,
                              "stamp": time.time()})
        # cls-atomic append (cls_rgw bilog semantics): the exec txn
        # touches (auto-creates) the log object; seq allocation +
        # entry + trim run as one transaction under PG serialization, so
        # concurrent index mutations never collide or lose entries
        seq = int(await self.ioctx.execute(
            log_oid, "rgw_bilog", "append",
            pickle.dumps({"entry": entry, "max": self.BILOG_MAX})))
        await self.ioctx.omap_set(self.DATALOG_OID,
                                  {bucket: str(seq).encode()})

    async def bilog_window(self, bucket: str) -> Tuple[int, int]:
        """(tail, head) seq bounds of the retained log (0, 0) = empty."""
        log_oid = self._bilog_oid(bucket)
        try:
            head = int(await self.ioctx.getxattr(log_oid, "bilog.head"))
        except (KeyError, FileNotFoundError, IOError):
            return 0, 0
        try:
            tail = int(await self.ioctx.getxattr(log_oid, "bilog.tail"))
        except (KeyError, FileNotFoundError, IOError):
            tail = 0
        return tail, head

    async def bilog_entries(self, bucket: str, after: int) -> List[Tuple[int, Dict]]:
        """Entries with seq > after, oldest first."""
        try:
            om = await self.ioctx.omap_get(self._bilog_oid(bucket))
        except (FileNotFoundError, IOError):
            return []
        out = []
        for k, blob in sorted(om.items()):
            seq = int(k)
            if seq > after:
                out.append((seq, pickle.loads(blob)))
        return out

    async def datalog(self) -> Dict[str, int]:
        """bucket -> latest change seq (reference data changes log)."""
        try:
            om = await self.ioctx.omap_get(self.DATALOG_OID)
        except (FileNotFoundError, IOError):
            return {}
        return {b: int(v) for b, v in om.items()}

    @staticmethod
    def _data_oid(bucket: str, key: str) -> str:
        # length-prefixed: unambiguous for ANY bucket/key bytes (S3 keys
        # may contain any separator we could pick)
        return f"{len(bucket)}:{bucket}:{key}"

    # -- buckets ------------------------------------------------------------

    async def create_bucket(self, bucket: str) -> None:
        try:
            await self.ioctx.stat(self._index_oid(bucket))
            raise FileExistsError(bucket)
        except FileNotFoundError:
            pass
        await self.ioctx.write_full(self._index_oid(bucket),
                                    pickle.dumps({"created": time.time()}))
        await self.ioctx.omap_set(self.BUCKETS_OID, {bucket: b"1"})

    async def delete_bucket(self, bucket: str) -> None:
        idx = await self._index(bucket)
        if idx:
            raise OSError(39, "bucket not empty", bucket)
        await self.ioctx.remove(self._index_oid(bucket))
        await self.ioctx.omap_rmkeys(self.BUCKETS_OID, [bucket])

    async def list_buckets(self) -> List[str]:
        # O(buckets) via the registry omap, not O(pool objects)
        try:
            return sorted(await self.ioctx.omap_get(self.BUCKETS_OID))
        except (FileNotFoundError, IOError):
            return []

    async def _index(self, bucket: str) -> Dict[str, bytes]:
        try:
            await self.ioctx.stat(self._index_oid(bucket))
        except FileNotFoundError:
            raise FileNotFoundError(f"bucket {bucket}")
        return await self.ioctx.omap_get(self._index_oid(bucket))

    # -- objects ------------------------------------------------------------

    async def put_object(self, bucket: str, key: str, data: bytes,
                         content_type: str = "application/octet-stream",
                         user_meta: Optional[Dict[str, str]] = None,
                         origin: Optional[str] = None,
                         meta: Optional[ObjectMeta] = None) -> str:
        """``origin``/``meta`` are the multisite apply path: the sync
        agent preserves the source zone's metadata (etag/mtime) and
        stamps the entry's TRUE origin for echo suppression."""
        try:
            await self.ioctx.stat(self._index_oid(bucket))  # must exist
        except FileNotFoundError:
            raise FileNotFoundError(f"bucket {bucket}")
        if meta is None:
            etag = hashlib.md5(data).hexdigest()
            meta = ObjectMeta(key=key, size=len(data), etag=etag,
                              mtime=time.time(),
                              content_type=content_type,
                              user_meta=dict(user_meta or {}))
        await self.ioctx.write_full(self._data_oid(bucket, key), data)
        self.perf.inc("rgw_put")
        self.perf.hinc("rgw_obj_bytes_hist", len(data))
        # index update AFTER the payload lands (cls_rgw prepares/completes
        # around the data write for the same reason)
        await self.ioctx.omap_set(self._index_oid(bucket),
                                  {key: pickle.dumps(meta)})
        await self._bilog_append(bucket, "put", key, origin)
        return meta.etag

    async def head_object(self, bucket: str, key: str) -> ObjectMeta:
        idx = await self._index(bucket)
        blob = idx.get(key)
        if blob is None:
            raise FileNotFoundError(f"{bucket}/{key}")
        return pickle.loads(blob)

    async def get_object(self, bucket: str,
                         key: str) -> Tuple[ObjectMeta, bytes]:
        meta = await self.head_object(bucket, key)
        data = await self.ioctx.read(self._data_oid(bucket, key))
        self.perf.inc("rgw_get")
        return meta, data

    async def delete_object(self, bucket: str, key: str,
                            origin: Optional[str] = None) -> None:
        await self.head_object(bucket, key)  # 404 when absent
        await self.ioctx.remove(self._data_oid(bucket, key))
        await self.ioctx.omap_rmkeys(self._index_oid(bucket), [key])
        await self._bilog_append(bucket, "delete", key, origin)

    async def list_objects(self, bucket: str, prefix: str = "",
                           marker: str = "",
                           max_keys: int = 1000) -> ListResult:
        """S3 ListObjects semantics: lexicographic, after ``marker``,
        filtered by ``prefix``, truncated at ``max_keys``."""
        idx = await self._index(bucket)
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        page = keys[:max_keys]
        return ListResult(
            keys=[pickle.loads(idx[k]) for k in page],
            is_truncated=len(keys) > max_keys,
            next_marker=page[-1] if len(keys) > max_keys else None)
