"""PG state + persistent pg log plumbing (reference src/osd/PG.h/cc).

Split out of osd.py along the reference's PG seam: PGState is the
pg_info_t/pg_log_t analog; PGLogMixin carries the incremental on-store
log persistence every mutation rides (PG::write_if_dirty) and the
recovery-time full rewrite/load paths."""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import pickle
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.pglog import LogEntry, PGInfo, PGLog
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.osdmap.osdmap import PGid, ceph_stable_mod
from ceph_tpu.analysis import racecheck
from ceph_tpu.utils.lockdep import DepLock

# the client reqid whose op vector is currently executing (set around
# _execute_client_ops by the mutation-dedup wrapper); _log_mutation stamps
# it into primary-minted log entries so dup protection replicates with
# the log.  A ContextVar so interleaved client tasks can't cross-stamp.
CURRENT_CLIENT_REQID: contextvars.ContextVar = contextvars.ContextVar(
    "ceph_tpu_current_client_reqid", default=None)

# the wall-clock deadline of the client op currently executing (set
# around _dispatch_client_op): sub-writes/sub-reads fanned out under it
# inherit the parent deadline so replicas can shed dead work.  None for
# recovery/scrub traffic, which has no client waiting.
CURRENT_OP_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    "ceph_tpu_current_op_deadline", default=None)


# the per-PG metadata object holding the persisted log + last_update
# (reference: the pgmeta ghobject, PG::_init / read_info)
PGMETA = "_pgmeta_"
# per-PG rollback journal: omap keyed by entry seq holding the local
# pre-write state of EC shard mutations (reference: the rollback info
# ECBackend attaches to local transactions,
# doc/dev/osd_internals/erasure_coding/ecbackend.rst:10-27)
PGRB = "_pgrb_"

@dataclass
class PGState:
    pgid: PGid
    up: List[int] = field(default_factory=list)
    acting: List[int] = field(default_factory=list)
    primary: int = -1
    # pg_info_t analog: every mutation advances last_update and appends to
    # the log (reference PG.h pg_log)
    last_update: pglog.Eversion = pglog.ZERO
    # newest version known acked by EVERY acting member (reference
    # last_complete / min_last_complete_ondisk): entries above it may be
    # rolled back during peering, entries at or below never are
    last_complete: pglog.Eversion = pglog.ZERO
    log: PGLog = field(default_factory=PGLog)
    # per-PG op serialization domain (reference PG lock / ShardedOpWQ,
    # src/osd/OSD.h:1599): mutations hold this across their whole
    # fan-out so concurrent writes order identically on all replicas.
    # DepLock so orderings against the daemon/messenger locks enter the
    # lockdep graphs; all PGs share one name — per-task nesting of two
    # PG locks is self-ordering lockdep cannot model, and the reference
    # likewise registers one lockdep id per lock NAME
    lock: DepLock = field(default_factory=lambda: DepLock("pg.lock"))
    # reqid -> cached replies of completed mutations (reference pg_log
    # dup tracking, osd_pg_log_dups_tracked): a resent non-idempotent op
    # (exec, delete, ...) returns its original reply instead of
    # re-executing.  In-memory only — a primary restart forgets dups the
    # way a reference OSD forgets dups past the trimmed log.
    reqid_replies: "OrderedDict[Tuple, List]" = field(
        default_factory=OrderedDict)
    # reqids currently executing: a dup that races its first instance
    # waits for that instance's replies rather than re-executing
    reqid_inflight: Dict[Tuple, asyncio.Future] = field(
        default_factory=dict)
    # in-flight client mutations awaiting their fan-out acks (round-11
    # pipelined writes, the RepGather in-progress-ops analog): version
    # -> acked?  Insertion order IS version order (registered under the
    # PG lock right after version assignment), and the commit watermark
    # only advances over the contiguous resolved prefix — an op whose
    # acks land out of order can never bless an earlier still-pending
    # write (see PGLogMixin._frontier_done)
    pipeline_pending: "OrderedDict[pglog.Eversion, bool]" = field(
        default_factory=OrderedDict)
    # crash-restart frontier reconstruction (round 12): logged entries
    # above the persisted watermark whose fan-out acks died with the
    # previous process life.  They sit in pipeline_pending as OPEN
    # entries (so last_complete cannot bless them) until peering
    # verifies every acting member holds them (roll forward) or rewinds
    # them; a recovery round is not complete while any remain.
    frontier_recovering: set = field(default_factory=set)
    # per-object write serialization for the pipelined RMW path (round
    # 12, reference ECBackend::start_rmw wait queue): read-merge-encode
    # runs under the OBJECT's lock, not the PG's, so one object's RMW
    # can never interleave with (or lose) another write to the same
    # object while the rest of the PG proceeds.  Entries are created on
    # demand and dropped when uncontended (see OSD._obj_write_lock).
    obj_locks: Dict[str, object] = field(default_factory=dict)
    obj_lock_refs: Dict[str, int] = field(default_factory=dict)
    # objects currently known inconsistent (round 16: a scrub or a
    # verifying read found a shard bad and the repair has not landed
    # yet).  Feeds the beacon's scrub_stats and so the mon's
    # PG_INCONSISTENT / OSD_SCRUB_ERRORS health flow: raise while
    # non-empty, clear when the repairs land.
    inconsistent: set = field(default_factory=set)

    def frontier_acked(self, seq: int) -> bool:
        """Is seq a RESOLVED (fully acked) frontier entry that the
        contiguous-prefix watermark merely hasn't swept yet?  Reads may
        serve such a generation: its durability is established even
        though last_complete is held back by an earlier open entry."""
        return any(ok and v[1] == seq
                   for v, ok in self.pipeline_pending.items())

    def info(self) -> PGInfo:
        return PGInfo(last_update=self.last_update, log_tail=self.log.tail,
                      last_complete=self.last_complete)


@dataclass
class MOSDPGQuery(M.Message):
    pgid: Optional[PGid] = None


@dataclass
class MOSDPGQueryReply(M.Message):
    pgid: Optional[PGid] = None
    objects: Dict[str, int] = field(default_factory=dict)  # oid -> seq
    info: Optional[PGInfo] = None
    log: Optional[PGLog] = None


def _coll(pgid: PGid) -> str:
    return f"pg_{pgid.pool}_{pgid.seed}"



class PGLogMixin:
    """Persistent pg-log state carried by the OSD daemon (PG::write_if_dirty
    / read_info seam)."""

    def _next_version(self, st: PGState) -> pglog.Eversion:
        """eversion for the next mutation: (map epoch, next seq)."""
        return (self.osdmap.epoch if self.osdmap else 0, st.last_update[1] + 1)

    @staticmethod
    def _meta_key(version: pglog.Eversion) -> str:
        return f"{version[0]:010d}.{version[1]:012d}"

    def _log_mutation(self, st: PGState, op: str, oid: str,
                      version: pglog.Eversion,
                      entry: Optional[LogEntry] = None):
        """Append a log entry + persist it INCREMENTALLY to the pgmeta
        object (one omap key per entry + a head attr), so a restarted OSD
        peers from its on-store log instead of backfilling and the hot
        write path never re-serializes the whole log (reference: log
        entries ride the op's own transaction, PG::write_if_dirty).
        Replicas pass the primary's ``entry`` through verbatim so every
        member's log (incl. prior_version chains) stays byte-identical.
        Returns the appended LogEntry, or None for a replayed duplicate."""
        if version <= st.last_update:
            return None  # replayed/duplicate entry
        if entry is None:
            entry = LogEntry(op=op, oid=oid, version=version,
                             prior_version=st.last_update,
                             committed=st.last_complete,
                             client_reqid=CURRENT_CLIENT_REQID.get())
        st.log.append(entry)
        st.last_update = version
        if racecheck.TRACKER:  # graft-race: the log head advanced —
            # any other task still resting on a round-start self-info
            # snapshot (recovery's roll-forward floor) is now stale
            racecheck.TRACKER.note_write(
                ("pg", getattr(self, "osd_id", -1), str(st.pgid)),
                "self_info")
        dropped = st.log.trim()
        coll = _coll(st.pgid)
        txn = (Transaction()
               .omap_set(coll, PGMETA,
                         {self._meta_key(version): pickle.dumps(entry)})
               .setattr(coll, PGMETA, "last_update", pickle.dumps(version))
               .setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail)))
        if dropped:
            txn.omap_rmkeys(coll, PGMETA,
                            [self._meta_key(e.version) for e in dropped])
        # learn the primary's commit watermark from the entry stream and
        # drop rollback records for entries that can no longer rewind.
        # Routed through _frontier_learn: the primary's word resolves
        # any boot-reconstructed open entries at/below it (a replica's
        # own frontier must never wedge on entries the primary already
        # committed cluster-wide)
        committed = getattr(entry, "committed", pglog.ZERO)
        if committed > st.last_complete:
            self._frontier_learn(st, committed, txn)
        self.store.queue_transaction(txn)
        return entry

    def _frontier_rebuild(self, st: PGState) -> None:
        """Crash-restart frontier reconstruction (round 12): the
        round-11 frontier was purely in-memory, so a restarted daemon
        forgot which logged entries were still awaiting their fan-out
        acks — and a post-restart write that fully acked would advance
        ``last_complete`` PAST them, blessing writes whose acks died
        with the process (peering might still rewind them: broken
        read-your-ack by construction).  Re-register every logged entry
        above the persisted watermark as an OPEN frontier entry;
        peering resolves each by verifying every acting member holds it
        (roll forward, reference PG::activate) or rewinding it."""
        for e in st.log.entries:
            if e.version > st.last_complete:
                st.pipeline_pending[e.version] = False
                st.frontier_recovering.add(e.version)
        if st.frontier_recovering:
            self.perf.inc("osd_frontier_rebuilt",
                          len(st.frontier_recovering))

    def _frontier_learn(self, st: PGState, version: pglog.Eversion,
                        txn=None) -> None:
        """An AUTHORITATIVE commit watermark arrived — the primary's
        entry stream, or a peering round that verified every acting
        member holds every entry up to ``version``.  Resolve open
        frontier entries at/below it (their durability is now
        established by authority, not by our own ack bookkeeping),
        sweep any contiguous resolved prefix beyond, and advance."""
        fl = st.pipeline_pending
        for v in [v for v in fl if v <= version]:
            del fl[v]
            st.frontier_recovering.discard(v)
        new = version
        while fl:
            v = next(iter(fl))
            if not fl[v]:
                break
            new = v
            del fl[v]
            st.frontier_recovering.discard(v)
        self._advance_last_complete(st, new, txn)

    @contextlib.asynccontextmanager
    async def _obj_write_lock(self, st: PGState, oid: str):
        """Per-object write serialization for the pipelined mutation
        path (round 12): an RMW holds this across its read-merge-encode
        window and commit start, and every other pipelined write to the
        SAME object takes it around its commit start — so no write can
        commit inside an RMW's read window (the lost-update race the
        full PG lock used to exclude), while writes to different
        objects of the PG proceed concurrently.  Always acquired BEFORE
        st.lock (the lockdep order pg.objlock -> pg.lock)."""
        lock = st.obj_locks.get(oid)
        if lock is None:
            lock = st.obj_locks[oid] = DepLock("pg.objlock")
        st.obj_lock_refs[oid] = st.obj_lock_refs.get(oid, 0) + 1
        try:
            async with lock:
                yield
        finally:
            n = st.obj_lock_refs.get(oid, 1) - 1
            if n <= 0:
                st.obj_lock_refs.pop(oid, None)
                st.obj_locks.pop(oid, None)
            else:
                st.obj_lock_refs[oid] = n

    def _entry_still_logged(self, st: PGState, entry) -> bool:
        """Is THIS LogEntry object still part of the PG's history?  The
        commit finishes use it to detect a concurrent peering rewind:
        comparing the version against the log head is foolable — new
        post-rewind writes re-advance ``last_update`` past (or a retry
        round at the same epoch re-MINTS) the rewound eversion, and a
        rolled-back write would ack as success.  Object identity cannot
        be re-minted.  A log ADOPTION (peering replaced the entries
        with auth copies) also fails the check — conservatively
        un-acked, and the client's retry dup-resolves against the log.
        Scans newest-first with an ordering early-exit: an in-flight
        commit's entry sits at/near the head."""
        if entry is None:
            return True
        for e in reversed(st.log.entries):
            if e is entry:
                return True
            if e.version < entry.version:
                return False
        return False

    def _frontier_open(self, st: PGState, version: pglog.Eversion) -> None:
        """Register an in-flight client mutation (called under the PG
        lock, immediately after version assignment, so insertion order
        is version order): the commit watermark may not advance past a
        PENDING entry — an out-of-order later ack blessing bytes that
        can still fail and roll back would break read-your-ack."""
        st.pipeline_pending[version] = False
        if racecheck.TRACKER:  # graft-race: the commit's registry
            # snapshot window OPENS here — `st` will outlive the PG
            # lock through the ack wait
            racecheck.TRACKER.note_read(
                ("pgs", getattr(self, "osd_id", -1), str(st.pgid)),
                "registry")

    def _frontier_done(self, st: PGState, version: pglog.Eversion,
                       ok: bool) -> None:
        """Resolve one in-flight mutation and advance the watermark over
        the contiguous RESOLVED prefix.  A failed (un-acked) entry is
        removed without blocking later acked entries — the pre-pipeline
        semantics, where a later fully-acked op advanced past an earlier
        failed one and peering owns the failed entry's fate."""
        if racecheck.TRACKER:  # graft-race: the snapshot window
            # CLOSES — resolution re-consults the registry downstream
            # (_advance_last_complete's identity re-check is the guard
            # this attests), so a registry swap during the ack wait is
            # revalidated, not acted on blind.  A commit task that
            # finishes without ever resolving its frontier entry keeps
            # the window open and convicts under the race smoke.
            racecheck.TRACKER.note_read(
                ("pgs", getattr(self, "osd_id", -1), str(st.pgid)),
                "registry")
        fl = st.pipeline_pending
        if version not in fl:
            # unregistered caller (recovery / roll-forward, or a commit
            # whose entry a concurrent peering round REWOUND out from
            # under its ack wait — version > last_update): direct
            # advance, still clamped below any pending entry and never
            # past the log head (blessing a rewound version would put
            # the watermark over history that no longer exists)
            if ok and version <= st.last_update:
                self._advance_last_complete(st, version)
            return
        if ok:
            fl[version] = True
        else:
            del fl[version]
            st.frontier_recovering.discard(version)
        new = None
        while fl:
            v = next(iter(fl))
            if not fl[v]:
                break
            new = v
            del fl[v]
            st.frontier_recovering.discard(v)
        if new is not None:
            self._advance_last_complete(st, new)
        self._frontier_rearm_if_short(st)

    def _frontier_rearm_if_short(self, st: PGState) -> None:
        """A DRAINED frontier with the watermark still short of the log
        head means some resolution failed (sub-write acks lost to a
        drop or a mid-fanout crash): no later ack will ever arrive for
        those entries and no map change is due, so without a kick the
        primary stays incomplete until an unrelated epoch — permanently
        on an idle pool (graft-race: batch-smoke at small scale wedges
        exactly here once the last round's acks are gone).  Peering's
        roll-forward owns the failed entries' fate — arm the
        capped-backoff recovery retry and let it rule on each."""
        if st.pipeline_pending or st.last_complete >= st.last_update:
            return
        if st.primary != getattr(self, "osd_id", -1):
            return
        retry = getattr(self, "_queue_recovery_retry", None)
        if retry is not None:
            retry(st)

    def _advance_last_complete(self, st: PGState, version: pglog.Eversion,
                               txn: Optional[Transaction] = None) -> None:
        """Raise the never-roll-back watermark and prune the rollback
        journal up to it (rollback info exists only to undo UN-acked
        entries, ecbackend.rst:10-27).  Never past a pending pipelined
        write: entries awaiting their fan-out acks are not durable."""
        if version <= st.last_complete:
            return
        if version > st.last_update:
            # never past the log head: a watermark over rewound (or
            # never-logged) history is unresolvable — peering elections
            # would find NO member whose log covers it
            return
        if st.pipeline_pending and \
                version >= next(iter(st.pipeline_pending)):
            return
        pgs = getattr(self, "pgs", None)
        if pgs is not None and pgs.get(st.pgid) is not st:
            # superseded PGState (the PG left and rejoined this OSD
            # while an op's ack-wait half was still in flight): its
            # watermark no longer owns the store attr — persisting it
            # here would race the LIVE state's view (surfaced by the
            # round-12 frontier invariant as persisted != in-memory).
            # The live state recomputes via peering / the entry stream.
            return
        st.last_complete = version
        coll = _coll(st.pgid)
        own = txn is None
        if own:
            txn = Transaction()
        txn.setattr(coll, PGMETA, "last_complete", pickle.dumps(version))
        dead = [k for k in self.store.omap_get(coll, PGRB)
                if int(k) <= version[1]]
        if dead:
            txn.omap_rmkeys(coll, PGRB, dead)
        if own:
            self.store.queue_transaction(txn)

    @staticmethod
    def _rb_key(seq: int) -> str:
        return f"{seq:012d}"

    def rewind_divergent_log(self, st: PGState,
                             auth_head: pglog.Eversion) -> List[str]:
        """Roll this member's log back to ``auth_head`` (reference
        PGLog::rewind_divergent_log, PGLog.cc:287): undo each divergent
        entry from its rollback record — restoring the EXACT pre-write
        shard bytes/attrs — newest first.  Entries without a record
        (replicated pools, lost records) fall back to removing the
        object; the returned oid list names those, for the caller to
        re-pull/push from the authoritative copy."""
        coll = _coll(st.pgid)
        rb = self.store.omap_get(coll, PGRB)
        need_copy: List[str] = []
        txn = Transaction()
        divergent = [e for e in st.log.entries if e.version > auth_head]
        for e in reversed(divergent):
            rec_blob = rb.get(self._rb_key(e.version[1]))
            if e.op == "trim":
                # snap-trim rollback is a no-op: removed_snaps come from
                # the osdmap, so the authoritative primary re-trims (the
                # operation is idempotent) and snap_sync reconciles
                pass
            elif rec_blob is None:
                txn.remove(coll, e.oid)
                need_copy.append(e.oid)
            else:
                rec = pickle.loads(rec_blob)
                if not rec["existed"]:
                    txn.remove(coll, rec["oid"])
                else:
                    if rec.get("layout") == "planar8":
                        # planar-at-rest object: old_range IS the
                        # captured plane blob — restore it AS planes (a
                        # byte write would land the blob as logical
                        # bytes and drop the layout); capture is
                        # whole-object (chunk_off 0)
                        txn.write_planar(coll, rec["oid"],
                                         rec["chunk_off"] // 8,
                                         rec["old_range"],
                                         rec["old_total"] // 8)
                    else:
                        txn.write(coll, rec["oid"], rec["chunk_off"],
                                  rec["old_range"])
                        txn.truncate(coll, rec["oid"], rec["old_total"])
                    # attrs + version roll back WITH the bytes on BOTH
                    # layouts: restoring planes while the divergent
                    # write's size/hinfo_crc/version attrs stay stamped
                    # leaves old data under a new crc, and the member
                    # fails verify-on-read forever after — an
                    # unrepairable-object wedge when it strikes more
                    # members than the code can spare (graft-race:
                    # batch-smoke seed 2, mid-fanout crash rewind on
                    # two of k+m=3 members)
                    for name, val in rec["old_attrs"].items():
                        if val is None:
                            txn.rmattr(coll, rec["oid"], name)
                        else:
                            txn.setattr(coll, rec["oid"], name, val)
                    txn.set_version(coll, rec["oid"], rec["old_version"])
                txn.omap_rmkeys(coll, PGRB, [self._rb_key(e.version[1])])
            txn.omap_rmkeys(coll, PGMETA, [self._meta_key(e.version)])
            self.perf.inc("osd_log_rewinds")
        st.log.entries = [e for e in st.log.entries
                          if e.version <= auth_head]
        # rolled-back entries leave the commit frontier too: a rewound
        # version can never ack, and a reconstructed open entry for it
        # would wedge the watermark forever
        for v in [v for v in st.pipeline_pending if v > auth_head]:
            del st.pipeline_pending[v]
            st.frontier_recovering.discard(v)
        # in-place entries rewrite: the lazy reqid dup index must rebuild,
        # or has_reqid would ack ops whose effects were just rolled back
        st.log._reqids = None
        st.last_update = auth_head
        txn.setattr(coll, PGMETA, "last_update", pickle.dumps(auth_head))
        self.store.queue_transaction(txn)
        return need_copy

    # ------------------------------------------------------- PG splitting

    def _split_pg(self, pool, st: "PGState") -> List[PGid]:
        """Split this parent PG's objects/log into child collections by
        stable_mod under the pool's CURRENT pg_num (reference
        PG::split_colls / split_into, PG.h:416-422,1436).

        Runs on every OSD holding the parent when pg_num grows; because
        pgp_num is unchanged at that moment, children place onto the SAME
        acting set as the parent (raw_pg_to_pps folds child seeds back to
        the parent's placement seed), so every member splits identically
        and the children activate with their data in place.  A later
        pgp_num increase migrates children via the normal remap+recovery
        path.  Returns the child pgids that received objects."""
        from ceph_tpu.cluster import snaps as snapmod
        from ceph_tpu.ops.jenkins import str_hash_rjenkins

        coll = _coll(st.pgid)
        new_num, mask = pool.pg_num, pool.pg_num_mask

        def child_seed(head: str) -> int:
            return ceph_stable_mod(
                str_hash_rjenkins(head.encode()), new_num, mask)

        from ceph_tpu.cluster.tiering import HITSET_PREFIX

        moves: Dict[int, List[str]] = {}
        for name in self.store.list_objects(coll):
            if name in (PGMETA, PGRB) or name.startswith(HITSET_PREFIX):
                continue  # pg-internal bookkeeping objects stay put
            seed = child_seed(snapmod.head_of(name))
            if seed != st.pgid.seed:
                moves.setdefault(seed, []).append(name)
        # the LOG splits by oid hash independently of surviving store
        # objects: entries for deleted objects must migrate too, or their
        # dup protection dies with the split
        log_moves: Dict[int, List[LogEntry]] = {}
        for e in st.log.entries:
            seed = child_seed(snapmod.head_of(e.oid))
            if seed != st.pgid.seed:
                log_moves.setdefault(seed, []).append(e)
        children: List[PGid] = []
        for seed in sorted(set(moves) | set(log_moves)):
            names = moves.get(seed, [])
            child = PGid(st.pgid.pool, seed)
            children.append(child)
            dst = _coll(child)
            txn = Transaction()
            if dst not in self.store.list_collections():
                txn.create_collection(dst)
            for name in names:
                data = self.store.read(coll, name)
                txn.write(dst, name, 0, data if data else b"")
                for k, v in self.store.get_xattrs(coll, name).items():
                    txn.setattr(dst, name, k, v)
                om = self.store.omap_get(coll, name)
                if om:
                    txn.omap_set(dst, name, om)
                txn.set_version(dst, name, self.store.get_version(coll, name))
                txn.remove(coll, name)
            # child log: the parent's entries for the child's objects,
            # with the parent's watermarks so peering among the child's
            # members (== the parent's members) agrees
            entries = log_moves.get(seed, [])
            txn.omap_set(dst, PGMETA,
                         {self._meta_key(e.version): pickle.dumps(e)
                          for e in entries})
            txn.setattr(dst, PGMETA, "last_update",
                        pickle.dumps(st.last_update))
            txn.setattr(dst, PGMETA, "log_tail", pickle.dumps(st.log.tail))
            txn.setattr(dst, PGMETA, "last_complete",
                        pickle.dumps(st.last_complete))
            txn.setattr(dst, PGMETA, "split_pgnum", pickle.dumps(new_num))
            self.store.queue_transaction(txn)
            self.perf.inc("osd_pg_splits")
        # stamp the parent: this collection is now consistent with new_num
        self.store.queue_transaction(Transaction().setattr(
            coll, PGMETA, "split_pgnum", pickle.dumps(new_num)))
        if children and hasattr(self, "clog"):
            self.clog("INF", f"pg {st.pgid} split into "
                             f"{[str(c) for c in children]} "
                             f"(pg_num {new_num})")
        return children

    def _maybe_split(self, pool, st: "PGState") -> bool:
        """Split this PG if its on-store split watermark is behind the
        pool's pg_num.  The watermark persists with the PG (setattr on
        PGMETA), so an OSD that was down or restarted across the pg_num
        bump still splits on resume — an in-memory tracker would not
        survive (reference: split is driven from the persisted map epoch).
        NOTE: children assume the parent's placement (pgp_num unchanged);
        bump pgp_num only after the cluster has advanced past the split.
        """
        coll = _coll(st.pgid)
        blob = self.store.getattr(coll, PGMETA, "split_pgnum")
        stored = pickle.loads(blob) if blob else -1
        # stored == -1: unstamped collection (predates the watermark, or
        # the OSD was down across the bump before creation stamping) —
        # scan once; _split_pg stamps even when nothing moves
        if 0 < pool.pg_num <= stored:
            return False
        self._split_pg(pool, st)
        return True

    def _save_pg_meta(self, st: PGState) -> None:
        """Full rewrite of the persisted log (recovery-time adoption of an
        authoritative log; NOT on the per-op path)."""
        coll = _coll(st.pgid)
        old = list(self.store.omap_get(coll, PGMETA))
        txn = Transaction()
        if old:
            txn.omap_rmkeys(coll, PGMETA, old)
        txn.omap_set(coll, PGMETA,
                     {self._meta_key(e.version): pickle.dumps(e)
                      for e in st.log.entries})
        txn.setattr(coll, PGMETA, "last_update", pickle.dumps(st.last_update))
        txn.setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail))
        self.store.queue_transaction(txn)

    def _load_pg_meta(self, pgid: PGid) -> Tuple[pglog.Eversion, PGLog]:
        coll = _coll(pgid)
        lu = self.store.getattr(coll, PGMETA, "last_update")
        if lu is None:
            return pglog.ZERO, PGLog()
        last_update = pickle.loads(lu)
        tail_blob = self.store.getattr(coll, PGMETA, "log_tail")
        tail = pickle.loads(tail_blob) if tail_blob else pglog.ZERO
        entries = [pickle.loads(v) for _, v in
                   sorted(self.store.omap_get(coll, PGMETA).items())]
        entries = [e for e in entries if e.version > tail]
        return last_update, PGLog(tail=tail, entries=entries)

    def _load_last_complete(self, pgid: PGid) -> pglog.Eversion:
        blob = self.store.getattr(_coll(pgid), PGMETA, "last_complete")
        return pickle.loads(blob) if blob else pglog.ZERO

    def _list_pg_objects(self, pgid: PGid) -> List[str]:
        # PGMETA, the rollback journal, and archived hit sets are PG
        # bookkeeping; the journal and hit sets are member-LOCAL (each
        # shard/primary records its own) — none may ever be listed,
        # scrubbed, or backfilled as data
        from ceph_tpu.cluster.tiering import HITSET_PREFIX

        return [o for o in self.store.list_objects(_coll(pgid))
                if o not in (PGMETA, PGRB)
                and not o.startswith(HITSET_PREFIX)]
