"""PG state + persistent pg log plumbing (reference src/osd/PG.h/cc).

Split out of osd.py along the reference's PG seam: PGState is the
pg_info_t/pg_log_t analog; PGLogMixin carries the incremental on-store
log persistence every mutation rides (PG::write_if_dirty) and the
recovery-time full rewrite/load paths."""

from __future__ import annotations

import asyncio
import pickle
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.pglog import LogEntry, PGInfo, PGLog
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.osdmap.osdmap import PGid


# the per-PG metadata object holding the persisted log + last_update
# (reference: the pgmeta ghobject, PG::_init / read_info)
PGMETA = "_pgmeta_"

@dataclass
class PGState:
    pgid: PGid
    up: List[int] = field(default_factory=list)
    acting: List[int] = field(default_factory=list)
    primary: int = -1
    # pg_info_t analog: every mutation advances last_update and appends to
    # the log (reference PG.h pg_log)
    last_update: pglog.Eversion = pglog.ZERO
    log: PGLog = field(default_factory=PGLog)
    # per-PG op serialization domain (reference PG lock / ShardedOpWQ,
    # src/osd/OSD.h:1599): mutations hold this across their whole
    # fan-out so concurrent writes order identically on all replicas
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # reqid -> cached replies of completed mutations (reference pg_log
    # dup tracking, osd_pg_log_dups_tracked): a resent non-idempotent op
    # (exec, delete, ...) returns its original reply instead of
    # re-executing.  In-memory only — a primary restart forgets dups the
    # way a reference OSD forgets dups past the trimmed log.
    reqid_replies: "OrderedDict[Tuple, List]" = field(
        default_factory=OrderedDict)
    # reqids currently executing: a dup that races its first instance
    # waits for that instance's replies rather than re-executing
    reqid_inflight: Dict[Tuple, asyncio.Future] = field(
        default_factory=dict)

    def info(self) -> PGInfo:
        return PGInfo(last_update=self.last_update, log_tail=self.log.tail)


@dataclass
class MOSDPGQuery(M.Message):
    pgid: Optional[PGid] = None


@dataclass
class MOSDPGQueryReply(M.Message):
    pgid: Optional[PGid] = None
    objects: Dict[str, int] = field(default_factory=dict)  # oid -> seq
    info: Optional[PGInfo] = None
    log: Optional[PGLog] = None


def _coll(pgid: PGid) -> str:
    return f"pg_{pgid.pool}_{pgid.seed}"



class PGLogMixin:
    """Persistent pg-log state carried by the OSD daemon (PG::write_if_dirty
    / read_info seam)."""

    def _next_version(self, st: PGState) -> pglog.Eversion:
        """eversion for the next mutation: (map epoch, next seq)."""
        return (self.osdmap.epoch if self.osdmap else 0, st.last_update[1] + 1)

    @staticmethod
    def _meta_key(version: pglog.Eversion) -> str:
        return f"{version[0]:010d}.{version[1]:012d}"

    def _log_mutation(self, st: PGState, op: str, oid: str,
                      version: pglog.Eversion,
                      entry: Optional[LogEntry] = None):
        """Append a log entry + persist it INCREMENTALLY to the pgmeta
        object (one omap key per entry + a head attr), so a restarted OSD
        peers from its on-store log instead of backfilling and the hot
        write path never re-serializes the whole log (reference: log
        entries ride the op's own transaction, PG::write_if_dirty).
        Replicas pass the primary's ``entry`` through verbatim so every
        member's log (incl. prior_version chains) stays byte-identical.
        Returns the appended LogEntry, or None for a replayed duplicate."""
        if version <= st.last_update:
            return None  # replayed/duplicate entry
        if entry is None:
            entry = LogEntry(op=op, oid=oid, version=version,
                             prior_version=st.last_update)
        st.log.append(entry)
        st.last_update = version
        dropped = st.log.trim()
        coll = _coll(st.pgid)
        txn = (Transaction()
               .omap_set(coll, PGMETA,
                         {self._meta_key(version): pickle.dumps(entry)})
               .setattr(coll, PGMETA, "last_update", pickle.dumps(version))
               .setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail)))
        if dropped:
            txn.omap_rmkeys(coll, PGMETA,
                            [self._meta_key(e.version) for e in dropped])
        self.store.queue_transaction(txn)
        return entry

    def _save_pg_meta(self, st: PGState) -> None:
        """Full rewrite of the persisted log (recovery-time adoption of an
        authoritative log; NOT on the per-op path)."""
        coll = _coll(st.pgid)
        old = list(self.store.omap_get(coll, PGMETA))
        txn = Transaction()
        if old:
            txn.omap_rmkeys(coll, PGMETA, old)
        txn.omap_set(coll, PGMETA,
                     {self._meta_key(e.version): pickle.dumps(e)
                      for e in st.log.entries})
        txn.setattr(coll, PGMETA, "last_update", pickle.dumps(st.last_update))
        txn.setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail))
        self.store.queue_transaction(txn)

    def _load_pg_meta(self, pgid: PGid) -> Tuple[pglog.Eversion, PGLog]:
        coll = _coll(pgid)
        lu = self.store.getattr(coll, PGMETA, "last_update")
        if lu is None:
            return pglog.ZERO, PGLog()
        last_update = pickle.loads(lu)
        tail_blob = self.store.getattr(coll, PGMETA, "log_tail")
        tail = pickle.loads(tail_blob) if tail_blob else pglog.ZERO
        entries = [pickle.loads(v) for _, v in
                   sorted(self.store.omap_get(coll, PGMETA).items())]
        entries = [e for e in entries if e.version > tail]
        return last_update, PGLog(tail=tail, entries=entries)

    def _list_pg_objects(self, pgid: PGid) -> List[str]:
        return [o for o in self.store.list_objects(_coll(pgid))
                if o != PGMETA]
