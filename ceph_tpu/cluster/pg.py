"""PG state + persistent pg log plumbing (reference src/osd/PG.h/cc).

Split out of osd.py along the reference's PG seam: PGState is the
pg_info_t/pg_log_t analog; PGLogMixin carries the incremental on-store
log persistence every mutation rides (PG::write_if_dirty) and the
recovery-time full rewrite/load paths."""

from __future__ import annotations

import asyncio
import contextvars
import pickle
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.pglog import LogEntry, PGInfo, PGLog
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.osdmap.osdmap import PGid

# the client reqid whose op vector is currently executing (set around
# _execute_client_ops by the mutation-dedup wrapper); _log_mutation stamps
# it into primary-minted log entries so dup protection replicates with
# the log.  A ContextVar so interleaved client tasks can't cross-stamp.
CURRENT_CLIENT_REQID: contextvars.ContextVar = contextvars.ContextVar(
    "ceph_tpu_current_client_reqid", default=None)


# the per-PG metadata object holding the persisted log + last_update
# (reference: the pgmeta ghobject, PG::_init / read_info)
PGMETA = "_pgmeta_"
# per-PG rollback journal: omap keyed by entry seq holding the local
# pre-write state of EC shard mutations (reference: the rollback info
# ECBackend attaches to local transactions,
# doc/dev/osd_internals/erasure_coding/ecbackend.rst:10-27)
PGRB = "_pgrb_"

@dataclass
class PGState:
    pgid: PGid
    up: List[int] = field(default_factory=list)
    acting: List[int] = field(default_factory=list)
    primary: int = -1
    # pg_info_t analog: every mutation advances last_update and appends to
    # the log (reference PG.h pg_log)
    last_update: pglog.Eversion = pglog.ZERO
    # newest version known acked by EVERY acting member (reference
    # last_complete / min_last_complete_ondisk): entries above it may be
    # rolled back during peering, entries at or below never are
    last_complete: pglog.Eversion = pglog.ZERO
    log: PGLog = field(default_factory=PGLog)
    # per-PG op serialization domain (reference PG lock / ShardedOpWQ,
    # src/osd/OSD.h:1599): mutations hold this across their whole
    # fan-out so concurrent writes order identically on all replicas
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # reqid -> cached replies of completed mutations (reference pg_log
    # dup tracking, osd_pg_log_dups_tracked): a resent non-idempotent op
    # (exec, delete, ...) returns its original reply instead of
    # re-executing.  In-memory only — a primary restart forgets dups the
    # way a reference OSD forgets dups past the trimmed log.
    reqid_replies: "OrderedDict[Tuple, List]" = field(
        default_factory=OrderedDict)
    # reqids currently executing: a dup that races its first instance
    # waits for that instance's replies rather than re-executing
    reqid_inflight: Dict[Tuple, asyncio.Future] = field(
        default_factory=dict)

    def info(self) -> PGInfo:
        return PGInfo(last_update=self.last_update, log_tail=self.log.tail,
                      last_complete=self.last_complete)


@dataclass
class MOSDPGQuery(M.Message):
    pgid: Optional[PGid] = None


@dataclass
class MOSDPGQueryReply(M.Message):
    pgid: Optional[PGid] = None
    objects: Dict[str, int] = field(default_factory=dict)  # oid -> seq
    info: Optional[PGInfo] = None
    log: Optional[PGLog] = None


def _coll(pgid: PGid) -> str:
    return f"pg_{pgid.pool}_{pgid.seed}"



class PGLogMixin:
    """Persistent pg-log state carried by the OSD daemon (PG::write_if_dirty
    / read_info seam)."""

    def _next_version(self, st: PGState) -> pglog.Eversion:
        """eversion for the next mutation: (map epoch, next seq)."""
        return (self.osdmap.epoch if self.osdmap else 0, st.last_update[1] + 1)

    @staticmethod
    def _meta_key(version: pglog.Eversion) -> str:
        return f"{version[0]:010d}.{version[1]:012d}"

    def _log_mutation(self, st: PGState, op: str, oid: str,
                      version: pglog.Eversion,
                      entry: Optional[LogEntry] = None):
        """Append a log entry + persist it INCREMENTALLY to the pgmeta
        object (one omap key per entry + a head attr), so a restarted OSD
        peers from its on-store log instead of backfilling and the hot
        write path never re-serializes the whole log (reference: log
        entries ride the op's own transaction, PG::write_if_dirty).
        Replicas pass the primary's ``entry`` through verbatim so every
        member's log (incl. prior_version chains) stays byte-identical.
        Returns the appended LogEntry, or None for a replayed duplicate."""
        if version <= st.last_update:
            return None  # replayed/duplicate entry
        if entry is None:
            entry = LogEntry(op=op, oid=oid, version=version,
                             prior_version=st.last_update,
                             committed=st.last_complete,
                             client_reqid=CURRENT_CLIENT_REQID.get())
        st.log.append(entry)
        st.last_update = version
        dropped = st.log.trim()
        coll = _coll(st.pgid)
        txn = (Transaction()
               .omap_set(coll, PGMETA,
                         {self._meta_key(version): pickle.dumps(entry)})
               .setattr(coll, PGMETA, "last_update", pickle.dumps(version))
               .setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail)))
        if dropped:
            txn.omap_rmkeys(coll, PGMETA,
                            [self._meta_key(e.version) for e in dropped])
        # learn the primary's commit watermark from the entry stream and
        # drop rollback records for entries that can no longer rewind
        committed = getattr(entry, "committed", pglog.ZERO)
        if committed > st.last_complete:
            self._advance_last_complete(st, committed, txn)
        self.store.queue_transaction(txn)
        return entry

    def _advance_last_complete(self, st: PGState, version: pglog.Eversion,
                               txn: Optional[Transaction] = None) -> None:
        """Raise the never-roll-back watermark and prune the rollback
        journal up to it (rollback info exists only to undo UN-acked
        entries, ecbackend.rst:10-27)."""
        if version <= st.last_complete:
            return
        st.last_complete = version
        coll = _coll(st.pgid)
        own = txn is None
        if own:
            txn = Transaction()
        txn.setattr(coll, PGMETA, "last_complete", pickle.dumps(version))
        dead = [k for k in self.store.omap_get(coll, PGRB)
                if int(k) <= version[1]]
        if dead:
            txn.omap_rmkeys(coll, PGRB, dead)
        if own:
            self.store.queue_transaction(txn)

    @staticmethod
    def _rb_key(seq: int) -> str:
        return f"{seq:012d}"

    def rewind_divergent_log(self, st: PGState,
                             auth_head: pglog.Eversion) -> List[str]:
        """Roll this member's log back to ``auth_head`` (reference
        PGLog::rewind_divergent_log, PGLog.cc:287): undo each divergent
        entry from its rollback record — restoring the EXACT pre-write
        shard bytes/attrs — newest first.  Entries without a record
        (replicated pools, lost records) fall back to removing the
        object; the returned oid list names those, for the caller to
        re-pull/push from the authoritative copy."""
        coll = _coll(st.pgid)
        rb = self.store.omap_get(coll, PGRB)
        need_copy: List[str] = []
        txn = Transaction()
        divergent = [e for e in st.log.entries if e.version > auth_head]
        for e in reversed(divergent):
            rec_blob = rb.get(self._rb_key(e.version[1]))
            if e.op == "trim":
                # snap-trim rollback is a no-op: removed_snaps come from
                # the osdmap, so the authoritative primary re-trims (the
                # operation is idempotent) and snap_sync reconciles
                pass
            elif rec_blob is None:
                txn.remove(coll, e.oid)
                need_copy.append(e.oid)
            else:
                rec = pickle.loads(rec_blob)
                if not rec["existed"]:
                    txn.remove(coll, rec["oid"])
                else:
                    txn.write(coll, rec["oid"], rec["chunk_off"],
                              rec["old_range"])
                    txn.truncate(coll, rec["oid"], rec["old_total"])
                    for name, val in rec["old_attrs"].items():
                        if val is None:
                            txn.rmattr(coll, rec["oid"], name)
                        else:
                            txn.setattr(coll, rec["oid"], name, val)
                    txn.set_version(coll, rec["oid"], rec["old_version"])
                txn.omap_rmkeys(coll, PGRB, [self._rb_key(e.version[1])])
            txn.omap_rmkeys(coll, PGMETA, [self._meta_key(e.version)])
            self.perf.inc("osd_log_rewinds")
        st.log.entries = [e for e in st.log.entries
                          if e.version <= auth_head]
        st.last_update = auth_head
        txn.setattr(coll, PGMETA, "last_update", pickle.dumps(auth_head))
        self.store.queue_transaction(txn)
        return need_copy

    def _save_pg_meta(self, st: PGState) -> None:
        """Full rewrite of the persisted log (recovery-time adoption of an
        authoritative log; NOT on the per-op path)."""
        coll = _coll(st.pgid)
        old = list(self.store.omap_get(coll, PGMETA))
        txn = Transaction()
        if old:
            txn.omap_rmkeys(coll, PGMETA, old)
        txn.omap_set(coll, PGMETA,
                     {self._meta_key(e.version): pickle.dumps(e)
                      for e in st.log.entries})
        txn.setattr(coll, PGMETA, "last_update", pickle.dumps(st.last_update))
        txn.setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail))
        self.store.queue_transaction(txn)

    def _load_pg_meta(self, pgid: PGid) -> Tuple[pglog.Eversion, PGLog]:
        coll = _coll(pgid)
        lu = self.store.getattr(coll, PGMETA, "last_update")
        if lu is None:
            return pglog.ZERO, PGLog()
        last_update = pickle.loads(lu)
        tail_blob = self.store.getattr(coll, PGMETA, "log_tail")
        tail = pickle.loads(tail_blob) if tail_blob else pglog.ZERO
        entries = [pickle.loads(v) for _, v in
                   sorted(self.store.omap_get(coll, PGMETA).items())]
        entries = [e for e in entries if e.version > tail]
        return last_update, PGLog(tail=tail, entries=entries)

    def _load_last_complete(self, pgid: PGid) -> pglog.Eversion:
        blob = self.store.getattr(_coll(pgid), PGMETA, "last_complete")
        return pickle.loads(blob) if blob else pglog.ZERO

    def _list_pg_objects(self, pgid: PGid) -> List[str]:
        # PGMETA and the rollback journal are PG bookkeeping, and the
        # journal is member-LOCAL (each shard's pre-write bytes differ) —
        # neither may ever be listed, scrubbed, or backfilled as data
        return [o for o in self.store.list_objects(_coll(pgid))
                if o not in (PGMETA, PGRB)]
