"""Wire messages (reference src/messages/ analog)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.cluster.messenger import Addr, Message
from ceph_tpu.osdmap.osdmap import PGid


# -- mon <-> daemons --------------------------------------------------------


@dataclass
class MPing(Message):
    stamp: float = 0.0
    reply: bool = False


@dataclass
class MOSDBoot(Message):
    osd_id: int = -1
    addr: Optional[Addr] = None
    instance: int = 0   # per-daemon-start nonce (addr-reuse fencing)


@dataclass
class MOSDFailure(Message):
    failed_osd: int = -1
    reporter: int = -1


@dataclass
class MOSDAlive(Message):
    """OSD beacon (reference MOSDBeacon): liveness + store usage +
    blocked-op telemetry for the mon's SLOW_OPS health check."""

    osd_id: int = -1
    statfs: Optional[Tuple[int, int]] = None   # (total_bytes, used_bytes)
    slow_ops: Optional[Tuple[int, float]] = None  # (count, oldest_age_s)
    # event-loop profiler feed (ceph_tpu/trace/loopmon.py): (last_lag_s,
    # window_max_s) since the previous beacon; None when the sampler is
    # off.  Drives the mon's LOOP_LAG health check beside SLOW_OPS.
    loop_lag: Optional[Tuple[float, float]] = None
    # integrity feed (round 16): (unrepaired inconsistent objects, PGs
    # holding any) on this OSD's primary PGs — drives the mon's
    # PG_INCONSISTENT / OSD_SCRUB_ERRORS health checks, raised while
    # nonzero and cleared by the next clean beacon like SLOW_OPS.
    scrub_stats: Optional[Tuple[int, int]] = None
    # recovery feed (round 21): primary PGs still owing a peering or
    # backfill round, and the map epoch this beacon judged them under.
    # Drives the mon's PG_RECOVERING check: an epoch older than the
    # last placement change means the claim is stale (pessimistic).
    unclean_pgs: Optional[int] = None
    map_epoch: int = 0


# throttle-full admission pushback result (EBUSY): distinct from the
# -11 misdirect hint on purpose — a pushed-back client must NOT refresh
# its map (the target is right, the daemon is full); it shrinks its
# congestion window and retries after a jittered backoff.  The errno
# alone is NOT the discriminator: op handlers can legitimately return
# -16 (cls lock contention), so pushback replies additionally set
# MOSDOpReply.throttled — the out-of-band flag clients key off.
THROTTLED = -16

# op verbs that mutate object state — shared by the OSD's dedup/caps
# logic and the objecter's cache-overlay targeting so the two can never
# drift (a verb classified differently on the two sides would route
# writes to the read tier)
MUTATING_OPS = frozenset({
    "write_full", "write", "delete", "setxattr", "rmxattr",
    "omap_set", "omap_rmkeys", "exec",
    "append", "truncate", "zero", "create",
    "copy_from", "rollback"})


@dataclass
class MLog(Message):
    """Cluster-log events daemon -> mon (reference MLog,
    src/messages/MLog.h; entries per src/common/LogEntry.h: who, stamp,
    priority, message).  The mon's log service Paxos-replicates them."""

    entries: Tuple = ()   # of (who: str, stamp: float, prio: str, msg: str)


@dataclass
class MOSDPGTemp(Message):
    """Primary -> mon temp-mapping request (reference MOSDPGTemp):
    ``osds`` empty asks the mon to CLEAR the pg's temp entry — sent by
    the acting primary once every up-member is backfilled current, the
    handoff that completes an elastic reshape."""

    pgid: Optional[PGid] = None
    osds: Tuple[int, ...] = ()
    epoch: int = 0       # sender's map epoch (staleness witness)
    osd_id: int = -1     # sender: the mon only honors a clear from a
                         # member of the live temp entry (a blip-degraded
                         # non-donor "primary" must not drop the handoff)


@dataclass
class MMonSubscribe(Message):
    what: str = "osdmap"
    addr: Optional[Addr] = None
    since: int = 0  # subscriber's current epoch; 0 = send the full map


@dataclass
class MOSDMapMsg(Message):
    epoch: int = 0
    osdmap_blob: bytes = b""


@dataclass
class MOSDIncMapMsg(Message):
    """Incremental map delta chain: apply in order on top of prev_epoch
    (reference OSDMap::Incremental distribution)."""

    prev_epoch: int = 0
    epoch: int = 0
    inc_blobs: List[bytes] = field(default_factory=list)


@dataclass
class MMonCommand(Message):
    cmd: Dict[str, Any] = field(default_factory=dict)
    tid: int = 0


@dataclass
class MMonCommandReply(Message):
    tid: int = 0
    result: int = 0
    data: Any = None


# -- mon <-> mon (election + paxos) ----------------------------------------


@dataclass
class MMonElection(Message):
    """Election protocol (reference src/mon/Elector.cc MMonElection):
    op in {"propose", "ack", "victory"}."""

    op: str = "propose"
    epoch: int = 0
    rank: int = -1
    quorum: List[int] = field(default_factory=list)
    # the candidate's paxos last_committed (round 14): peers holding
    # newer committed state refuse to defer, so a revived blank monitor
    # cannot win leadership (and fork map epochs) before catching up
    last_committed: int = 0


@dataclass
class MMonPaxos(Message):
    """Paxos phases (reference src/mon/Paxos.cc and MMonPaxos):
    op in {"collect", "last", "begin", "accept", "commit", "lease"}."""

    op: str = "collect"
    pn: int = 0
    rank: int = -1
    epoch: int = 0             # election epoch (lease fencing)
    last_committed: int = 0
    version: int = 0           # version being proposed / committed
    value: bytes = b""         # pickled payload
    uncommitted_pn: int = 0
    uncommitted_version: int = 0
    uncommitted_value: bytes = b""
    catch_up: List[Tuple[int, bytes]] = field(default_factory=list)


# -- client <-> osd ---------------------------------------------------------


@dataclass
class MOSDOp(Message):
    """Client op (reference MOSDOp): ops are (opname, kwargs) pairs."""

    reqid: Tuple[str, int] = ("", 0)
    pgid: Optional[PGid] = None
    oid: str = ""
    ops: List[Tuple[str, Dict[str, Any]]] = field(default_factory=list)
    epoch: int = 0
    # snapshot axis (reference MOSDOp carries both): snapc governs
    # clone-on-write for mutations, snapid selects the snap a read sees
    snapc: Optional[Tuple[int, Tuple[int, ...]]] = None
    snapid: Optional[int] = None
    # absolute wall-clock deadline of the CLIENT's total op budget: OSDs
    # drop the op at dequeue once it passes (nobody awaits the reply),
    # and sub-ops inherit it so replicas shed dead work too
    deadline: Optional[float] = None


@dataclass
class MOSDOpReply(Message):
    reqid: Tuple[str, int] = ("", 0)
    result: int = 0
    data: Any = None
    epoch: int = 0
    # True ONLY for admission-throttle pushback: result=-16 alone is
    # ambiguous (a cls lock EBUSY is an op RESULT to surface, not a
    # congestion signal to retry)
    throttled: bool = False


@dataclass
class MOSDOpBatch(Message):
    """A client tick's ops for ONE OSD in ONE frame (round 18): each
    item is a complete MOSDOp, resolved/admitted per item on the OSD —
    the client-edge twin of MOSDECSubOpWriteBatch.  Collapses the
    per-op frame churn the objecter coalescer measured dominating the
    saturation knee."""

    items: List[Any] = field(default_factory=list)
    epoch: int = 0


@dataclass
class MOSDOpReplyBatch(Message):
    """A reply tick's acks for ONE client conn in ONE frame: each item
    is a complete MOSDOpReply (result, data, epoch, throttled, and the
    reply-leg trace all per item).  Ops the OSD SHED (expired deadline)
    are absent — their clients must stay un-acked, exactly the
    MOSDECSubOpWriteBatchReply per-item rule."""

    items: List[Any] = field(default_factory=list)


@dataclass
class MCommand(Message):
    """Daemon-directed admin command (reference MCommand / the admin
    socket surface: 'ceph tell osd.N <cmd>')."""

    tid: int = 0
    cmd: Dict[str, Any] = field(default_factory=dict)


@dataclass
class MCommandReply(Message):
    tid: int = 0
    result: int = 0
    data: Any = None


@dataclass
class MMgrReport(Message):
    """Perf-counter stream to the mgr (reference MMgrReport,
    MgrClient::send_report, src/mgr/MgrClient.cc:232)."""

    daemon: str = ""
    counters: Dict[str, Any] = field(default_factory=dict)
    stamp: float = 0.0


@dataclass
class MMgrBeacon(Message):
    """Mgr announces itself to the mon (reference MMgrBeacon)."""

    addr: Optional[Addr] = None


@dataclass
class MWatchNotify(Message):
    """Watcher callback delivery (reference MWatchNotify): sent by the
    primary OSD to every registered watcher when a notify op fires."""

    pool: int = -1
    oid: str = ""
    notify_id: int = 0
    cookie: int = 0
    payload: bytes = b""


# -- osd <-> osd (replication / EC / recovery) ------------------------------


@dataclass
class MOSDRepOp(Message):
    """Replica transaction (reference MOSDRepOp): carries the pg log entry
    so every member's log advances identically with the mutation."""

    reqid: Tuple[str, int] = ("", 0)
    pgid: Optional[PGid] = None
    txn_blob: bytes = b""
    entry: Any = None            # pglog.LogEntry
    epoch: int = 0
    # inherited from the parent client op (None for recovery traffic):
    # an expired sub-write is dead work — the primary's client is gone
    deadline: Optional[float] = None


@dataclass
class MOSDRepOpReply(Message):
    reqid: Tuple[str, int] = ("", 0)
    result: int = 0


@dataclass
class MOSDECSubOpWrite(Message):
    """Shard write (reference MOSDECSubOpWrite, ECBackend.cc:921).

    chunk_off/shard_size carry the RMW sub-range: data lands at chunk_off
    within the shard, which is then truncated/zero-extended to shard_size
    (zero stripes encode to zero parity — the code is linear — so extension
    commutes with encode)."""

    reqid: Tuple[str, int] = ("", 0)
    pgid: Optional[PGid] = None
    oid: str = ""
    shard: int = -1
    data: bytes = b""
    chunk_off: int = 0
    shard_size: Optional[int] = None
    # store-level ops applied atomically BEFORE the shard write (COW
    # clone of the pre-write shard, snapset persistence, clone trims) —
    # the shard-local analog of the replicated txn fan-out
    pre_ops: List[Tuple] = field(default_factory=list)
    hinfo: Dict[str, Any] = field(default_factory=dict)
    entry: Any = None            # pglog.LogEntry
    epoch: int = 0
    deadline: Optional[float] = None  # inherited parent-op deadline
    # at-rest layout of ``data`` (round 19): None = shard bytes;
    # "planar8" = the (8, len/8) packed bit-plane matrix row-major, to
    # be landed via Transaction.write_planar — wire, store, and kernel
    # agree on layout so the steady state never converts
    layout: Optional[str] = None


@dataclass
class MOSDECSubOpWriteReply(Message):
    reqid: Tuple[str, int] = ("", 0)
    result: int = 0


@dataclass
class MOSDECSubOpWriteBatch(Message):
    """A dispatch tick's shard sub-writes for ONE peer in ONE frame
    (round 11): each item is a complete MOSDECSubOpWrite, applied in
    list order.  Collapses the per-op frame/ack churn of the fan-out —
    the wire analog of the tick's coalesced encode."""

    items: List[Any] = field(default_factory=list)
    epoch: int = 0


@dataclass
class MOSDECSubOpWriteBatchReply(Message):
    """Per-item acks for a sub-write batch: (reqid, result, shard)
    triples.  Items the replica SHED (expired deadline) are absent —
    their primaries must stay un-acked, exactly like the unbatched
    path's no-reply contract."""

    results: List[Tuple] = field(default_factory=list)


@dataclass
class MOSDECSubOpRead(Message):
    """Shard read (reference handle_sub_read, ECBackend.cc:986).
    off/length select a chunk sub-range (None = whole shard)."""

    reqid: Tuple[str, int] = ("", 0)
    pgid: Optional[PGid] = None
    oid: str = ""
    shard: int = -1
    off: int = 0
    length: Optional[int] = None
    deadline: Optional[float] = None  # inherited parent-op deadline


@dataclass
class MOSDECSubOpReadReply(Message):
    reqid: Tuple[str, int] = ("", 0)
    result: int = 0
    shard: int = -1
    data: bytes = b""
    hinfo: Dict[str, Any] = field(default_factory=dict)
    # at-rest layout of ``data`` (round 19): None = shard bytes;
    # "planar8" = packed bit-planes straight off the store (full-shard
    # reads only — sub-range reads always ship bytes)
    layout: Optional[str] = None


@dataclass
class MOSDPGPush(Message):
    """Recovery push (reference push/pull recovery, ReplicatedBackend).
    op="push" writes the object; op="delete" removes it (a logged delete
    replayed onto a stale member)."""

    pgid: Optional[PGid] = None
    oid: str = ""
    shard: int = -1  # -1 for replicated full object
    op: str = "push"
    data: bytes = b""
    version: int = 0
    entry: Any = None            # pglog.LogEntry
    xattrs: Dict[str, bytes] = field(default_factory=dict)


@dataclass
class MOSDPGPushReply(Message):
    pgid: Optional[PGid] = None
    oid: str = ""
    result: int = 0


@dataclass
class MOSDScrub(Message):
    """Scrub-map request from the primary (reference MOSDRepScrub)."""

    reqid: Tuple[str, int] = ("", 0)
    pgid: Optional[PGid] = None


@dataclass
class MOSDScrubMap(Message):
    """Member's scrub map: oid -> (version, size, computed_crc,
    stored_crc) (reference ScrubMap exchange)."""

    reqid: Tuple[str, int] = ("", 0)
    pgid: Optional[PGid] = None
    objects: Dict[str, Tuple[int, int, int, Optional[int]]] = \
        field(default_factory=dict)
