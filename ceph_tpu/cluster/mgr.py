"""Mgr: the metrics/management daemon.

Behavioral mirror of the reference ceph-mgr core loop (src/mgr/): daemons
stream their perf counters as MMgrReport (MgrClient::send_report,
src/mgr/MgrClient.cc:232), the mgr keeps per-daemon state
(DaemonState/DaemonPerfCounters, src/mgr/DaemonState.h:65) and serves
aggregated views over admin commands — the substrate the reference's
dashboard/restful python modules sit on.

Round 6: a Prometheus-style exporter (the reference's mgr prometheus
module, src/pybind/mgr/prometheus/module.py) renders every reported
daemon's counters in the Prometheus text exposition format with
``daemon`` labels — u64 counters as plain gauges, time/avg counters as
``_sum``/``_count`` pairs, perf histograms as cumulative ``_bucket``
series — served both over the admin socket (``prometheus metrics``) and
an optional HTTP endpoint (``serve_exporter``).
"""

from __future__ import annotations

import asyncio
import pickle
import time
from typing import Any, Dict, Optional, Tuple

from ceph_tpu.balance import PgAutoscaler, Reshaper, UpmapBalancer
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import Addr, Connection, Dispatcher, EntityName, Messenger
from ceph_tpu.cluster.monclient import MonTargeter
from ceph_tpu.utils import AdminSocket, Config, KERNELS, PerfCountersCollection
from ceph_tpu.utils.backoff import ExpBackoff

# the graft-balance counter families, DECLARED (present-and-zero on the
# scrape) at mgr init whether or not the loops ever run: the SLO
# balance gate asserts presence, and a disabled subsystem showing
# all-zeros is the provable-no-op witness
_BALANCE_COUNTERS = (
    ("mgr_balancer_rounds", "balancer optimization rounds"),
    ("mgr_balancer_candidates", "candidate moves scored"),
    ("mgr_balancer_moves_proposed", "moves chosen by the optimizer"),
    ("mgr_balancer_moves_committed", "moves committed to the mon"),
    ("mgr_balancer_throttled", "rounds skipped for *full flags, "
                               "recovery pressure, or unclean health"),
    ("mgr_balancer_bytes_projected", "projected bytes the committed "
                                     "moves will shift"),
    ("mgr_balancer_skew_before_milli", "pg-per-osd stddev before the "
                                       "last round (x1000)"),
    ("mgr_balancer_skew_after_milli", "pg-per-osd stddev after the "
                                      "last round (x1000)"),
    ("mgr_autoscale_rounds", "autoscaler rounds"),
    ("mgr_autoscale_splits", "pg_num doublings issued"),
    ("mgr_autoscale_pgp_bumps", "pgp_num catch-ups issued"),
    ("mgr_reshape_grows", "grow operations started"),
    ("mgr_reshape_drains", "drain operations started"),
)


def _prom_name(counter: str) -> str:
    """Counter -> Prometheus metric name (the exporter module's
    sanitization: [a-zA-Z0-9_] only, 'ceph_' prefix)."""
    safe = "".join(c if c.isalnum() or c == "_" else "_"
                   for c in counter)
    return f"ceph_{safe}"


def render_prometheus(daemons: Dict[str, Dict]) -> str:
    """Render {daemon_name: {counter: value}} as Prometheus text format.

    Values may be ints (u64 counters), {"avgcount","sum",...} dicts
    (time/avg counters -> _sum + _count), or {"buckets","lower_bounds",
    ...} dicts (perf histograms -> cumulative _bucket + _sum + _count).
    Pure function so the format is testable without a cluster.
    """
    by_metric: Dict[str, list] = {}
    for daemon in sorted(daemons):
        counters = daemons[daemon]
        for name in sorted(counters):
            val = counters[name]
            metric = _prom_name(name)
            label = f'daemon="{daemon}"'
            if isinstance(val, dict) and "buckets" in val:
                rows = by_metric.setdefault(metric, [])
                cum = 0
                # le bounds must be in the SAME units as _sum (the raw
                # recorded value): un-apply the histogram's bucketing
                # scale (e.g. 1e6 for microsecond-bucketed latencies)
                scale = val.get("scale", 1.0) or 1.0
                for count, lb in zip(val["buckets"],
                                     val["lower_bounds"]):
                    cum += count
                    # bucket upper bound: the NEXT bucket's lower bound
                    # (bucket 0 spans scaled [0, 2), so its bound is 2)
                    ub = (lb * 2 if lb else 2) / scale
                    rows.append((f'{metric}_bucket{{{label},'
                                 f'le="{ub:g}"}}', cum))
                rows.append((f'{metric}_bucket{{{label},le="+Inf"}}',
                             val["count"]))
                rows.append((f"{metric}_count{{{label}}}", val["count"]))
                rows.append((f"{metric}_sum{{{label}}}", val["sum"]))
            elif isinstance(val, dict) and "avgcount" in val:
                rows = by_metric.setdefault(metric, [])
                rows.append((f"{metric}_count{{{label}}}",
                             val["avgcount"]))
                rows.append((f"{metric}_sum{{{label}}}", val["sum"]))
            elif isinstance(val, (int, float)):
                by_metric.setdefault(metric, []).append(
                    (f"{metric}{{{label}}}", val))
    lines = []
    for metric in sorted(by_metric):
        lines.append(f"# TYPE {metric} untyped")
        for series, value in by_metric[metric]:
            lines.append(f"{series} {value}")
    return "\n".join(lines) + "\n"


class MgrDaemon(Dispatcher):
    def __init__(self, mon_addr, config: Optional[Config] = None,
                 rank: int = 0):
        self.rank = rank
        # per-daemon config copy: injectargs on one daemon must never
        # leak into another (each reference daemon owns its md_config_t)
        self.config = Config(**config.show()) if config else Config()
        self.messenger = Messenger(
            EntityName("mgr", rank),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"mgr.{rank}"),
            config=self.config)
        self.messenger.add_dispatcher(self)
        self.monc = MonTargeter(self.messenger, mon_addr)
        self.perfcoll = PerfCountersCollection()
        self.perf = self.perfcoll.create(f"mgr.{rank}")
        self.perfcoll.register(KERNELS)
        # daemon -> {counters, last_report} (DaemonStateIndex analog)
        self.daemons: Dict[str, Dict] = {}
        self._stopped = False
        self._exporter = None
        self.exporter_addr: Optional[Tuple[str, int]] = None
        # graft-blackbox flight ring (NULL_FLIGHT when disabled)
        from ceph_tpu.trace import FlightRecorder

        self.flight = FlightRecorder.from_config(
            "mgr", self.config)
        # graft-balance: the policy subsystem.  Objects always exist
        # (admin commands work pull-driven); the LOOPS only start when
        # mgr_balancer_enabled / mgr_autoscale_enabled say so.
        for name, desc in _BALANCE_COUNTERS:
            self.perf.add_u64(name, desc=desc)
        self.osdmap = None
        self._mon_tid = 0
        self._mon_inflight: Dict[int, asyncio.Future] = {}
        self.balancer = UpmapBalancer(self)
        self.autoscaler = PgAutoscaler(self)
        self.reshaper = Reshaper(self)
        self.asok = self._build_admin_socket()

    def _build_admin_socket(self) -> AdminSocket:
        asok = AdminSocket()
        asok.register_common(self.perfcoll, self.config,
                             flight=self.flight)
        asok.register("mgr status",
                      lambda cmd: {
                          "daemons": sorted(self.daemons),
                          "reports": self.perf.get("mgr_reports"),
                      }, "reporting daemons + report count")
        asok.register("counter dump",
                      lambda cmd: {d: s["counters"]
                                   for d, s in self.daemons.items()},
                      "every reported daemon's raw counters")
        asok.register("counter sum", self._counter_sum,
                      "aggregate one counter across daemons")
        asok.register("prometheus metrics",
                      lambda cmd: self.prometheus_metrics(),
                      "Prometheus text-format exposition of all "
                      "daemons' counters")
        asok.register("balance status", self._cmd_balance_status,
                      "balancer/autoscaler last rounds + reshape ops "
                      "(advances open reshape ops)")
        asok.register("balance optimize",
                      lambda cmd: self.balancer.tick(
                          dry_run=bool(cmd.get("dry_run"))),
                      "run one balancer round now (dry_run=True plans "
                      "without committing)")
        asok.register("balance autoscale",
                      lambda cmd: self.autoscaler.tick(
                          dry_run=bool(cmd.get("dry_run"))),
                      "run one autoscaler round now")
        asok.register("balance grow",
                      lambda cmd: self.reshaper.grow(
                          int(cmd.get("count", 0)),
                          int(cmd.get("osds_per_host", 1) or 1)),
                      "mint new OSD ids + CRUSH hosts through the mon")
        asok.register("balance drain",
                      lambda cmd: self.reshaper.drain_osds(
                          [int(o) for o in cmd.get("osds", [])]),
                      "start draining OSDs (out -> wait-clean -> purge)")
        return asok

    async def _cmd_balance_status(self, cmd) -> Dict:
        # pull-driven advance: with the loops disabled, polling status
        # is what moves reshape ops forward (zero background activity)
        ops = await self.reshaper.advance()
        return {"enabled": bool(self.config.mgr_balancer_enabled),
                "autoscale_enabled": bool(self.config.mgr_autoscale_enabled),
                "vectorized": bool(self.config.mgr_balancer_vectorized),
                "epoch": self.osdmap.epoch if self.osdmap else 0,
                "last_round": self.balancer.last_round,
                "last_autoscale": self.autoscaler.last_round,
                "pools": self.autoscaler.pool_targets(),
                "reshape_ops": ops}

    def _counter_sum(self, cmd):
        name = cmd.get("counter", "")
        return sum(s["counters"].get(name, 0)
                   for s in self.daemons.values()
                   if isinstance(s["counters"].get(name, 0),
                                 (int, float)))

    def prometheus_metrics(self) -> str:
        """Every reported daemon's counters + the mgr's own, labeled."""
        all_daemons = {d: s["counters"] for d, s in self.daemons.items()}
        for name, counters in self.perfcoll.dump().items():
            all_daemons.setdefault(name, counters)
        return render_prometheus(all_daemons)

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        addr = await self.messenger.bind(host, port)
        # announce to the mon; the mon publishes us through the osdmap
        # (MgrMap analog) so daemons learn where to report.  Beacons
        # REPEAT: a single one can land on a leaderless mon mid-election
        # and be dropped silently (the mon only commits from its leader)
        await self.monc.send(M.MMgrBeacon(addr=addr), raise_on_fail=True)
        self._beacon_task = asyncio.get_event_loop().create_task(
            self._beacon_loop(addr))
        # follow the osdmap like any daemon: the balance subsystem plans
        # against the subscribed map, never a side-channel copy
        await self.monc.send(M.MMonSubscribe(what="osdmap", addr=addr),
                             raise_on_fail=True)
        if self.config.mgr_balancer_enabled:
            self._balance_task = asyncio.get_event_loop().create_task(
                self._balance_loop())
        if self.config.mgr_autoscale_enabled:
            self._autoscale_task = asyncio.get_event_loop().create_task(
                self._autoscale_loop())
        return addr

    async def _balance_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(
                max(0.05, self.config.mgr_balancer_interval))
            try:
                await self.reshaper.advance()
                await self.balancer.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # a failed round must not kill the policy loop; counted,
                # and the next round reads fresh state anyway
                self.perf.inc("mgr_balancer_round_errors")

    async def _autoscale_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(
                max(0.05, self.config.mgr_autoscale_interval))
            try:
                await self.autoscaler.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                self.perf.inc("mgr_autoscale_round_errors")

    async def mon_command(self, cmd: Dict[str, Any],
                          timeout: float = 10.0):
        """Objecter-style mon command from the mgr: tid-matched futures,
        capped jittered retry on -11 (leaderless quorum) and transport
        errors, RuntimeError on real failures."""
        deadline = asyncio.get_event_loop().time() + timeout * 3
        backoff = ExpBackoff(base=0.05, cap=1.0)
        last_err: Optional[BaseException] = None
        while asyncio.get_event_loop().time() < deadline:
            self._mon_tid += 1
            tid = self._mon_tid
            fut = asyncio.get_event_loop().create_future()
            self._mon_inflight[tid] = fut
            try:
                await self.monc.send(M.MMonCommand(cmd=cmd, tid=tid),
                                     raise_on_fail=True)
                reply = await asyncio.wait_for(fut, timeout=timeout)
            except (asyncio.TimeoutError, ConnectionError, OSError) as e:
                self._mon_inflight.pop(tid, None)
                last_err = e
                await asyncio.sleep(backoff.next())
                continue
            if reply.result == -11:   # no leader yet: retry
                last_err = RuntimeError(str(reply.data))
                await asyncio.sleep(backoff.next())
                continue
            if reply.result != 0:
                raise RuntimeError(f"mon command failed: {reply.data}")
            return reply.data
        raise TimeoutError(f"mgr mon command never succeeded: {last_err}")

    async def serve_exporter(self, host: str = "127.0.0.1",
                             port: int = 0) -> Tuple[str, int]:
        """Start the HTTP scrape endpoint (the prometheus module's
        StandbyModule server analog): GET anything -> text metrics."""
        self._exporter = await asyncio.start_server(
            self._serve_scrape, host, port)
        self.exporter_addr = self._exporter.sockets[0].getsockname()[:2]
        return self.exporter_addr

    async def _serve_scrape(self, reader, writer) -> None:
        try:
            # drain the request head; the path is irrelevant (every
            # scrape gets the full exposition).  Bounded: a client that
            # connects and never finishes its head must not wedge the
            # handler task for the life of the mgr
            async def _head():
                while True:
                    line = await reader.readline()
                    if not line or line in (b"\r\n", b"\n"):
                        return

            await asyncio.wait_for(_head(), timeout=5.0)
            body = self.prometheus_metrics().encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() +
                b"\r\nConnection: close\r\n\r\n" + body)
            await writer.drain()
            self.perf.inc("mgr_scrapes")
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
        finally:
            try:
                writer.close()
            except (ConnectionError, OSError, RuntimeError):
                pass  # best-effort close of a dying scrape socket

    async def _beacon_loop(self, addr: Addr) -> None:
        while not self._stopped:
            await asyncio.sleep(max(1.0, self.config.mon_lease_interval * 4))
            await self.monc.send(M.MMgrBeacon(addr=addr))

    async def stop(self) -> None:
        self._stopped = True
        for tname in ("_beacon_task", "_balance_task", "_autoscale_task"):
            t = getattr(self, tname, None)
            if t:
                t.cancel()
        if self._exporter is not None:
            self._exporter.close()
        await self.messenger.shutdown()
        self.perfcoll.remove(self.perf.name)

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MMgrReport):
            self.daemons[msg.daemon] = {
                "counters": msg.counters,
                "last_report": time.monotonic(),
            }
            self.perf.inc("mgr_reports")
            if self.flight and self.perf.get("mgr_reports") % 16 == 0:
                # sampled: the report stream is per-daemon-per-beacon;
                # one ring event every 16 keeps the box from being all
                # mgr traffic
                self.flight.record("report", daemon=msg.daemon)
            return True
        if isinstance(msg, M.MCommand):
            result, data = await self.asok.dispatch(msg.cmd)
            await conn.send(M.MCommandReply(tid=msg.tid, result=result,
                                            data=data))
            return True
        if isinstance(msg, M.MOSDMapMsg):
            newmap = pickle.loads(msg.osdmap_blob)
            if self.osdmap is None or newmap.epoch >= self.osdmap.epoch:
                self.osdmap = newmap
            return True
        if isinstance(msg, M.MOSDIncMapMsg):
            m = self.osdmap
            if m is not None and msg.prev_epoch == m.epoch:
                for blob in msg.inc_blobs:
                    m.apply_incremental(pickle.loads(blob))
            elif m is not None and msg.epoch <= m.epoch:
                pass  # already current
            else:
                # gap: resync from our epoch (objecter's recovery move)
                await self.monc.send(M.MMonSubscribe(
                    what="osdmap", addr=self.messenger.my_addr,
                    since=m.epoch if m else 0))
            return True
        if isinstance(msg, M.MMonCommandReply):
            fut = self._mon_inflight.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        return False
