"""Mgr: the metrics/management daemon.

Behavioral mirror of the reference ceph-mgr core loop (src/mgr/): daemons
stream their perf counters as MMgrReport (MgrClient::send_report,
src/mgr/MgrClient.cc:232), the mgr keeps per-daemon state
(DaemonState/DaemonPerfCounters, src/mgr/DaemonState.h:65) and serves
aggregated views over admin commands — the substrate the reference's
dashboard/restful python modules sit on.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import Addr, Connection, Dispatcher, EntityName, Messenger
from ceph_tpu.cluster.monclient import MonTargeter
from ceph_tpu.utils import Config, PerfCounters


class MgrDaemon(Dispatcher):
    def __init__(self, mon_addr, config: Optional[Config] = None,
                 rank: int = 0):
        self.rank = rank
        # per-daemon config copy: injectargs on one daemon must never
        # leak into another (each reference daemon owns its md_config_t)
        self.config = Config(**config.show()) if config else Config()
        self.messenger = Messenger(
            EntityName("mgr", rank),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"mgr.{rank}"))
        self.messenger.add_dispatcher(self)
        self.monc = MonTargeter(self.messenger, mon_addr)
        self.perf = PerfCounters(f"mgr.{rank}")
        # daemon -> {counters, last_report} (DaemonStateIndex analog)
        self.daemons: Dict[str, Dict] = {}
        self._stopped = False

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        addr = await self.messenger.bind(host, port)
        # announce to the mon; the mon publishes us through the osdmap
        # (MgrMap analog) so daemons learn where to report.  Beacons
        # REPEAT: a single one can land on a leaderless mon mid-election
        # and be dropped silently (the mon only commits from its leader)
        await self.monc.send(M.MMgrBeacon(addr=addr), raise_on_fail=True)
        self._beacon_task = asyncio.get_event_loop().create_task(
            self._beacon_loop(addr))
        return addr

    async def _beacon_loop(self, addr: Addr) -> None:
        while not self._stopped:
            await asyncio.sleep(max(1.0, self.config.mon_lease_interval * 4))
            await self.monc.send(M.MMgrBeacon(addr=addr))

    async def stop(self) -> None:
        self._stopped = True
        if getattr(self, "_beacon_task", None):
            self._beacon_task.cancel()
        await self.messenger.shutdown()

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MMgrReport):
            self.daemons[msg.daemon] = {
                "counters": msg.counters,
                "last_report": time.monotonic(),
            }
            self.perf.inc("mgr_reports")
            return True
        if isinstance(msg, M.MCommand):
            result, data = 0, None
            prefix = msg.cmd.get("prefix")
            if prefix == "mgr status":
                data = {
                    "daemons": sorted(self.daemons),
                    "reports": self.perf.get("mgr_reports"),
                }
            elif prefix == "counter dump":
                data = {d: s["counters"] for d, s in self.daemons.items()}
            elif prefix == "counter sum":
                # aggregate one counter across daemons
                name = msg.cmd.get("counter", "")
                data = sum(s["counters"].get(name, 0)
                           for s in self.daemons.values())
            else:
                result = -22
            await conn.send(M.MCommandReply(tid=msg.tid, result=result,
                                            data=data))
            return True
        return False
