"""ObjectStore + Transaction, with a MemStore implementation.

Mirrors the reference's storage contract (src/os/ObjectStore.h:1470-1498):
every mutation is an ordered, atomic Transaction of typed ops applied to
collections of objects (data + xattrs + omap), and MemStore
(src/os/memstore/MemStore.cc) is the in-RAM implementation backing tests
and the dev cluster.
"""

from __future__ import annotations

import pickle
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster.optracker import mark_current
from ceph_tpu.ec import planar_store


@dataclass
class Obj:
    data: bytearray = field(default_factory=bytearray)
    xattrs: Dict[str, bytes] = field(default_factory=dict)
    omap: Dict[str, bytes] = field(default_factory=dict)
    version: int = 0
    # at-rest data layout: None = classic bytes; planar_store.LAYOUT_PLANAR
    # means ``data`` holds the shard's (8, L/8) packed bit-plane matrix
    # serialized row-major (round 19).  Same byte length either way, so
    # _used/statfs/stat need no layout awareness.
    layout: Optional[str] = None


class Transaction:
    """Ordered op list; atomic at queue_transaction."""

    def __init__(self):
        self.ops: List[Tuple] = []

    def create_collection(self, coll: str):
        self.ops.append(("create_collection", coll))
        return self

    def remove_collection(self, coll: str):
        self.ops.append(("remove_collection", coll))
        return self

    def write(self, coll: str, oid: str, offset: int, data: bytes):
        self.ops.append(("write", coll, oid, offset, bytes(data)))
        return self

    def write_planar(self, coll: str, oid: str, plane_off: int,
                     data: bytes, total_cols: int):
        """Planar-at-rest shard write (round 19): land ``data`` — an
        (8, wc) plane-column window serialized row-major — at plane
        column ``plane_off`` (= byte offset / 8) and size the object to
        exactly ``total_cols`` columns (= shard bytes / 8).  One op
        covers the byte path's write+truncate pair, and the object's
        layout becomes planar."""
        self.ops.append(("write_planar", coll, oid, plane_off,
                         bytes(data), total_cols))
        return self

    def truncate(self, coll: str, oid: str, size: int):
        self.ops.append(("truncate", coll, oid, size))
        return self

    def remove(self, coll: str, oid: str):
        self.ops.append(("remove", coll, oid))
        return self

    def clone(self, coll: str, src: str, dst: str):
        """Full-object copy (data + xattrs + omap), the COW primitive of
        the snapshot axis (reference ObjectStore::Transaction::clone)."""
        self.ops.append(("clone", coll, src, dst))
        return self

    def rb_capture(self, coll: str, oid: str, rb_oid: str, key: str):
        """Snapshot THIS store's current state of ``oid`` into the
        rollback journal object's omap under ``key`` — evaluated locally
        by each member so a fanned-out transaction captures each member's
        OWN pre-op bytes (EC shards differ per member; the reference
        attaches rollback info to the local transaction the same way,
        ecbackend.rst:10-27)."""
        self.ops.append(("rb_capture", coll, oid, rb_oid, key))
        return self

    def setattr(self, coll: str, oid: str, name: str, value: bytes):
        self.ops.append(("setattr", coll, oid, name, bytes(value)))
        return self

    def rmattr(self, coll: str, oid: str, name: str):
        self.ops.append(("rmattr", coll, oid, name))
        return self

    def omap_set(self, coll: str, oid: str, kv: Dict[str, bytes]):
        self.ops.append(("omap_set", coll, oid, dict(kv)))
        return self

    def omap_rmkeys(self, coll: str, oid: str, keys: List[str]):
        self.ops.append(("omap_rmkeys", coll, oid, list(keys)))
        return self

    def touch(self, coll: str, oid: str):
        self.ops.append(("touch", coll, oid))
        return self

    def set_version(self, coll: str, oid: str, version: int):
        self.ops.append(("set_version", coll, oid, version))
        return self

    def encode(self) -> bytes:
        return pickle.dumps(self.ops)

    @classmethod
    def decode(cls, blob: bytes) -> "Transaction":
        t = cls()
        t.ops = pickle.loads(blob)
        return t


class ObjectStore:
    # disk fault injector (ceph_tpu/chaos/disk.py DiskInjector), the
    # filestore_debug_inject_read_err analog; None (the default) keeps
    # every hot path to a single `is None` test
    chaos = None

    def mount(self) -> None: ...

    def umount(self) -> None: ...

    def debug_bitrot(self, coll: str, oid: str, bit: int) -> None:
        """Flip one stored bit WITHOUT touching any checksum — the
        silent-corruption seam the disk injector drives."""
        raise NotImplementedError

    def statfs(self) -> Tuple[int, int]:
        """(total_bytes, used_bytes) — reference ObjectStore::statfs."""
        return (0, 0)

    def queue_transaction(self, txn: Transaction) -> None:
        raise NotImplementedError

    def read(self, coll: str, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        raise NotImplementedError

    def read_planar(self, coll: str, oid: str) -> bytes:
        raise NotImplementedError

    def object_layout(self, coll: str, oid: str) -> Optional[str]:
        """At-rest layout tag (None = bytes / missing / unsupported)."""
        return None

    def stat(self, coll: str, oid: str) -> Optional[int]:
        raise NotImplementedError


class MemStore(ObjectStore):
    def __init__(self, device_bytes: int = 1 << 30):
        self._colls: Dict[str, Dict[str, Obj]] = {}
        self._lock = threading.RLock()
        # advertised AND enforced capacity (memstore_device_bytes
        # analog): statfs reports against it, and (round 16) a
        # transaction whose net data growth would exceed it is refused
        # whole with ENOSPC — the store-level backstop beneath the
        # mon's full-flag protection.  Used bytes are maintained
        # incrementally (_used) so neither statfs nor admission pays an
        # all-objects scan on the hot path.
        self.device_bytes = device_bytes
        self._used = 0

    # -- transaction application (atomic under lock) -----------------------

    def _txn_growth(self, txn: Transaction) -> int:
        """Net DATA bytes this transaction would add (write extensions,
        upward truncates, clones), credited for its own removes/shrinks
        — so a delete-and-rewrite txn admits whenever its net effect
        fits.  Attr/omap bytes are not counted, matching statfs."""
        grow = 0
        sizes: Dict[Tuple[str, str], int] = {}

        def cur(coll: str, oid: str) -> int:
            key = (coll, oid)
            if key not in sizes:
                o = self._colls.get(coll, {}).get(oid)
                sizes[key] = len(o.data) if o is not None else 0
            return sizes[key]

        for op in txn.ops:
            kind = op[0]
            if kind == "write":
                _, coll, oid, offset, data = op
                new = max(cur(coll, oid), offset + len(data))
                grow += new - sizes[(coll, oid)]
                sizes[(coll, oid)] = new
            elif kind == "write_planar":
                _, coll, oid, _plane_off, _data, total_cols = op
                # one op fixes the final size exactly: 8 plane rows of
                # total_cols packed bytes == the shard's byte length, so
                # planar admission counts TRUE plane bytes (satellite:
                # same ENOSPC behavior as the byte anchor)
                new = 8 * total_cols
                grow += new - cur(coll, oid)
                sizes[(coll, oid)] = new
            elif kind == "truncate":
                _, coll, oid, size = op
                grow += size - cur(coll, oid)
                sizes[(coll, oid)] = size
            elif kind == "clone":
                _, coll, src, dst = op
                grow += cur(coll, src) - cur(coll, dst)
                sizes[(coll, dst)] = sizes[(coll, src)]
            elif kind == "remove":
                _, coll, oid = op
                grow -= cur(coll, oid)
                sizes[(coll, oid)] = 0
            elif kind == "remove_collection":
                for oid, o in self._colls.get(op[1], {}).items():
                    grow -= len(o.data)
                    sizes[(op[1], oid)] = 0
        return grow

    def _check_capacity(self, txn: Transaction) -> None:
        """Refuse a transaction whose net data growth would exceed the
        enforced capacity — WHOLE, before any byte lands (atomicity,
        like the injected ENOSPC).  Deletes and shrinks (grow <= 0)
        always admit, so a full store can dig itself out.  Shared by
        MemStore and the journal-backed FileStore subclass (which must
        check BEFORE journaling, or replay would re-meet the frame)."""
        if not self.device_bytes:
            return
        with self._lock:
            grow = self._txn_growth(txn)
            if grow > 0 and self._used + grow > self.device_bytes:
                raise OSError(
                    28, f"store full: {self._used} used + "
                        f"{grow} > {self.device_bytes}")

    def queue_transaction(self, txn: Transaction) -> None:
        if self.chaos is not None:
            # injected ENOSPC refuses the WHOLE txn before any byte
            # lands (atomicity preserved)
            self.chaos.on_write(txn)
        self._check_capacity(txn)
        self._commit(txn)
        if self.chaos is not None:
            self.chaos.maybe_rot(self, txn)
        # store-commit boundary on the current op's timeline (no-op
        # outside a tracked dispatch — recovery, replicas, scrub)
        mark_current("store:commit")

    def _commit(self, txn: Transaction) -> None:
        with self._lock:
            for op in txn.ops:
                self._apply(op)

    def _apply(self, op: Tuple) -> None:
        kind = op[0]
        if kind == "create_collection":
            self._colls.setdefault(op[1], {})
        elif kind == "remove_collection":
            dropped = self._colls.pop(op[1], None)
            if dropped:
                self._used -= sum(len(o.data) for o in dropped.values())
        elif kind == "touch":
            self._coll(op[1]).setdefault(op[2], Obj())
        elif kind == "write":
            _, coll, oid, offset, data = op
            o = self._coll(coll).setdefault(oid, Obj())
            old = len(o.data)
            end = offset + len(data)
            if o.layout == planar_store.LAYOUT_PLANAR:
                # byte write onto a planar object: the object leaves
                # planar-at-rest.  A full rewrite just drops the layout;
                # a partial overlay must land on LOGICAL bytes, so
                # materialize once (counted relayout) before splicing.
                if not (offset == 0 and old <= end):
                    o.data[:] = planar_store.planes_to_shard(
                        planar_store.blob_to_planes(bytes(o.data)),
                        seam="relayout")
                o.layout = None
            if offset == 0 and len(o.data) <= end:
                # full rewrite/extend from 0 (the EC full-shard write):
                # one copy, no zero-fill of bytes about to be replaced
                o.data[:] = data
            else:
                if len(o.data) < end:
                    o.data.extend(b"\0" * (end - len(o.data)))
                o.data[offset:end] = data
            o.version += 1
            self._used += len(o.data) - old
        elif kind == "write_planar":
            _, coll, oid, plane_off, data, total_cols = op
            o = self._coll(coll).setdefault(oid, Obj())
            old = len(o.data)
            window = planar_store.blob_to_planes(data)
            if o.data and o.layout == planar_store.LAYOUT_PLANAR:
                cur = planar_store.blob_to_planes(bytes(o.data))
            elif o.data:
                # a planar write landing on a byte-at-rest object: the
                # config gate flipped mid-life — convert once, counted
                # (zero-pad to the 8-byte packing quantum; EC shards are
                # stripe-unit aligned so this is a non-EC-object guard)
                raw = bytes(o.data)
                if len(raw) % 8:
                    raw += b"\0" * (8 - len(raw) % 8)
                cur = planar_store.shard_to_planes(raw, seam="relayout")
            else:
                cur = None
            merged = planar_store.splice_columns(
                cur, plane_off, window, total_cols)
            o.data[:] = planar_store.planes_to_blob(merged)
            o.layout = planar_store.LAYOUT_PLANAR
            o.version += 1
            self._used += len(o.data) - old
        elif kind == "truncate":
            _, coll, oid, size = op
            o = self._coll(coll).setdefault(oid, Obj())
            old = len(o.data)
            if o.layout == planar_store.LAYOUT_PLANAR and old != size:
                # byte truncate of a planar object cuts PLANE ROWS, not
                # logical bytes — leave planar first (counted relayout)
                o.data[:] = planar_store.planes_to_shard(
                    planar_store.blob_to_planes(bytes(o.data)),
                    seam="relayout")
                o.layout = None
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
            o.version += 1
            self._used += len(o.data) - old
        elif kind == "remove":
            dropped = self._coll(op[1]).pop(op[2], None)
            if dropped is not None:
                self._used -= len(dropped.data)
        elif kind == "clone":
            _, coll, src, dst = op
            s = self._coll(coll).get(src)
            if s is not None:
                prev = self._coll(coll).get(dst)
                self._used += len(s.data) - \
                    (len(prev.data) if prev is not None else 0)
                self._coll(coll)[dst] = Obj(
                    data=bytearray(s.data), xattrs=dict(s.xattrs),
                    omap=dict(s.omap), version=s.version,
                    layout=s.layout)
        elif kind == "rb_capture":
            _, coll, oid, rb_oid, key = op
            o = self._coll(coll).get(oid)
            rec = {
                "oid": oid, "existed": o is not None, "chunk_off": 0,
                "old_range": bytes(o.data) if o else b"",
                "old_total": len(o.data) if o else 0,
                "old_attrs": ({k: o.xattrs.get(k)
                               for k in ("shard", "size", "hinfo_crc")}
                              if o else {}),
                "old_version": o.version if o else 0,
                # at-rest layout travels with the rollback record so a
                # rewind restores planar objects AS planar (pg.py
                # rewind_divergent_log dispatches on it)
                "layout": o.layout if o else None,
            }
            self._coll(coll).setdefault(rb_oid, Obj()).omap[key] = \
                pickle.dumps(rec)
        elif kind == "setattr":
            _, coll, oid, name, value = op
            self._coll(coll).setdefault(oid, Obj()).xattrs[name] = value
        elif kind == "rmattr":
            _, coll, oid, name = op
            o = self._coll(coll).get(oid)
            if o is not None:
                o.xattrs.pop(name, None)
        elif kind == "omap_set":
            _, coll, oid, kv = op
            self._coll(coll).setdefault(oid, Obj()).omap.update(kv)
        elif kind == "omap_rmkeys":
            _, coll, oid, keys = op
            o = self._coll(coll).get(oid)
            if o is not None:
                for k in keys:
                    o.omap.pop(k, None)
        elif kind == "set_version":
            _, coll, oid, version = op
            self._coll(coll).setdefault(oid, Obj()).version = version
        else:
            raise ValueError(f"unknown transaction op {kind}")

    def _coll(self, coll: str) -> Dict[str, Obj]:
        return self._colls.setdefault(coll, {})

    # -- reads -------------------------------------------------------------

    def read(self, coll: str, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        if self.chaos is not None:
            self.chaos.on_read(coll, oid)
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            if o is None:
                raise FileNotFoundError(f"{coll}/{oid}")
            if o.layout == planar_store.LAYOUT_PLANAR and o.data:
                # byte view of a planar object OUTSIDE the sanctioned
                # seams (egress of last resort): correct, but it books
                # the ``unseamed`` counter the steady-state contract
                # pins to zero — EC hot paths must use read_planar.
                data = planar_store.planes_to_shard(  # graftlint: ignore[planar-conversion-hygiene]
                    planar_store.blob_to_planes(bytes(o.data)),
                    seam="unseamed")
                if length is None:
                    return data[offset:]
                return data[offset : offset + length]
            if length is None:
                return bytes(o.data[offset:])
            return bytes(o.data[offset : offset + length])

    def read_planar(self, coll: str, oid: str) -> bytes:
        """The at-rest plane blob of a planar object, as stored — ZERO
        layout conversion.  Callers gate on object_layout first; a
        byte-at-rest object raises (mixed generations are the caller's
        relayout decision, not a silent conversion here)."""
        if self.chaos is not None:
            self.chaos.on_read(coll, oid)
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            if o is None:
                raise FileNotFoundError(f"{coll}/{oid}")
            if o.layout != planar_store.LAYOUT_PLANAR:
                raise ValueError(f"{coll}/{oid} is not planar-at-rest")
            return bytes(o.data)

    def object_layout(self, coll: str, oid: str) -> Optional[str]:
        """At-rest layout tag (None = bytes / missing object)."""
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return None if o is None else o.layout

    def debug_bitrot(self, coll: str, oid: str, bit: int) -> None:
        """Silent in-place bit flip (no version bump, no attr change):
        only a checksum-verifying reader — deep scrub comparing against
        the stored hinfo crc — can tell."""
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            if o is None or not o.data:
                raise FileNotFoundError(f"{coll}/{oid}")
            byte, shift = divmod(bit % (len(o.data) * 8), 8)
            o.data[byte] ^= 1 << shift

    def stat(self, coll: str, oid: str) -> Optional[int]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return None if o is None else len(o.data)

    def get_version(self, coll: str, oid: str) -> int:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return 0 if o is None else o.version

    def getattr(self, coll: str, oid: str, name: str) -> Optional[bytes]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return None if o is None else o.xattrs.get(name)

    def omap_get(self, coll: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return {} if o is None else dict(o.omap)

    def get_xattrs(self, coll: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return {} if o is None else dict(o.xattrs)

    def list_objects(self, coll: str) -> List[str]:
        with self._lock:
            return sorted(self._colls.get(coll, {}))

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(self._colls)

    def _recount_used(self) -> None:
        """Rebuild the incremental used-bytes counter from the object
        map — for mount paths that restore ``_colls`` wholesale (the
        FileStore checkpoint load) instead of replaying ops."""
        with self._lock:
            self._used = sum(len(o.data) for c in self._colls.values()
                             for o in c.values())

    def statfs(self) -> Tuple[int, int]:
        with self._lock:
            return (self.device_bytes, self._used)
