"""ObjectStore + Transaction, with a MemStore implementation.

Mirrors the reference's storage contract (src/os/ObjectStore.h:1470-1498):
every mutation is an ordered, atomic Transaction of typed ops applied to
collections of objects (data + xattrs + omap), and MemStore
(src/os/memstore/MemStore.cc) is the in-RAM implementation backing tests
and the dev cluster.
"""

from __future__ import annotations

import pickle
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ceph_tpu.cluster.optracker import mark_current


@dataclass
class Obj:
    data: bytearray = field(default_factory=bytearray)
    xattrs: Dict[str, bytes] = field(default_factory=dict)
    omap: Dict[str, bytes] = field(default_factory=dict)
    version: int = 0


class Transaction:
    """Ordered op list; atomic at queue_transaction."""

    def __init__(self):
        self.ops: List[Tuple] = []

    def create_collection(self, coll: str):
        self.ops.append(("create_collection", coll))
        return self

    def remove_collection(self, coll: str):
        self.ops.append(("remove_collection", coll))
        return self

    def write(self, coll: str, oid: str, offset: int, data: bytes):
        self.ops.append(("write", coll, oid, offset, bytes(data)))
        return self

    def truncate(self, coll: str, oid: str, size: int):
        self.ops.append(("truncate", coll, oid, size))
        return self

    def remove(self, coll: str, oid: str):
        self.ops.append(("remove", coll, oid))
        return self

    def clone(self, coll: str, src: str, dst: str):
        """Full-object copy (data + xattrs + omap), the COW primitive of
        the snapshot axis (reference ObjectStore::Transaction::clone)."""
        self.ops.append(("clone", coll, src, dst))
        return self

    def rb_capture(self, coll: str, oid: str, rb_oid: str, key: str):
        """Snapshot THIS store's current state of ``oid`` into the
        rollback journal object's omap under ``key`` — evaluated locally
        by each member so a fanned-out transaction captures each member's
        OWN pre-op bytes (EC shards differ per member; the reference
        attaches rollback info to the local transaction the same way,
        ecbackend.rst:10-27)."""
        self.ops.append(("rb_capture", coll, oid, rb_oid, key))
        return self

    def setattr(self, coll: str, oid: str, name: str, value: bytes):
        self.ops.append(("setattr", coll, oid, name, bytes(value)))
        return self

    def rmattr(self, coll: str, oid: str, name: str):
        self.ops.append(("rmattr", coll, oid, name))
        return self

    def omap_set(self, coll: str, oid: str, kv: Dict[str, bytes]):
        self.ops.append(("omap_set", coll, oid, dict(kv)))
        return self

    def omap_rmkeys(self, coll: str, oid: str, keys: List[str]):
        self.ops.append(("omap_rmkeys", coll, oid, list(keys)))
        return self

    def touch(self, coll: str, oid: str):
        self.ops.append(("touch", coll, oid))
        return self

    def set_version(self, coll: str, oid: str, version: int):
        self.ops.append(("set_version", coll, oid, version))
        return self

    def encode(self) -> bytes:
        return pickle.dumps(self.ops)

    @classmethod
    def decode(cls, blob: bytes) -> "Transaction":
        t = cls()
        t.ops = pickle.loads(blob)
        return t


class ObjectStore:
    # disk fault injector (ceph_tpu/chaos/disk.py DiskInjector), the
    # filestore_debug_inject_read_err analog; None (the default) keeps
    # every hot path to a single `is None` test
    chaos = None

    def mount(self) -> None: ...

    def umount(self) -> None: ...

    def debug_bitrot(self, coll: str, oid: str, bit: int) -> None:
        """Flip one stored bit WITHOUT touching any checksum — the
        silent-corruption seam the disk injector drives."""
        raise NotImplementedError

    def statfs(self) -> Tuple[int, int]:
        """(total_bytes, used_bytes) — reference ObjectStore::statfs."""
        return (0, 0)

    def queue_transaction(self, txn: Transaction) -> None:
        raise NotImplementedError

    def read(self, coll: str, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        raise NotImplementedError

    def stat(self, coll: str, oid: str) -> Optional[int]:
        raise NotImplementedError


class MemStore(ObjectStore):
    def __init__(self, device_bytes: int = 1 << 30):
        self._colls: Dict[str, Dict[str, Obj]] = {}
        self._lock = threading.RLock()
        # advertised AND enforced capacity (memstore_device_bytes
        # analog): statfs reports against it, and (round 16) a
        # transaction whose net data growth would exceed it is refused
        # whole with ENOSPC — the store-level backstop beneath the
        # mon's full-flag protection.  Used bytes are maintained
        # incrementally (_used) so neither statfs nor admission pays an
        # all-objects scan on the hot path.
        self.device_bytes = device_bytes
        self._used = 0

    # -- transaction application (atomic under lock) -----------------------

    def _txn_growth(self, txn: Transaction) -> int:
        """Net DATA bytes this transaction would add (write extensions,
        upward truncates, clones), credited for its own removes/shrinks
        — so a delete-and-rewrite txn admits whenever its net effect
        fits.  Attr/omap bytes are not counted, matching statfs."""
        grow = 0
        sizes: Dict[Tuple[str, str], int] = {}

        def cur(coll: str, oid: str) -> int:
            key = (coll, oid)
            if key not in sizes:
                o = self._colls.get(coll, {}).get(oid)
                sizes[key] = len(o.data) if o is not None else 0
            return sizes[key]

        for op in txn.ops:
            kind = op[0]
            if kind == "write":
                _, coll, oid, offset, data = op
                new = max(cur(coll, oid), offset + len(data))
                grow += new - sizes[(coll, oid)]
                sizes[(coll, oid)] = new
            elif kind == "truncate":
                _, coll, oid, size = op
                grow += size - cur(coll, oid)
                sizes[(coll, oid)] = size
            elif kind == "clone":
                _, coll, src, dst = op
                grow += cur(coll, src) - cur(coll, dst)
                sizes[(coll, dst)] = sizes[(coll, src)]
            elif kind == "remove":
                _, coll, oid = op
                grow -= cur(coll, oid)
                sizes[(coll, oid)] = 0
            elif kind == "remove_collection":
                for oid, o in self._colls.get(op[1], {}).items():
                    grow -= len(o.data)
                    sizes[(op[1], oid)] = 0
        return grow

    def _check_capacity(self, txn: Transaction) -> None:
        """Refuse a transaction whose net data growth would exceed the
        enforced capacity — WHOLE, before any byte lands (atomicity,
        like the injected ENOSPC).  Deletes and shrinks (grow <= 0)
        always admit, so a full store can dig itself out.  Shared by
        MemStore and the journal-backed FileStore subclass (which must
        check BEFORE journaling, or replay would re-meet the frame)."""
        if not self.device_bytes:
            return
        with self._lock:
            grow = self._txn_growth(txn)
            if grow > 0 and self._used + grow > self.device_bytes:
                raise OSError(
                    28, f"store full: {self._used} used + "
                        f"{grow} > {self.device_bytes}")

    def queue_transaction(self, txn: Transaction) -> None:
        if self.chaos is not None:
            # injected ENOSPC refuses the WHOLE txn before any byte
            # lands (atomicity preserved)
            self.chaos.on_write(txn)
        self._check_capacity(txn)
        self._commit(txn)
        if self.chaos is not None:
            self.chaos.maybe_rot(self, txn)
        # store-commit boundary on the current op's timeline (no-op
        # outside a tracked dispatch — recovery, replicas, scrub)
        mark_current("store:commit")

    def _commit(self, txn: Transaction) -> None:
        with self._lock:
            for op in txn.ops:
                self._apply(op)

    def _apply(self, op: Tuple) -> None:
        kind = op[0]
        if kind == "create_collection":
            self._colls.setdefault(op[1], {})
        elif kind == "remove_collection":
            dropped = self._colls.pop(op[1], None)
            if dropped:
                self._used -= sum(len(o.data) for o in dropped.values())
        elif kind == "touch":
            self._coll(op[1]).setdefault(op[2], Obj())
        elif kind == "write":
            _, coll, oid, offset, data = op
            o = self._coll(coll).setdefault(oid, Obj())
            old = len(o.data)
            end = offset + len(data)
            if offset == 0 and len(o.data) <= end:
                # full rewrite/extend from 0 (the EC full-shard write):
                # one copy, no zero-fill of bytes about to be replaced
                o.data[:] = data
            else:
                if len(o.data) < end:
                    o.data.extend(b"\0" * (end - len(o.data)))
                o.data[offset:end] = data
            o.version += 1
            self._used += len(o.data) - old
        elif kind == "truncate":
            _, coll, oid, size = op
            o = self._coll(coll).setdefault(oid, Obj())
            old = len(o.data)
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
            o.version += 1
            self._used += len(o.data) - old
        elif kind == "remove":
            dropped = self._coll(op[1]).pop(op[2], None)
            if dropped is not None:
                self._used -= len(dropped.data)
        elif kind == "clone":
            _, coll, src, dst = op
            s = self._coll(coll).get(src)
            if s is not None:
                prev = self._coll(coll).get(dst)
                self._used += len(s.data) - \
                    (len(prev.data) if prev is not None else 0)
                self._coll(coll)[dst] = Obj(
                    data=bytearray(s.data), xattrs=dict(s.xattrs),
                    omap=dict(s.omap), version=s.version)
        elif kind == "rb_capture":
            _, coll, oid, rb_oid, key = op
            o = self._coll(coll).get(oid)
            rec = {
                "oid": oid, "existed": o is not None, "chunk_off": 0,
                "old_range": bytes(o.data) if o else b"",
                "old_total": len(o.data) if o else 0,
                "old_attrs": ({k: o.xattrs.get(k)
                               for k in ("shard", "size", "hinfo_crc")}
                              if o else {}),
                "old_version": o.version if o else 0,
            }
            self._coll(coll).setdefault(rb_oid, Obj()).omap[key] = \
                pickle.dumps(rec)
        elif kind == "setattr":
            _, coll, oid, name, value = op
            self._coll(coll).setdefault(oid, Obj()).xattrs[name] = value
        elif kind == "rmattr":
            _, coll, oid, name = op
            o = self._coll(coll).get(oid)
            if o is not None:
                o.xattrs.pop(name, None)
        elif kind == "omap_set":
            _, coll, oid, kv = op
            self._coll(coll).setdefault(oid, Obj()).omap.update(kv)
        elif kind == "omap_rmkeys":
            _, coll, oid, keys = op
            o = self._coll(coll).get(oid)
            if o is not None:
                for k in keys:
                    o.omap.pop(k, None)
        elif kind == "set_version":
            _, coll, oid, version = op
            self._coll(coll).setdefault(oid, Obj()).version = version
        else:
            raise ValueError(f"unknown transaction op {kind}")

    def _coll(self, coll: str) -> Dict[str, Obj]:
        return self._colls.setdefault(coll, {})

    # -- reads -------------------------------------------------------------

    def read(self, coll: str, oid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        if self.chaos is not None:
            self.chaos.on_read(coll, oid)
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            if o is None:
                raise FileNotFoundError(f"{coll}/{oid}")
            if length is None:
                return bytes(o.data[offset:])
            return bytes(o.data[offset : offset + length])

    def debug_bitrot(self, coll: str, oid: str, bit: int) -> None:
        """Silent in-place bit flip (no version bump, no attr change):
        only a checksum-verifying reader — deep scrub comparing against
        the stored hinfo crc — can tell."""
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            if o is None or not o.data:
                raise FileNotFoundError(f"{coll}/{oid}")
            byte, shift = divmod(bit % (len(o.data) * 8), 8)
            o.data[byte] ^= 1 << shift

    def stat(self, coll: str, oid: str) -> Optional[int]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return None if o is None else len(o.data)

    def get_version(self, coll: str, oid: str) -> int:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return 0 if o is None else o.version

    def getattr(self, coll: str, oid: str, name: str) -> Optional[bytes]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return None if o is None else o.xattrs.get(name)

    def omap_get(self, coll: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return {} if o is None else dict(o.omap)

    def get_xattrs(self, coll: str, oid: str) -> Dict[str, bytes]:
        with self._lock:
            o = self._colls.get(coll, {}).get(oid)
            return {} if o is None else dict(o.xattrs)

    def list_objects(self, coll: str) -> List[str]:
        with self._lock:
            return sorted(self._colls.get(coll, {}))

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(self._colls)

    def _recount_used(self) -> None:
        """Rebuild the incremental used-bytes counter from the object
        map — for mount paths that restore ``_colls`` wholesale (the
        FileStore checkpoint load) instead of replaying ops."""
        with self._lock:
            self._used = sum(len(o.data) for c in self._colls.values()
                             for o in c.values())

    def statfs(self) -> Tuple[int, int]:
        with self._lock:
            return (self.device_bytes, self._used)
