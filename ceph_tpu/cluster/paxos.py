"""Elector + Paxos: the multi-monitor quorum machinery.

Behavioral mirror of the reference monitor consensus stack:

- Elector (src/mon/Elector.cc): rank-based leader election — a candidate
  proposes with a bumped election epoch, defers (acks) to lower ranks,
  and declares victory when a majority acked and no lower rank spoke up;
  epochs are odd while electing, even when stable.
- Paxos (src/mon/Paxos.cc): the leader runs collect (:146) to learn the
  peons' last_committed and any accepted-but-uncommitted value (promised
  under a higher proposal number), re-proposes it if newer, catches
  lagging peons up from its committed log, then serves begin (:606) /
  accept (:765) / commit (:840) rounds — ONE in-flight proposal at a
  time, exactly like the reference.
- Leases (:Paxos lease extend): the leader heartbeats the quorum; a peon
  whose lease goes stale calls a new election.

The Monitor drives these with callbacks: ``send(rank, msg)`` transmits to
a peer monitor, ``apply(version, value)`` applies a committed value to
the replicated state (the osdmap service), ``on_leader_change(leader)``
re-points forwarding.
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.utils.lockdep import DepLock


class Elector:
    def __init__(self, rank: int, n_mons: int, send, on_elected,
                 timeout: float = 0.3, state_version=None):
        self.rank = rank
        self.n = n_mons
        self.send = send                  # async (peer_rank, msg)
        self.on_elected = on_elected      # async (leader, quorum, epoch)
        self.timeout = timeout
        # the candidate-preference input (round 14): paxos
        # last_committed.  A peer holding NEWER committed state never
        # defers to a stale candidate — the reference's "deferred to
        # whoever has the freshest store" rule, which keeps a revived
        # blank monitor from winning (and forking epochs) before the
        # collect/catch-up path has healed it.
        self.state_version = state_version or (lambda: 0)
        self.epoch = 1
        self.electing = False
        self.stopped = False
        self.leader: Optional[int] = None
        self.quorum: List[int] = []
        self._acked: set = set()
        self._deferred_to: Optional[int] = None
        self._deferred_key: Optional[Tuple[int, int]] = None
        self._victory_task: Optional[asyncio.Task] = None

    def _cand_key(self, rank: int, last_committed: int) -> Tuple[int, int]:
        """Election preference: freshest committed state first, lowest
        rank as the tiebreak (smaller key wins)."""
        return (-last_committed, rank)

    @property
    def majority(self) -> int:
        return self.n // 2 + 1

    def stop(self) -> None:
        """A stopped monitor must never campaign again — a dead-but-
        running elector would starve the surviving quorum with endless
        lowest-rank proposals."""
        self.stopped = True
        self.electing = False
        if self._victory_task:
            self._victory_task.cancel()

    async def start_election(self) -> None:
        if self.electing or self.stopped:
            return
        self.electing = True
        self.leader = None
        self._deferred_to = None
        self._deferred_key = None
        if self.epoch % 2 == 0:
            self.epoch += 1
        else:
            self.epoch += 2
        self._acked = {self.rank}
        for r in range(self.n):
            if r != self.rank:
                try:
                    await self.send(r, M.MMonElection(
                        op="propose", epoch=self.epoch, rank=self.rank,
                        last_committed=self.state_version()))
                except (ConnectionError, OSError):
                    pass
        if self._victory_task:
            self._victory_task.cancel()
        self._victory_task = asyncio.get_event_loop().create_task(
            self._victory_check())

    async def _victory_check(self) -> None:
        await asyncio.sleep(self.timeout)
        if not self.electing:
            return
        if self._deferred_to is not None:
            # a lower rank is out there; wait for its victory, or retry
            await asyncio.sleep(self.timeout * 4)
            if self.electing:
                self._deferred_to = None
                self._deferred_key = None
                self.electing = False
                await self.start_election()
            return
        if len(self._acked) >= self.majority:
            self.epoch += 1  # stable epochs are even
            self.electing = False
            self.leader = self.rank
            self.quorum = sorted(self._acked)
            for r in range(self.n):
                if r != self.rank:
                    try:
                        await self.send(r, M.MMonElection(
                            op="victory", epoch=self.epoch, rank=self.rank,
                            quorum=self.quorum))
                    except (ConnectionError, OSError):
                        pass
            await self.on_elected(self.rank, self.quorum, self.epoch)
        else:
            # not enough acks (peers down / racing): retry
            self.electing = False
            await self.start_election()

    async def handle(self, msg: M.MMonElection) -> None:
        if self.stopped:
            return
        if msg.op == "propose":
            if msg.epoch > self.epoch:
                self.epoch = msg.epoch
                self._deferred_to = None
                self._deferred_key = None
            key = self._cand_key(msg.rank,
                                 getattr(msg, "last_committed", 0))
            if key < self._cand_key(self.rank, self.state_version()):
                # defer to the preferred candidate (reference
                # Elector::defer + the catch-up guard: freshest
                # committed state beats rank) — but ack at most ONE
                # candidate per epoch unless a strictly better one
                # appears, or two mutually-unreachable candidates could
                # both collect a majority
                if self._deferred_key is not None and \
                        key >= self._deferred_key:
                    return
                self._deferred_to = msg.rank
                self._deferred_key = key
                if not self.electing:
                    self.electing = True
                    self._acked = set()
                    if self._victory_task:
                        self._victory_task.cancel()
                    self._victory_task = asyncio.get_event_loop() \
                        .create_task(self._victory_check())
                try:
                    await self.send(msg.rank, M.MMonElection(
                        op="ack", epoch=msg.epoch, rank=self.rank))
                except (ConnectionError, OSError):
                    pass
            else:
                # a worse candidate (higher rank, or staler committed
                # state) is campaigning: counter with our own
                if not self.electing or self._deferred_to is None:
                    self.electing = False
                    await self.start_election()
        elif msg.op == "ack":
            if self.electing and msg.epoch == self.epoch:
                self._acked.add(msg.rank)
        elif msg.op == "victory":
            # accept a strictly newer epoch, or break same-epoch ties in
            # favour of the LOWER rank (dueling-candidates window)
            if msg.epoch > self.epoch or (
                    msg.epoch == self.epoch and
                    (self.leader is None or msg.rank < self.leader)):
                self.epoch = msg.epoch
                self.electing = False
                self.leader = msg.rank
                self.quorum = list(msg.quorum)
                if self._victory_task:
                    self._victory_task.cancel()
                await self.on_elected(msg.rank, self.quorum, msg.epoch)


class Paxos:
    """Single-decree-at-a-time multi-Paxos over the mon quorum."""

    def __init__(self, rank: int, n_mons: int, send, apply_fn,
                 timeout: float = 1.0):
        self.rank = rank
        self.n = n_mons
        self.send = send                  # async (peer_rank, msg)
        self.apply_fn = apply_fn          # async (version, value)
        self.timeout = timeout
        self.last_committed = 0
        self.accepted_pn = 0
        self.values: Dict[int, bytes] = {}   # committed log (trimmed)
        self.max_log = 500
        # peon-side promised-but-uncommitted value
        self.uncommitted: Optional[Tuple[int, int, bytes]] = None
        self.leading = False
        self.active = False               # collect finished, may propose
        self.quorum: List[int] = []
        self._propose_lock = DepLock("paxos.propose")
        self._round_waiter: Optional[asyncio.Future] = None
        self._round_acks: set = set()
        self._round_key: Tuple = ()
        self._pending_commits: Dict[int, bytes] = {}

    @property
    def majority(self) -> int:
        return self.n // 2 + 1

    # ------------------------------------------------------------- leader

    async def leader_init(self, quorum: List[int]) -> None:
        """Collect phase after winning an election (Paxos.cc:146)."""
        self.leading = True
        self.active = False
        self.quorum = list(quorum)
        pn = ((self.accepted_pn // 100) + 1) * 100 + self.rank
        self.accepted_pn = pn
        self._round_key = ("collect", pn)
        self._round_acks = {self.rank}
        self._replies: List[M.MMonPaxos] = []
        fut = self._round_waiter = asyncio.get_event_loop().create_future()
        for r in self.quorum:
            if r != self.rank:
                try:
                    await self.send(r, M.MMonPaxos(
                        op="collect", pn=pn, rank=self.rank,
                        last_committed=self.last_committed))
                except (ConnectionError, OSError):
                    pass
        try:
            await asyncio.wait_for(fut, timeout=self.timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._round_waiter = None
        # adopt the newest uncommitted value promised under this pn
        best: Optional[Tuple[int, int, bytes]] = None
        if self.uncommitted and self.uncommitted[1] == self.last_committed + 1:
            best = self.uncommitted
        for rep in self._replies:
            if rep.uncommitted_version == self.last_committed + 1 and \
                    rep.uncommitted_value:
                if best is None or rep.uncommitted_pn > best[0]:
                    best = (rep.uncommitted_pn, rep.uncommitted_version,
                            rep.uncommitted_value)
        self.active = True
        if best is not None:
            await self.propose(best[2])

    async def propose(self, value: bytes) -> bool:
        """begin/accept/commit one value (Paxos.cc:606,765,840)."""
        if not (self.leading and self.active):
            return False
        async with self._propose_lock:
            if not (self.leading and self.active):
                return False
            version = self.last_committed + 1
            pn = self.accepted_pn
            self.uncommitted = (pn, version, value)
            self._round_key = ("accept", pn, version)
            self._round_acks = {self.rank}
            fut = self._round_waiter = \
                asyncio.get_event_loop().create_future()
            for r in self.quorum:
                if r != self.rank:
                    try:
                        await self.send(r, M.MMonPaxos(
                            op="begin", pn=pn, rank=self.rank,
                            version=version, value=value,
                            last_committed=self.last_committed))
                    except (ConnectionError, OSError):
                        pass
            try:
                await asyncio.wait_for(fut, timeout=self.timeout)
            except asyncio.TimeoutError:
                return False
            finally:
                self._round_waiter = None
            # majority accepted: commit
            await self._commit(version, value)
            for r in self.quorum:
                if r != self.rank:
                    try:
                        await self.send(r, M.MMonPaxos(
                            op="commit", pn=pn, rank=self.rank,
                            version=version, value=value))
                    except (ConnectionError, OSError):
                        pass
            return True

    # --------------------------------------------------------------- peon

    def step_down(self) -> None:
        self.leading = False
        self.active = False

    async def _commit(self, version: int, value: bytes) -> None:
        if version != self.last_committed + 1:
            if version > self.last_committed + 1:
                self._pending_commits[version] = value
                # a rejoiner behind a TRIMMED log can never drain this
                # gap from commits alone (the map itself resyncs via
                # the mon's osdmap subscription; the log via the next
                # election's collect) — bound the buffer so a long-dead
                # revived peon does not grow it for the quorum's life
                while len(self._pending_commits) > self.max_log:
                    del self._pending_commits[min(self._pending_commits)]
            return
        self.values[version] = value
        self.last_committed = version
        if self.uncommitted and self.uncommitted[1] <= version:
            self.uncommitted = None
        for v in sorted(k for k in self.values if
                        k <= self.last_committed - self.max_log):
            del self.values[v]
        await self.apply_fn(version, value)
        # drain any out-of-order commits that are now contiguous
        while self.last_committed + 1 in self._pending_commits:
            v = self.last_committed + 1
            await self._commit(v, self._pending_commits.pop(v))

    async def handle(self, msg: M.MMonPaxos) -> None:
        if msg.op == "collect":
            if msg.pn > self.accepted_pn:
                self.accepted_pn = msg.pn
                self.step_down()
                reply = M.MMonPaxos(
                    op="last", pn=msg.pn, rank=self.rank,
                    last_committed=self.last_committed)
                if self.uncommitted:
                    reply.uncommitted_pn = self.uncommitted[0]
                    reply.uncommitted_version = self.uncommitted[1]
                    reply.uncommitted_value = self.uncommitted[2]
                # a peon AHEAD of the collecting leader hands it the
                # committed values it lacks (reference handle_collect
                # share_state): without this a lagging new leader would
                # re-propose old version numbers and fork the state
                if msg.last_committed < self.last_committed:
                    reply.catch_up = [
                        (v, self.values[v])
                        for v in range(msg.last_committed + 1,
                                       self.last_committed + 1)
                        if v in self.values]
                try:
                    await self.send(msg.rank, reply)
                except (ConnectionError, OSError):
                    pass
        elif msg.op == "last":
            if self._round_waiter is not None and \
                    self._round_key == ("collect", msg.pn):
                # learn anything the peon committed that we lack FIRST
                for v, blob in msg.catch_up:
                    await self._commit(v, blob)
                self._replies.append(msg)
                self._round_acks.add(msg.rank)
                # catch a lagging peon up from the committed log
                if msg.last_committed < self.last_committed:
                    catch = [(v, self.values[v])
                             for v in range(msg.last_committed + 1,
                                            self.last_committed + 1)
                             if v in self.values]
                    try:
                        await self.send(msg.rank, M.MMonPaxos(
                            op="commit", pn=msg.pn, rank=self.rank,
                            version=0, catch_up=catch))
                    except (ConnectionError, OSError):
                        pass
                if len(self._round_acks) >= self.majority and \
                        not self._round_waiter.done():
                    self._round_waiter.set_result(None)
        elif msg.op == "begin":
            # version guard: never accept a proposal for a version we
            # already committed (a stale leader that missed commits)
            if msg.pn >= self.accepted_pn and \
                    msg.version == self.last_committed + 1:
                # promise invariant: once we accept pn we must refuse any
                # later collect with a lower pn (reference handle_begin)
                self.accepted_pn = msg.pn
                self.uncommitted = (msg.pn, msg.version, msg.value)
                try:
                    await self.send(msg.rank, M.MMonPaxos(
                        op="accept", pn=msg.pn, rank=self.rank,
                        version=msg.version))
                except (ConnectionError, OSError):
                    pass
        elif msg.op == "accept":
            if self._round_waiter is not None and \
                    self._round_key == ("accept", msg.pn, msg.version):
                self._round_acks.add(msg.rank)
                if len(self._round_acks) >= self.majority and \
                        not self._round_waiter.done():
                    self._round_waiter.set_result(None)
        elif msg.op == "commit":
            for v, blob in msg.catch_up:
                await self._commit(v, blob)
            if msg.version:
                await self._commit(msg.version, msg.value)
